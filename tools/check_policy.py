#!/usr/bin/env python
"""CI gate for the programmable policy plane (`make check-policy`).

End-to-end promotion, all phases HARD-FAIL:

1. **Record** — a randomized bind/forget soak (fractional + whole-chip
   shapes, binpack incumbent) with the flight recorder on: the workload
   the replay gate will judge candidates against.
2. **Gate blocks worse** — an anti-binpack candidate (inverted formula)
   must be BLOCKED by the replay gate (worse on the rater-neutral
   metrics: placements completed / contiguity / whole-free-chip
   preservation), with the verdict journaled.
3. **Gate passes better** — a monotone transform of the incumbent's own
   formula (same placement ordering, different score scale) must pass
   and stage as a canary.
4. **Canary divergence journaled** — live binds split by deterministic
   pod hash; both arms must journal `policy` decide records, and the
   cross-scored divergence must be non-zero (the score scales differ).
5. **Promote** — the canary promotes; the engine rater IS the policy.
6. **Fault fallback** — a candidate that faults at runtime (division by
   zero) must still bind every pod (incumbent fallback) and journal
   `policy_fault` annotations.
7. **Injected SLO regression auto-rolls back** — synthetic candidate
   bind-latency regression fed to the SLO monitor trips the automatic
   rollback, journaled with the reason.
8. **Replay reconstruction** — journal replay is clean (zero
   violations), counts every policy record, and rebuilds WHICH policy
   (and which arm) decided every canary bind; what-if under a policy
   expressing the built-in binpack is BIT-IDENTICAL to the built-in.
9. **Overhead budget** — bind p99 with a policy-backed rater stays
   within POLICY_OVERHEAD_BUDGET_PCT (default 5) of the built-in via
   bench.policy_bench's interleaved storm-trimmed estimator, x3
   attempts like check-journal.

Usage:
    python tools/check_policy.py [--ops N] [--skip-overhead]

Environment:
    CHECK_POLICY_SEED           soak RNG seed (default 20260804)
    POLICY_OVERHEAD_BUDGET_PCT  bind p99 budget (default 5)

Wired into the Makefile as `make check-policy`, next to
`check-cluster-scale`.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from elastic_gpu_scheduler_tpu.cli import build_stack  # noqa: E402
from elastic_gpu_scheduler_tpu.core.rater import Binpack  # noqa: E402
from elastic_gpu_scheduler_tpu.journal import JOURNAL, read_journal  # noqa: E402
from elastic_gpu_scheduler_tpu.journal.replay import replay, what_if  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.client import FakeClientset  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.fake import FakeCluster  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.objects import (  # noqa: E402
    Container,
    ResourceRequirements,
    make_pod,
    make_tpu_node,
)
from elastic_gpu_scheduler_tpu.policy import (  # noqa: E402
    POLICIES,
    VERB_INPUTS,
    compile_expr,
)
from elastic_gpu_scheduler_tpu.policy.rater import PolicyRater  # noqa: E402
from elastic_gpu_scheduler_tpu.utils import consts  # noqa: E402

BINPACK_EXPR = "35*node_used + 30*chip_used + 25*preserve + 10*locality"
ANTI_EXPR = "100 - (35*node_used + 30*chip_used + 25*preserve + 10*locality)"
# monotone transform of the incumbent: same placement ordering (gate
# ties on every neutral metric) but a different score scale, so every
# canary decision has measurable divergence
SCALED_EXPR = "1 + 0.9*(35*node_used + 30*chip_used + 25*preserve + 10*locality)"
FAULTY_EXPR = "100 / (free_chips - free_chips)"  # div-by-zero every eval


def _pod(name, core=0, chips=0):
    res = {}
    if core:
        res[consts.RESOURCE_TPU_CORE] = core
    if chips:
        res[consts.RESOURCE_TPU_CORE] = chips * 100
    return make_pod(
        name,
        containers=[
            Container(
                name="main",
                resources=ResourceRequirements(limits=res),
            )
        ],
    )


class Driver:
    def __init__(self, seed: int, journal_dir: str):
        JOURNAL.configure(journal_dir, fsync="off",
                          max_segment_bytes=64 << 20)
        self.cluster = FakeCluster()
        for i in range(4):
            self.cluster.add_node(
                make_tpu_node(f"n{i}", chips=4, hbm_gib=64,
                              accelerator="v5e")
            )
        self.nodes = [f"n{i}" for i in range(4)]
        clientset = FakeClientset(self.cluster)
        (self.registry, self.predicate, _prio, self.bind, _ctl,
         self.status, self.gang) = build_stack(
            clientset, cluster=None, priority="binpack",
        )
        self.sched = self.registry[consts.RESOURCE_TPU_CORE]
        self.rng = random.Random(seed)
        self.serial = 0
        self.live: list = []

    def churn(self, ops: int, forget_p: float = 0.4) -> int:
        """Randomized bind/forget ops; returns binds committed."""
        binds = 0
        for _ in range(ops):
            if self.live and self.rng.random() < forget_p:
                pod = self.live.pop(self.rng.randrange(len(self.live)))
                self.sched.forget_pod(pod, source="soak_delete")
                continue
            self.serial += 1
            shape = self.rng.random()
            if shape < 0.3:
                pod = _pod(f"soak-{self.serial}", chips=2)  # whole 2-chip
            else:
                pod = _pod(f"soak-{self.serial}",
                           core=self.rng.choice([50, 100, 200]))
            self.cluster.create_pod(pod)
            ok, _failed = self.sched.assume(list(self.nodes), pod)
            if not ok:
                continue
            self.sched.bind(self.rng.choice(ok), pod)
            self.live.append(pod)
            binds += 1
        return binds

    def drain(self):
        for pod in self.live:
            self.sched.forget_pod(pod, source="soak_drain")
        self.live = []


def main() -> int:
    ops = 140
    skip_overhead = False
    for a in sys.argv[1:]:
        if a.startswith("--ops="):
            ops = int(a.split("=", 1)[1])
        elif a == "--skip-overhead":
            skip_overhead = True
        else:
            print(f"unknown argument {a!r}", file=sys.stderr)
            return 2

    seed = int(os.environ.get("CHECK_POLICY_SEED", "20260804"))
    tmp = tempfile.mkdtemp(prefix="tpu-policy-check-")
    journal_dir = os.path.join(tmp, "journal")
    failures: list[str] = []
    result: dict = {"metric": "check_policy", "seed": seed, "ops": ops}
    POLICIES.reset()
    try:
        drv = Driver(seed, journal_dir)
        sched = drv.sched

        # phase 1: record workload
        binds = drv.churn(ops)
        result["recorded_binds"] = binds
        if binds < 20:
            failures.append(f"soak recorded only {binds} binds")

        # phase 2: the replay gate must BLOCK a worse candidate
        blocked = POLICIES.load(
            "anti-binpack", "score", ANTI_EXPR, canary_pct=50.0,
        )
        result["gate_block"] = blocked.get("gate")
        if blocked.get("state") != "blocked":
            failures.append(
                f"replay gate passed the anti-binpack candidate: {blocked}"
            )

        # phase 3: a better/equal candidate passes and canaries
        passed = POLICIES.load(
            "binpack-scaled", "score", SCALED_EXPR, canary_pct=50.0,
            translation_invariant=True, whole_chip_compact_first=True,
        )
        result["gate_pass"] = passed.get("gate")
        if passed.get("state") != "canary":
            failures.append(
                f"replay gate blocked the equivalent candidate: {passed}"
            )

        # phase 4: canary — both arms journaled, divergence non-zero
        drv.churn(80, forget_p=0.5)
        dec = dict(POLICIES.decisions.get("score") or {})
        result["canary_decisions"] = dec
        if not dec.get("candidate"):
            failures.append("no canary bind decided by the candidate arm")
        if not dec.get("incumbent"):
            failures.append("no canary bind decided by the incumbent arm")
        if not dec.get("diverged"):
            failures.append(
                "zero canary divergence recorded — the scaled candidate "
                "must cross-score differently from the incumbent"
            )
        result["canary_divergence_pct"] = POLICIES.divergence_pct("score")

        # phase 5: promote — the engine rater IS the policy
        POLICIES.promote("score")
        if sched.rater.name != "binpack-scaled":
            failures.append(
                f"promotion did not swap the engine rater "
                f"(got {sched.rater.name!r})"
            )
        drv.churn(20, forget_p=0.5)
        POLICIES.rollback("score", reason="check-policy phase done")
        if sched.rater.name != "binpack":
            failures.append(
                f"rollback did not restore the incumbent "
                f"(got {sched.rater.name!r})"
            )

        # phase 6: runtime faults fall back to the incumbent, never a
        # failed bind
        POLICIES.load(
            "faulty", "score", FAULTY_EXPR, canary_pct=100.0,
            skip_gate=True,
        )
        before = len(drv.live)
        drv.churn(12, forget_p=0.0)
        pol = POLICIES.canary.get("score")
        faults = pol.rater.faults if pol and pol.rater else 0
        result["fault_evals"] = faults
        if len(drv.live) <= before:
            failures.append("faulty policy blocked binds (fallback broken)")
        if faults < 1:
            failures.append("faulty policy recorded zero faults")
        POLICIES.rollback("score", reason="fault phase done")

        # phase 7: injected SLO regression auto-rolls back
        POLICIES.load(
            "slo-victim", "score", SCALED_EXPR, canary_pct=50.0,
            skip_gate=True,
        )
        slo = POLICIES.slo
        for _ in range(40):
            slo.note_latency("candidate", 0.050)
            slo.note_latency("incumbent", 0.001)
        rb = POLICIES.check_slo()
        result["slo_rollback"] = rb
        if rb is None or rb.get("state") != "builtin":
            failures.append(
                "injected bind-p99 regression did not auto-roll back"
            )
        if POLICIES.canary.get("score") is not None:
            failures.append("canary still staged after SLO rollback")
        if sched.rater.name != "binpack":
            failures.append(
                "engine rater not restored after SLO rollback"
            )
        hist = [h for h in POLICIES.history
                if h["event"] == "rollback" and h.get("auto")]
        if not hist:
            failures.append("auto rollback missing from plane history")

        # phase 8: replay reconstruction + what-if parity
        drv.drain()
        JOURNAL.flush()
        JOURNAL.close()
        events = read_journal(journal_dir)
        result["records"] = len(events)
        res = replay(events)
        if res.violations:
            failures.append(f"replay violations: {res.violations[:5]}")
        result["policy_records"] = res.policy_records
        result["policy_faults"] = res.policy_faults
        result["policy_decisions"] = len(res.policy_decisions)
        if res.policy_records < 6:
            failures.append(
                f"too few policy records replayed ({res.policy_records})"
            )
        if res.policy_faults < 1:
            failures.append("no policy_fault annotation reached the journal")
        want_decides = dec.get("candidate", 0) + dec.get("incumbent", 0)
        if len(res.policy_decisions) < want_decides:
            failures.append(
                f"replay reconstructed {len(res.policy_decisions)} canary "
                f"decisions, journal should hold >= {want_decides}"
            )
        arms = {d["arm"] for d in res.policy_decisions.values()}
        if not {"candidate", "incumbent"} <= arms:
            failures.append(f"replay decisions missing an arm: {arms}")

        pr = PolicyRater(
            compile_expr(BINPACK_EXPR, VERB_INPUTS["score"]),
            fallback=Binpack(), name="parity",
            translation_invariant=True, whole_chip_compact_first=True,
        )
        base = what_if(events, Binpack())
        poli = what_if(events, pr)
        result["what_if_base"] = base["mean_score"]
        result["what_if_policy"] = poli["mean_score"]
        for k in ("binds", "placed", "mean_score", "contiguous_frac",
                  "final_frag_mean", "mean_free_chip_frac"):
            if base[k] != poli[k]:
                failures.append(
                    f"what-if parity broke on {k}: policy {poli[k]} vs "
                    f"built-in {base[k]} (must be bit-identical)"
                )
    finally:
        JOURNAL.close()
        POLICIES.reset()
        shutil.rmtree(tmp, ignore_errors=True)

    # phase 9: bind-p99 overhead budget (bench estimator, 3 attempts)
    if not skip_overhead:
        from bench import policy_bench

        try:
            budget = float(
                os.environ.get("POLICY_OVERHEAD_BUDGET_PCT", "5")
            )
        except ValueError:
            budget = 5.0
        attempts = []
        ok = False
        overhead = {}
        for _attempt in range(3):
            overhead = policy_bench()
            attempts.append(overhead["policy_overhead_pct"])
            ok = (
                overhead["policy_overhead_pct"] <= budget
                or overhead["policy_overhead_trimmed_pct"] <= budget
            )
            if ok:
                break
        result.update(overhead)
        result["overhead_budget_pct"] = budget
        result["overhead_attempts_pct"] = attempts
        if not ok:
            failures.append(
                f"policy-backed bind p99 over budget on every attempt "
                f"({attempts}% vs {budget}%; trimmed "
                f"{overhead.get('policy_overhead_trimmed_pct')}%)"
            )

    result["failures"] = failures
    print(json.dumps(result))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
