#!/usr/bin/env python
"""CI gate for the HA control plane (`make check-ha`).

Seeded chaos soak: a leader stack on a fleetgen cluster ships its
journal to a live follower while a deterministic fault plan
(faultinject/) fires at the new injection sites; then the leader is
killed mid-gang-commit and mid-write (torn tail + abort ≈ SIGKILL) and
a standby performs a WARM takeover.  HARD-FAILS when:

- the follower ends the soak lagging, failed, or with any replay
  violation (double-book / capacity conservation / gang all-or-nothing),
- the leader killed mid-gang-commit leaves ANY chip double-booked or a
  conservation violation on follower replay,
- the warm-takeover engine disagrees with a cold ledger rebuild
  (field-by-field diff — the no-double-book arbiter),
- the new leader's OWN journal (fresh dir, boot checkpoint) does not
  replay to exactly its live state (empty live diff after takeover),
- warm takeover is not at least CHECK_HA_MIN_SPEEDUP× faster than the
  cold rebuild it replaces, or
- leader-election chaos (injected renew faults) fails to fail-stop and
  re-acquire, or the router's probe-fault breaker never re-closes, or
- a federation shard leader killed mid-phase-1 of a cross-shard gang
  (prepare sealed, then death + a second-shard prepare fault) leaves
  any chip double-booked, any surviving shard's journal without its
  compensating rollback, or the cross-shard conservation audit dirty.

Usage:
    python tools/check_ha.py

Environment:
    CHECK_HA_SEED           soak RNG seed (default 20260804)
    CHECK_HA_NODES          fleetgen node count (default 240)
    CHECK_HA_OPS            churn ops (default 400)
    CHECK_HA_MIN_SPEEDUP    warm-vs-cold takeover floor (default 3.0;
                            bench.py's 10k-node `ha` section records the
                            ≥10× headline)

Wired into the Makefile as `make check-ha`, next to check-analysis.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from elastic_gpu_scheduler_tpu.cli import build_stack  # noqa: E402
from elastic_gpu_scheduler_tpu.faultinject import FAULTS  # noqa: E402
from elastic_gpu_scheduler_tpu.journal import JOURNAL, read_journal  # noqa: E402
from elastic_gpu_scheduler_tpu.journal.replay import (  # noqa: E402
    diff_live,
    replay,
)
from elastic_gpu_scheduler_tpu.journal.ship import JournalFollower  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.client import FakeClientset  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.extender import (  # noqa: E402
    ExtenderArgs,
    ExtenderBindingArgs,
)
from elastic_gpu_scheduler_tpu.k8s.fake import FakeCluster  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.objects import (  # noqa: E402
    Container,
    ResourceRequirements,
    make_pod,
)
from elastic_gpu_scheduler_tpu.scheduler.ha import warm_takeover  # noqa: E402
from elastic_gpu_scheduler_tpu.scheduler.leader import LeaderElector  # noqa: E402
from elastic_gpu_scheduler_tpu.server.routes import ExtenderServer  # noqa: E402
from elastic_gpu_scheduler_tpu.utils import consts  # noqa: E402
from tools.fleetgen import make_fleet  # noqa: E402

SEED = int(os.environ.get("CHECK_HA_SEED", "20260804"))
NODES = int(os.environ.get("CHECK_HA_NODES", "240"))
OPS = int(os.environ.get("CHECK_HA_OPS", "400"))
MIN_SPEEDUP = float(os.environ.get("CHECK_HA_MIN_SPEEDUP", "3.0"))


def _pod(name, core=0, gang=None, gang_size=0):
    ann = {}
    if gang:
        ann[consts.ANNOTATION_GANG_NAME] = gang
        ann[consts.ANNOTATION_GANG_SIZE] = str(gang_size)
    res = {consts.RESOURCE_TPU_CORE: core} if core else {}
    return make_pod(
        name,
        containers=[
            Container(name="main", resources=ResourceRequirements(limits=res))
        ],
        annotations=ann,
    )


def _elector_chaos(failures: list) -> None:
    """Injected lease-renew faults must fail-stop (fence+drain) and the
    elector must then RE-ACQUIRE — availability comes back by itself."""
    cs = FakeClientset(FakeCluster())
    drained = []
    a = LeaderElector(
        cs, identity="chaos", lease_duration=0.6, renew_period=0.15,
        on_stepping_down=lambda: drained.append(1),
    )
    a.start()
    deadline = time.monotonic() + 10
    while not a.is_leader() and time.monotonic() < deadline:
        time.sleep(0.02)
    if not a.is_leader():
        failures.append("elector chaos: never acquired")
        a.stop()
        return
    FAULTS.configure([
        {"site": "lease.renew", "kind": "error", "nth": 1, "count": 1},
    ], seed=SEED)
    deadline = time.monotonic() + 10
    while not drained and time.monotonic() < deadline:
        time.sleep(0.02)
    if not drained:
        failures.append("elector chaos: renew fault never drained/stepped")
    deadline = time.monotonic() + 10
    while not a.is_leader() and time.monotonic() < deadline:
        time.sleep(0.02)
    if not a.is_leader():
        failures.append("elector chaos: never re-acquired after fail-stop")
    a.stop()
    FAULTS.clear()


def _router_chaos(failures: list, scheduler_base_port: int) -> None:
    """Probe faults open the breaker with jittered cooldown; the
    breaker must re-close once probes succeed again."""
    from elastic_gpu_scheduler_tpu.fleet.router import Replica, ReplicaSet

    rs = ReplicaSet(interval_s=0.05, probe_timeout_s=1.0,
                    breaker_threshold=2, breaker_cooldown_s=0.1)
    r = rs.add(Replica("r0", "127.0.0.1", scheduler_base_port))
    FAULTS.configure([
        {"site": "router.probe", "kind": "partition", "p": 1.0, "count": 2},
    ], seed=SEED)
    rs.refresh_one(r)
    rs.refresh_one(r)
    if r.state != "down" or r.breaker_open_until <= 0:
        failures.append(
            f"router chaos: breaker never opened (state={r.state})"
        )
    rs.refresh_one(r)  # faults exhausted (count=2): healthy probe
    if r.state != "up" or r.consecutive_failures != 0:
        failures.append(
            f"router chaos: breaker never re-closed (state={r.state})"
        )
    FAULTS.clear()


def _federation_chaos(failures: list, result: dict) -> None:
    """Phase 5: shard-leader death mid-phase-1 of a cross-shard gang.
    The victim seals (journals + flushes) its prepare, dies, and the
    second participant's phase-1 faults — the front door must decide
    abort, compensate every SURVIVING shard (reverse-order
    gang_unallocate, journaled fed_gang abort), and the revived victim
    must presume abort from the decision log.  Zero double-booked
    chips: aggregate free core returns exactly to the pre-gang
    baseline, and the cross-shard journal audit is clean."""
    from elastic_gpu_scheduler_tpu.federation import (
        FederationFrontDoor,
        SchedulerShard,
    )
    from elastic_gpu_scheduler_tpu.federation.audit import audit_federation

    tmp = tempfile.mkdtemp(prefix="check_ha_fed_")
    try:
        fd = FederationFrontDoor()
        shards = {}
        for i, sid in enumerate(["us/v5e/4x4", "us/v5p/4x4x4",
                                 "eu/v6e/4x4"]):
            cluster = FakeCluster()
            names = make_fleet(cluster, nodes=24, seed=SEED + i)
            sh = SchedulerShard(
                sid, FakeClientset(cluster),
                os.path.join(tmp, sid), node_names=names,
            )
            sh.cluster = cluster
            sh.warm()
            shards[sid] = sh
            fd.add_shard(sh)
        fd.refresh_summaries()

        def free_core():
            return sum(
                sh.engine.status_summary()["capacity"]["core_avail"]
                for sh in shards.values()
            )

        sids = sorted(shards)
        base_free = free_core()
        victim = sids[0]  # first in shard order → prepares first
        # the kill lands AFTER the victim's prepare is sealed on disk
        # (journal flushed) — the in-doubt reservation revive must
        # resolve; the nth=2 fault then fails the SECOND prepare
        fd.on_prepared = (
            lambda txn, sid: shards[sid].kill() if sid == victim else None
        )
        FAULTS.configure(
            [{"site": "fed.prepare", "kind": "error", "nth": 2,
              "count": 1}],
            seed=SEED,
        )
        members = []
        for j, sid in enumerate(sids[:2]):
            sh = shards[sid]
            gp = _pod(f"fed-kill-{j}", core=100, gang="fedkill",
                      gang_size=2)
            sh.cluster.create_pod(gp)
            members.append((sid, sh.node_names[j], gp))
        res = fd.admit_gang("default/fedkill", members)
        FAULTS.clear()
        fd.on_prepared = None
        if res["ok"]:
            failures.append(
                "phase 5: gang admitted despite shard death mid-phase-1"
            )
            return
        txn = res["txn"]
        if fd.decisions.get(txn) != "abort":
            failures.append(
                f"phase 5: decision log says {fd.decisions.get(txn)!r} "
                "for a failed phase-1, expected 'abort'"
            )
        # surviving shards must already be compensated and conserved
        for sid in sids:
            if sid == victim:
                continue
            sh = shards[sid]
            if not sh.JOURNAL.flush():
                failures.append(f"phase 5: {sid} journal flush failed")
                continue
            r = replay(read_journal(sh.journal_dir))
            if r.violations:
                failures.append(
                    f"phase 5: survivor {sid} replay violations: "
                    f"{r.violations[:3]}"
                )
            d = diff_live(r, sh.engine.status())
            if d:
                failures.append(
                    f"phase 5: survivor {sid} live diff non-empty: "
                    f"{d[:3]}"
                )
        # revive the victim: presumed abort from the decision log
        rec = shards[victim].revive(fd.decisions)
        if rec["aborted"] != [txn]:
            failures.append(
                f"phase 5: revive resolved {rec}, expected abort of {txn}"
            )
        audit = audit_federation(tmp)
        if audit["violations"]:
            failures.append(
                f"phase 5: cross-shard audit violations: "
                f"{audit['violations'][:3]}"
            )
        after = free_core()
        result["federation_free_core_baseline"] = base_free
        result["federation_free_core_after"] = after
        if after != base_free:
            failures.append(
                f"phase 5: {base_free - after} core double-booked/lost "
                "after shard-kill rollback"
            )
    finally:
        FAULTS.clear()
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> int:
    failures: list[str] = []
    result: dict = {"seed": SEED, "nodes": NODES, "ops": OPS}
    rng = random.Random(SEED)
    tmp = tempfile.mkdtemp(prefix="check_ha_")
    dir_a = os.path.join(tmp, "leader-a")
    dir_b = os.path.join(tmp, "leader-b")
    try:
        # -- leader stack + follower -------------------------------------
        cluster = FakeCluster()
        names = make_fleet(cluster, nodes=NODES, seed=SEED)
        result["nodes"] = len(names)
        clientset = FakeClientset(cluster)
        JOURNAL.configure(dir_a, fsync="off", max_segment_bytes=256 << 10)
        registry, predicate, prioritize, bind, _ctl, status, gang = (
            build_stack(clientset, cluster=None, gang_timeout=10.0)
        )
        sched_a = registry[consts.RESOURCE_TPU_CORE]
        server = ExtenderServer(
            predicate, prioritize, bind, status, host="127.0.0.1", port=0
        )
        port = server.start()
        base = f"http://127.0.0.1:{port}"
        follower = JournalFollower(base, wait_s=2.0).start()

        # -- phase 1: seeded churn under transport chaos -----------------
        # recoverable faults only: stream/poll/ledger-read failures and
        # fsync errors never LOSE acknowledged records, so the follower
        # must ride them out and converge
        FAULTS.configure([
            {"site": "ship.stream", "kind": "error", "p": 0.10},
            {"site": "ship.follow", "kind": "error", "p": 0.05},
            {"site": "k8s.list_pods", "kind": "error", "p": 0.01},
            {"site": "journal.fsync", "kind": "error", "p": 0.05},
        ], seed=SEED)
        serial = 0
        live: list = []
        bind_fail = 0
        for _op in range(OPS):
            if live and rng.random() < 0.35:
                pod = live.pop(rng.randrange(len(live)))
                cluster.delete_pod(
                    pod.metadata.namespace, pod.metadata.name
                )
                sched_a.forget_pod(pod)
                continue
            serial += 1
            core = rng.choice((100, 100, 200, 400, 50))
            pod = _pod(f"soak-{serial}", core=core)
            cluster.create_pod(pod)
            cands = rng.sample(names, min(32, len(names)))
            r = predicate.handle(ExtenderArgs(pod=pod, node_names=cands))
            if not r.node_names:
                cluster.delete_pod("default", pod.metadata.name)
                continue
            res = bind.handle(ExtenderBindingArgs(
                pod_name=pod.metadata.name, pod_namespace="default",
                pod_uid=pod.metadata.uid, node=r.node_names[0],
            ))
            if res.error:
                bind_fail += 1
                cluster.delete_pod("default", pod.metadata.name)
            else:
                live.append(pod)
        # one gang that SUCCEEDS under chaos
        gpods = [
            _pod(f"gang-ok-{i}", core=400, gang="chaos-ok", gang_size=2)
            for i in range(2)
        ]
        gnodes = [n for n in names if "v5p" in n][:8] or names[:8]
        for p in gpods:
            cluster.create_pod(p)
            predicate.handle(ExtenderArgs(pod=p, node_names=gnodes))
        gang_ok_errors = []

        def _member(i):
            res = bind.handle(ExtenderBindingArgs(
                pod_name=gpods[i].metadata.name, pod_namespace="default",
                pod_uid=gpods[i].metadata.uid, node=gnodes[i % len(gnodes)],
            ))
            gang_ok_errors.append(res.error or "")

        ts = [threading.Thread(target=_member, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        FAULTS.clear()
        result["soak_bind_failures"] = bind_fail
        result["soak_live_pods"] = len(live)

        if not JOURNAL.flush():
            failures.append("phase 1: journal flush failed")
        deadline = time.monotonic() + 20
        while follower.lag_seqs() > 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        result["follow_lag_after_soak"] = follower.lag_seqs()
        result["follower_transport_errors"] = follower.transport_errors
        if follower.state == "failed":
            failures.append(f"phase 1: follower hard-failed: {follower.error}")
        if follower.lag_seqs() > 0:
            failures.append(
                f"phase 1: follower still lags {follower.lag_seqs()} seqs"
            )
        sv = follower.engine.result.violations
        if sv:
            failures.append(f"phase 1: follower replay violations: {sv[:3]}")
        d = diff_live(follower.engine.result, status())
        if d:
            failures.append(f"phase 1: follower live diff non-empty: {d[:3]}")

        # -- phase 2: elector + router chaos (server still alive) --------
        _elector_chaos(failures)
        _router_chaos(failures, port)

        # -- phase 3: kill the leader mid-gang-commit + mid-write --------
        FAULTS.configure([
            # first annotate call of the doomed gang dies (post-seal)
            {"site": "gang.phase2", "kind": "error", "nth": 1, "count": 1},
            # then the next journal batch tears mid-record (kill -9 tail)
            {"site": "journal.write", "kind": "torn-write", "nth": 40,
             "count": 1},
        ], seed=SEED)
        dpods = [
            _pod(f"gang-doomed-{i}", core=400, gang="doomed", gang_size=2)
            for i in range(2)
        ]
        for p in dpods:
            cluster.create_pod(p)
            predicate.handle(ExtenderArgs(pod=p, node_names=gnodes))
        doomed_errors = []

        def _dmember(i):
            res = bind.handle(ExtenderBindingArgs(
                pod_name=dpods[i].metadata.name, pod_namespace="default",
                pod_uid=dpods[i].metadata.uid,
                node=gnodes[(i + 2) % len(gnodes)],
            ))
            doomed_errors.append(res.error or "")

        ts = [threading.Thread(target=_dmember, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        if not any(doomed_errors):
            failures.append(
                "phase 3: injected mid-commit fault did not fail the gang"
            )
        JOURNAL.flush()
        deadline = time.monotonic() + 20
        while follower.lag_seqs() > 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        # the kill: torn tail is on disk (or pending); writer dies with
        # its buffer (abort ≈ SIGKILL), server goes away
        JOURNAL.abort()
        server.stop()
        follower.stop()
        FAULTS.clear()
        res_f = follower.engine.result
        if res_f.violations:
            failures.append(
                f"phase 3: follower replay violations: {res_f.violations[:3]}"
            )
        cons = follower.engine.conservation_violations()
        if cons:
            failures.append(f"phase 3: conservation violations: {cons[:3]}")
        if any(lp.gang == "default/doomed" for lp in res_f.pods.values()):
            failures.append(
                "phase 3: doomed gang member survived in follower state "
                "(double-book risk)"
            )

        # -- phase 4: warm takeover vs cold rebuild ----------------------
        # cold reference FIRST, while the journal is down (its ledger
        # rebuild must not journal into the new leader's fresh dir)
        t0 = time.perf_counter()
        registry_c, _pc, _prc, _bc, _cc, status_c, _gc = build_stack(
            clientset, cluster=None, gang_timeout=10.0,
        )  # the cold path: full annotation-ledger rebuild
        cold_ms = round((time.perf_counter() - t0) * 1000.0, 2)

        # timing probes (journal still down, throwaway engines): the
        # REAL takeover below is a once-only measurement, so a stray
        # GC/alloc stall in it would flake the speedup floor — min over
        # probe reps + the real run is the honest steady-state number
        import gc

        warm_probe_ms = []
        events_a = read_journal(dir_a)
        for _rep in range(2):
            probe_res = replay(events_a)
            reg_p, _pp, _prp, _bp, _cp, _sp, _gp = build_stack(
                clientset, cluster=None, gang_timeout=10.0,
                rebuild_on_start=False,
            )
            gc.collect()
            t0 = time.perf_counter()
            warm_takeover(reg_p[consts.RESOURCE_TPU_CORE], probe_res)
            warm_probe_ms.append((time.perf_counter() - t0) * 1000.0)
        result["ha_takeover_warm_probe_ms"] = [
            round(v, 2) for v in warm_probe_ms
        ]

        JOURNAL.configure(dir_b, fsync="off")
        registry_b, pred_b, _prio_b, bind_b, _c, status_b, _g = build_stack(
            clientset, cluster=None, gang_timeout=10.0,
            rebuild_on_start=False,
        )
        sched_b = registry_b[consts.RESOURCE_TPU_CORE]
        summary = warm_takeover(sched_b, follower)
        result["takeover"] = summary
        warm_ms = round(min([summary["wall_ms"]] + warm_probe_ms), 2)
        result["ha_takeover_warm_ms"] = warm_ms
        result["ha_takeover_cold_ms"] = cold_ms
        speedup = cold_ms / max(warm_ms, 1e-3)
        result["ha_takeover_speedup"] = round(speedup, 1)
        if speedup < MIN_SPEEDUP:
            failures.append(
                f"warm takeover only {speedup:.1f}x faster than cold "
                f"({warm_ms}ms vs {cold_ms}ms; floor {MIN_SPEEDUP}x)"
            )

        # the arbiter: warm-takeover engine ≡ cold ledger rebuild
        sched_c = registry_c[consts.RESOURCE_TPU_CORE]
        if sorted(sched_b.pod_maps) != sorted(sched_c.pod_maps):
            only_b = sorted(set(sched_b.pod_maps) - set(sched_c.pod_maps))
            only_c = sorted(set(sched_c.pod_maps) - set(sched_b.pod_maps))
            failures.append(
                f"takeover/cold ledger disagree: warm-only {only_b[:3]}, "
                f"cold-only {only_c[:3]}"
            )
        used_b = sum(
            na.chips.total_core() - na.chips.avail_core()
            for na in sched_b.allocators.values()
        )
        used_c = sum(
            na.chips.total_core() - na.chips.avail_core()
            for na in sched_c.allocators.values()
        )
        result["takeover_used_core"] = used_b
        if used_b != used_c:
            failures.append(
                f"takeover core charges {used_b} != cold rebuild {used_c} "
                "(double-book or lost free)"
            )

        # new leader keeps serving on adopted capacity
        post = _pod("post-takeover", core=100)
        cluster.create_pod(post)
        r = pred_b.handle(ExtenderArgs(
            pod=post, node_names=rng.sample(names, min(32, len(names)))
        ))
        if not r.node_names:
            failures.append("post-takeover filter found no feasible node")
        else:
            res = bind_b.handle(ExtenderBindingArgs(
                pod_name="post-takeover", pod_namespace="default",
                pod_uid=post.metadata.uid, node=r.node_names[0],
            ))
            if res.error:
                failures.append(f"post-takeover bind failed: {res.error}")

        # empty live diff after takeover: the new leader's OWN journal
        # (boot checkpoint + takeover diff + post bind) replays to
        # exactly its live state
        if not JOURNAL.flush():
            failures.append("phase 3: journal B flush failed")
        res_b = replay(read_journal(dir_b))
        if res_b.violations:
            failures.append(
                f"journal B replay violations: {res_b.violations[:3]}"
            )
        d = diff_live(res_b, status_b())
        if d:
            failures.append(
                f"post-takeover live diff non-empty: {d[:3]}"
            )
        JOURNAL.close()

        # -- phase 5: federation shard-leader death mid-phase-1 ----------
        _federation_chaos(failures, result)
    finally:
        FAULTS.clear()
        JOURNAL.close()
        shutil.rmtree(tmp, ignore_errors=True)

    result["failures"] = failures
    print(json.dumps(result))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
