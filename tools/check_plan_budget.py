#!/usr/bin/env python
"""CI tripwire for the gang-plan hot path.

Runs the same 1024-member / v5p-2048 plan microbench as bench.py
(bench.plan_microbench — one source of truth) and exits non-zero when the
min-of-trials wall exceeds the budget.  The r02→r03 27% plan regression and
the r05 false alarm both happened because nothing FAILED when the number
moved; the bench only warns.  This fails.

Usage:
    python tools/check_plan_budget.py [--trials N]

Environment:
    BENCH_PLAN_BUDGET_MS   budget in ms (default 135, same as bench.py)

Wired into the Makefile as `make check-plan-budget`.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import plan_microbench  # noqa: E402


def main() -> int:
    trials = 5
    args = sys.argv[1:]
    i = 0
    while i < len(args):
        if args[i].startswith("--trials="):
            trials = int(args[i].split("=", 1)[1])
        elif args[i] == "--trials" and i + 1 < len(args):
            i += 1
            trials = int(args[i])
        else:
            print(f"unknown argument {args[i]!r}", file=sys.stderr)
            return 2
        i += 1
    try:
        budget_ms = float(os.environ.get("BENCH_PLAN_BUDGET_MS", "135"))
    except ValueError:
        print("bad BENCH_PLAN_BUDGET_MS; using 135", file=sys.stderr)
        budget_ms = 135.0
    trials_ms = plan_microbench(trials=trials)
    best = min(trials_ms)
    result = {
        "metric": "v5p2048_gang1024_plan_ms",
        "value": round(best, 3),
        "median_ms": round(sorted(trials_ms)[len(trials_ms) // 2], 3),
        "trials": [round(t, 3) for t in trials_ms],
        "budget_ms": budget_ms,
        "over_budget": best > budget_ms,
    }
    print(json.dumps(result))
    if best > budget_ms:
        print(
            f"FAIL: 1024-member plan min-of-{trials} {best:.1f}ms exceeds "
            f"{budget_ms}ms budget (BENCH_PLAN_BUDGET_MS)",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
