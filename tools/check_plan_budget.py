#!/usr/bin/env python
"""CI tripwire for the gang-plan hot path.

Runs the same 1024-member / v5p-2048 plan microbench as bench.py
(bench.plan_microbench — one source of truth) and exits non-zero when the
min-of-trials wall exceeds the budget.  The r02→r03 27% plan regression and
the r05 false alarm both happened because nothing FAILED when the number
moved; the bench only warns.  This fails.

The budget self-calibrates per box: BENCH_r05 tripped the 135ms budget at
170ms on a cgroup-throttled CI box while the SAME tree planned in 58-62ms
on the dev box.  A fixed CPU reference loop (bench.plan_reference_trial_ms)
measures how slow THIS box is relative to the dev-class baseline
(PLAN_REF_BASELINE_MS) and the budget scales by that ratio, never below the
base.  Reference and plan trials are interleaved (check_journal's pooling
trick) so a throttling storm spanning adjacent trials slows both
measurements and cancels out of the ratio.

Usage:
    python tools/check_plan_budget.py [--trials N] [--no-calibrate]

Environment:
    BENCH_PLAN_BUDGET_MS   base budget in ms (default 135, same as bench.py)
    PLAN_REF_BASELINE_MS   reference-loop min on a healthy dev box (20)

Wired into the Makefile as `make check-plan-budget`.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import (  # noqa: E402
    calibrated_plan_budget,
    plan_microbench,
    plan_reference_trial_ms,
)


def main() -> int:
    trials = 5
    calibrate = True
    args = sys.argv[1:]
    i = 0
    while i < len(args):
        if args[i].startswith("--trials="):
            trials = int(args[i].split("=", 1)[1])
        elif args[i] == "--trials" and i + 1 < len(args):
            i += 1
            trials = int(args[i])
        elif args[i] == "--no-calibrate":
            calibrate = False
        else:
            print(f"unknown argument {args[i]!r}", file=sys.stderr)
            return 2
        i += 1
    try:
        base_budget_ms = float(os.environ.get("BENCH_PLAN_BUDGET_MS", "135"))
    except ValueError:
        print("bad BENCH_PLAN_BUDGET_MS; using 135", file=sys.stderr)
        base_budget_ms = 135.0
    # interleaved: ref trial, plan trial, ref trial, ... — a throttling
    # storm hits both series, so min-of-trials on each side drops it and
    # the calibration ratio stays honest
    trials_ms: list = []
    ref_trials_ms: list = []
    for _ in range(trials):
        ref_trials_ms.append(plan_reference_trial_ms())
        trials_ms.extend(plan_microbench(trials=1))
    if calibrate:
        budget_ms, ref_min_ms, scale = calibrated_plan_budget(
            base_budget_ms, ref_trials_ms
        )
    else:
        budget_ms, ref_min_ms, scale = base_budget_ms, min(ref_trials_ms), 1.0
    best = min(trials_ms)
    result = {
        "metric": "v5p2048_gang1024_plan_ms",
        "value": round(best, 3),
        "median_ms": round(sorted(trials_ms)[len(trials_ms) // 2], 3),
        "trials": [round(t, 3) for t in trials_ms],
        "budget_ms": round(budget_ms, 3),
        "base_budget_ms": base_budget_ms,
        "ref_ms": round(ref_min_ms, 3),
        "box_scale": round(scale, 3),
        "over_budget": best > budget_ms,
    }
    print(json.dumps(result))
    if best > budget_ms:
        print(
            f"FAIL: 1024-member plan min-of-{trials} {best:.1f}ms exceeds "
            f"{budget_ms:.1f}ms budget (base {base_budget_ms:.0f}ms × box "
            f"scale {scale:.2f}; set BENCH_PLAN_BUDGET_MS / "
            "PLAN_REF_BASELINE_MS to retune)",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
