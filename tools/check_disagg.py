#!/usr/bin/env python
"""CI gate for the disaggregated serving data plane (`make check-disagg`).

A multi-replica CPU soak over REAL engines (tiny model, real inference
HTTP servers, the real fleet router), all HARD-FAIL:

1. **Migration under churn, zero parity breaks** — a seeded burst of
   concurrent greedy streams through the router while live sessions are
   repeatedly migrated between replicas (`/v1/migrate/out` → bundle →
   `/v1/migrate/in` → relayed continuation): EVERY stream must complete
   cleanly ([DONE]) and token-identical to an undisturbed reference
   run, and at least CHECK_DISAGG_MIN_MIGRATIONS sessions must actually
   have hopped (a soak where nothing migrated gates nothing).
2. **Cold-replica adoption beats re-prefill** — a repeated long prefix
   served to a cold engine via imported KV pages (the wire bundle) must
   reach its first tokens at least DISAGG_ADOPT_FLOOR× faster than
   re-prefilling from scratch, import cost included, with identical
   tokens (best of 3 independent trials; bench.py's disagg section
   records the headline magnitude, this guards the direction).
3. **Prefix-index hygiene** — routed prefixes land in the fleet index;
   draining a holder (scale-down pin) prunes its entries, so stale
   digests cannot steer prompts at a leaving backend.
4. **Clean journal replay** — every commanded migration is journaled as
   a `kv_migrate` annotation; replay reports ZERO violations and zero
   warnings, and reconstructs exactly the commanded count.

Usage:
    python tools/check_disagg.py

Environment:
    CHECK_DISAGG_SEED             soak RNG seed (default 20260804)
    CHECK_DISAGG_MIN_MIGRATIONS   executed-hop floor (default 3)
    DISAGG_ADOPT_FLOOR            adoption speedup floor (default 1.2)

Wired into the Makefile as `make check-disagg`, next to `check-ha`.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from bench import _make_cpu_replica  # noqa: E402
from elastic_gpu_scheduler_tpu.fleet import (  # noqa: E402
    Autoscaler,
    FleetRouter,
    ReplicaSet,
)
from elastic_gpu_scheduler_tpu.journal import JOURNAL, read_journal  # noqa: E402
from elastic_gpu_scheduler_tpu.journal.replay import replay  # noqa: E402
from elastic_gpu_scheduler_tpu.utils import kvwire  # noqa: E402


class _NoRelay:
    up = None
    detail = ""


def stream_request(port, prompt, max_tokens, results, idx):
    """One streaming completion through the router; records
    (tokens list, done_clean, error)."""
    import socket as _socket

    raw = json.dumps(
        {"prompt": prompt, "max_tokens": max_tokens, "stream": True}
    ).encode()
    toks: list[int] = []
    try:
        with _socket.create_connection(
            ("127.0.0.1", port), timeout=300
        ) as s:
            s.sendall((
                f"POST /v1/completions HTTP/1.1\r\nHost: x\r\n"
                f"Content-Length: {len(raw)}\r\n"
                "Connection: close\r\n\r\n"
            ).encode() + raw)
            buf = b""
            while True:
                b = s.recv(65536)
                if not b:
                    break
                buf += b
        for line in buf.split(b"\n"):
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            payload = line[6:]
            if payload == b"[DONE]":
                continue
            try:
                ev = json.loads(payload)
            except ValueError:
                continue
            if "token" in ev:
                toks.append(ev["token"])
        results[idx] = (toks, b"data: [DONE]" in buf, "")
    except OSError as e:
        results[idx] = (toks, False, str(e))


def main() -> int:
    seed = int(os.environ.get("CHECK_DISAGG_SEED", "20260804"))
    min_migrations = int(
        os.environ.get("CHECK_DISAGG_MIN_MIGRATIONS", "3")
    )
    try:
        adopt_floor = float(os.environ.get("DISAGG_ADOPT_FLOOR", "1.2"))
    except ValueError:
        adopt_floor = 1.2
    rng = random.Random(seed)
    tmp = tempfile.mkdtemp(prefix="tpu-disagg-check-")
    journal_dir = os.path.join(tmp, "journal")
    failures: list[str] = []
    result: dict = {"metric": "check_disagg", "seed": seed}

    import jax

    from elastic_gpu_scheduler_tpu.models.serving import (
        InferenceEngine,
        Request,
    )
    from elastic_gpu_scheduler_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        dtype="float32",
    )
    params = init_params(jax.random.key(0), cfg)
    JOURNAL.configure(journal_dir, fsync="off")

    reps = [
        _make_cpu_replica(
            f"disagg-rep-{i}", params, cfg,
            max_batch=4, max_len=256, page_size=16, fused_steps=4,
            prefix_cache=True,
        )
        for i in range(3)
    ]
    rs = ReplicaSet(interval_s=0.2, relay_monitor=_NoRelay())
    for r in reps:
        rs.add(r["replica"])
    rs.refresh()
    router = FleetRouter(rs, host="127.0.0.1", port=0, page_size=16)
    rport = router.start()
    # the journaling shape the production rebalance path uses
    auto = Autoscaler(
        rs, executor=None, migrator=router.migrate_session,
        shed_queue_margin=1.0,
    )

    commanded = 0
    try:
        # ---- 1. migration-under-churn parity soak ----------------------
        prompts = []
        for i in range(14):
            n = rng.randrange(4, 40)
            prompts.append(
                [rng.randrange(0, 64) for _ in range(n)]
            )
        max_toks = [rng.randrange(32, 64) for _ in prompts]
        # references: undisturbed greedy runs on a private engine
        ref_eng = InferenceEngine(
            params, cfg, max_batch=4, max_len=256, page_size=16,
            fused_steps=4, prefix_cache=True,
        )
        refs = []
        for p, mt in zip(prompts, max_toks):
            req = ref_eng.submit(Request(prompt=list(p), max_new_tokens=mt))
            ref_eng.run_until_idle(max_steps=200_000)
            assert not req.error, req.error
            refs.append(list(req.output))

        results: dict = {}
        threads = [
            threading.Thread(
                target=stream_request,
                args=(rport, p, mt, results, i),
                daemon=True,
            )
            for i, (p, mt) in enumerate(zip(prompts, max_toks))
        ]
        for t in threads:
            t.start()
            time.sleep(0.02)
        names = [r["name"] for r in reps]
        migrate_ok = 0
        deadline = time.monotonic() + 120
        while (
            any(t.is_alive() for t in threads)
            and time.monotonic() < deadline
        ):
            src, dst = rng.sample(names, 2)
            res = router.migrate_session(src, dst)
            commanded += 1
            auto._journal_migrate(src, dst, "churn", res)
            if res.get("ok"):
                migrate_ok += 1
            time.sleep(0.05)
        for t in threads:
            t.join(timeout=60)
        result["streams"] = len(threads)
        result["migrations_commanded"] = commanded
        result["migrations_ok"] = migrate_ok
        parity_breaks = dropped = 0
        for i, ref in enumerate(refs):
            toks, clean, err = results.get(i, ([], False, "no result"))
            if not clean or err:
                dropped += 1
            elif toks != ref:
                parity_breaks += 1
        result["parity_breaks"] = parity_breaks
        result["dropped_streams"] = dropped
        if parity_breaks:
            failures.append(
                f"{parity_breaks} stream(s) diverged from the "
                "undisturbed reference under migration churn"
            )
        if dropped:
            failures.append(
                f"{dropped} stream(s) dropped (no clean [DONE])"
            )
        if migrate_ok < min_migrations:
            failures.append(
                f"only {migrate_ok} migrations executed "
                f"(< {min_migrations}); the soak gated nothing"
            )
        moved_in = sum(r["engine"].sessions_migrated_in for r in reps)
        result["sessions_migrated_in"] = moved_in
        if moved_in != migrate_ok:
            failures.append(
                f"engines report {moved_in} sessions migrated in, "
                f"router reports {migrate_ok} ok handoffs"
            )

        # ---- 2. cold-replica adoption beats re-prefill ------------------
        # a HEAVIER model than the soak's: adoption pays when prefill
        # COMPUTE dominates page-shipping BYTES, which needs a real
        # d_model even on CPU (compute scales d², bytes d) — the same
        # configuration bench.py's disagg section records
        acfg = TransformerConfig(
            vocab_size=256, d_model=256, n_layers=4, n_heads=8,
            d_ff=512, dtype="float32",
        )
        aparams = init_params(jax.random.key(1), acfg)
        long_prompt = [rng.randrange(0, 256) for _ in range(449)]
        warm_other = [rng.randrange(0, 256) for _ in range(449)]

        def mk():
            return InferenceEngine(
                aparams, acfg, max_batch=2, max_len=512, page_size=16,
                fused_steps=8, prefix_cache=True,
            )

        donor = mk()
        req = donor.submit(
            Request(prompt=list(long_prompt), max_new_tokens=2)
        )
        donor.run_until_idle(max_steps=200_000)
        data = donor.export_prefix_pages(long_prompt, "")
        hdr, pages = kvwire.decode_bundle(data)
        result["adopt_pages"] = len(pages)

        def run_once(eng, p):
            r = eng.submit(Request(prompt=list(p), max_new_tokens=2))
            t0 = time.perf_counter()
            eng.run_until_idle(max_steps=200_000)
            assert not r.error, r.error
            return time.perf_counter() - t0, list(r.output)

        best = 0.0
        ref_toks = None
        for _ in range(3):
            cold = mk()
            run_once(cold, warm_other)  # compile warm
            w_re, t_re = run_once(cold, long_prompt)
            adopted = mk()
            run_once(adopted, warm_other)
            t0 = time.perf_counter()
            adopted.import_pages(hdr, pages)
            imp = time.perf_counter() - t0
            w_ad, t_ad = run_once(adopted, long_prompt)
            if t_ad != t_re:
                failures.append("adopted tokens diverged from re-prefill")
                break
            ref_toks = t_re
            best = max(best, w_re / (w_ad + imp))
        del ref_toks
        result["adopt_speedup_best"] = round(best, 2)
        if best < adopt_floor:
            failures.append(
                f"cold-replica adoption speedup {best:.2f}x below the "
                f"{adopt_floor}x floor — shipping pages lost to "
                "re-prefilling"
            )

        # ---- 3. prefix-index hygiene ------------------------------------
        idx_before = len(router.prefix_index)
        holder = max(
            reps, key=lambda r: r["engine"].prefix_lookups
        )["name"]
        rs.drain(holder, reason="scale-down")
        pruned = router.pruned_digests
        rs.undrain(holder)
        result["index_entries"] = idx_before
        result["pruned_digests"] = pruned
        if idx_before == 0:
            failures.append(
                "routed prefixes never landed in the fleet index"
            )
        if pruned == 0:
            failures.append(
                "draining a holder pruned zero index entries — stale "
                "digests would outlive the backend"
            )
    finally:
        router.stop()
        for r in reps:
            r["server"].shutdown()
            r["loop"].stop()
        JOURNAL.flush()
        JOURNAL.close()

    # ---- 4. journal replay ----------------------------------------------
    events = read_journal(journal_dir)
    res = replay(events)
    result["journal_kv_migrations"] = res.kv_migrations
    if res.violations:
        failures.append(f"replay violations: {res.violations[:5]}")
    if res.warnings:
        failures.append(f"replay warnings: {res.warnings[:5]}")
    if res.kv_migrations != commanded:
        failures.append(
            f"replay reconstructed {res.kv_migrations} kv_migrate "
            f"records, {commanded} were commanded"
        )

    shutil.rmtree(tmp, ignore_errors=True)
    result["failures"] = failures
    print(json.dumps(result))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
