#!/usr/bin/env python
"""CI gate for the workload-profiling observatory (`make check-profile`).

Four phases, all HARD-FAIL:

1. **Convergence** — a randomized bind soak over a mixed v5e/v5p fleet
   with class-annotated pods, plus synthetic step samples injected at
   known per-(class, generation) rates: the EWMA profiles must converge
   to the injected throughput within tolerance.
2. **Interference** — a fractional co-location (two classes sharing a
   chip, the co-located rate injected at half the solo rate): the
   (class, neighbor) interference ratio must detect the slowdown.
3. **Journal round trip** — the soak runs with the flight recorder on
   and periodic `profile` records: replay must accept them as
   annotations (zero violations, zero warnings), and `what_if` under the
   profile-aware rater must consume the recorded profiles and produce a
   different placement score than its geometry base (the offline
   promotion-harness demonstration).
4. **Overhead budgets** — (a) bind p99 with profiling on stays within
   PROFILE_OVERHEAD_BUDGET_PCT (default 5%) of profiling-off, via
   bench.profile_bench's interleaved-chunk + storm-trimmed estimator,
   retried 3x like check-journal; (b) decode throughput through a real
   (CPU) engine with profiling on stays within
   PROFILE_SERVE_BUDGET_PCT (default 10%) of profiling-off, min-of-
   rounds each side, AND the engine's device-upload counter matches
   exactly (profiling must add ZERO host→device uploads).

Usage:
    python tools/check_profile.py [--ops N] [--skip-serve] [--skip-overhead]

Environment:
    CHECK_PROFILE_SEED            soak RNG seed (default 20260803)
    PROFILE_OVERHEAD_BUDGET_PCT   bind p99 budget (default 5)
    PROFILE_SERVE_BUDGET_PCT      decode-throughput budget (default 10)

Wired into the Makefile as `make check-profile`, next to `check-defrag`.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from elastic_gpu_scheduler_tpu.cli import build_stack  # noqa: E402
from elastic_gpu_scheduler_tpu.journal import JOURNAL, read_journal  # noqa: E402
from elastic_gpu_scheduler_tpu.journal.replay import replay, what_if  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.client import FakeClientset  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.extender import (  # noqa: E402
    ExtenderArgs,
    ExtenderBindingArgs,
)
from elastic_gpu_scheduler_tpu.k8s.fake import FakeCluster  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.objects import (  # noqa: E402
    Container,
    ResourceRequirements,
    make_pod,
    make_tpu_node,
)
from elastic_gpu_scheduler_tpu.profile import PROFILER  # noqa: E402
from elastic_gpu_scheduler_tpu.profile.rater import ProfileAwareRater  # noqa: E402
from elastic_gpu_scheduler_tpu.utils import consts  # noqa: E402

# injected synthetic rates (tokens/s/chip) per (class, generation):
# "serve" measured 3x faster on v5p; "train" flat
INJECTED = {
    ("serve", "v5e"): 1000.0,
    ("serve", "v5p"): 3000.0,
    ("train", "v5e"): 400.0,
    ("train", "v5p"): 400.0,
}
COLOCATED_FACTOR = 0.5  # co-located "serve" runs at half its solo rate


def _pod(name, core, wclass):
    return make_pod(
        name,
        containers=[
            Container(
                name="main",
                resources=ResourceRequirements(
                    limits={consts.RESOURCE_TPU_CORE: core}
                ),
            )
        ],
        annotations={consts.ANNOTATION_WORKLOAD_CLASS: wclass},
    )


def _inject_samples(pod_key, wclass, gen, rng, n=40, colocated=False):
    """Synthetic engine-step samples at the injected rate (exact rate,
    jittered wall so the reservoir sees variety)."""
    rate = INJECTED[(wclass, gen)]
    if colocated:
        rate *= COLOCATED_FACTOR
    for _ in range(n):
        wall = 0.008 + rng.random() * 0.004
        PROFILER.record_step(
            tokens=max(1, round(rate * wall)),
            wall_s=max(1e-4, round(rate * wall)) / rate,  # exact rate
            slots_active=rng.randint(1, 4), slots_total=4,
            host_gap_ms=rng.random(), queue_depth=rng.randint(0, 3),
            hbm_pages=rng.randint(4, 40),
            pod=pod_key, wclass=wclass, generation=gen, chips=1,
        )


def _soak(ops, rng, journal_dir):
    """Randomized class-annotated bind/forget churn over a v5e+v5p fleet
    with synthetic step samples per live pod; ends with a forced
    fractional co-location for the interference phase."""
    JOURNAL.configure(journal_dir, fsync="off", max_segment_bytes=64 << 20)
    cluster = FakeCluster()
    gens = {}
    for i in range(2):
        cluster.add_node(
            make_tpu_node(f"v5e-{i}", chips=4, hbm_gib=64, accelerator="v5e")
        )
        gens[f"v5e-{i}"] = "v5e"
    for i in range(2):
        cluster.add_node(
            make_tpu_node(f"v5p-{i}", chips=4, hbm_gib=96, accelerator="v5p")
        )
        gens[f"v5p-{i}"] = "v5p"
    clientset = FakeClientset(cluster)
    registry, predicate, prioritize, bind, controller, status, gang = (
        build_stack(clientset, cluster=None, priority="ici-locality")
    )
    sched = registry[consts.RESOURCE_TPU_CORE]
    nodes = list(gens)

    live = {}
    serial = 0
    for _op in range(ops):
        if live and rng.random() < 0.4:
            key = rng.choice(sorted(live))
            sched.forget_pod(live.pop(key), source="soak_delete")
            continue
        serial += 1
        wclass = rng.choice(["serve", "train"])
        core = rng.choice([50, 100, 200])
        pod = _pod(f"soak-{serial}", core, wclass)
        cluster.create_pod(pod)
        filt = predicate.handle(ExtenderArgs(pod=pod, node_names=nodes))
        if filt.error or not filt.node_names:
            continue
        target = rng.choice(filt.node_names)
        res = bind.handle(
            ExtenderBindingArgs(
                pod_name=pod.metadata.name,
                pod_namespace=pod.metadata.namespace,
                pod_uid=pod.metadata.uid,
                node=target,
            )
        )
        if res.error:
            continue
        live[pod.key] = pod
        # a solo batch of samples for this pod — fold first so the
        # neighbor resolution below sees tenancy as of THIS bind
        PROFILER._fold()
        if rng.random() < 0.8:
            _inject_samples(
                pod.key, wclass, gens[target], rng,
                colocated=bool(PROFILER.neighbors_of(pod.key)),
            )
        if rng.random() < 0.2:
            PROFILER.maybe_journal(force=True)

    # drain, then force the interference scenario: solo fractional serve
    # on one chip, then a train tenant sharing it, rates halving
    for key in sorted(live):
        sched.forget_pod(live.pop(key), source="soak_drain")
    PROFILER._fold()
    p_serve = _pod("ifx-serve", 50, "serve")
    cluster.create_pod(p_serve)
    sched.bind("v5e-0", p_serve)
    _inject_samples(p_serve.key, "serve", "v5e", rng, n=60)
    PROFILER._fold()  # solo regime folded before the co-tenant lands
    p_train = _pod("ifx-train", 50, "train")
    cluster.create_pod(p_train)
    sched.bind("v5e-0", p_train)
    _inject_samples(p_serve.key, "serve", "v5e", rng, n=60, colocated=True)
    _inject_samples(p_train.key, "train", "v5e", rng, n=30)
    PROFILER.maybe_journal(force=True)
    return status()


def _serve_overhead(budget_pct, failures, result):
    """Decode throughput + upload parity with profiling off vs on,
    through a real CPU engine (min-of-rounds each side; 3 attempts)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from elastic_gpu_scheduler_tpu.models.serving import (
        InferenceEngine,
        Request,
    )
    from elastic_gpu_scheduler_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        dtype="float32",
    )
    params = init_params(jax.random.key(0), cfg)

    def run(profiling_on):
        PROFILER.configure(sample=1.0 if profiling_on else 0.0)
        eng = InferenceEngine(
            params, cfg, max_batch=4, max_len=96, page_size=16,
            fused_steps=4,
        )
        reqs = [
            Request(prompt=[3 + i, 9, 14], max_new_tokens=24)
            for i in range(8)
        ]
        for r in reqs:
            eng.submit(r)
        t0 = time.perf_counter()
        eng.run_until_idle(max_steps=100_000)
        wall = time.perf_counter() - t0
        toks = sum(len(r.output) for r in reqs)
        for r in reqs:
            assert not r.error, r.error
        return toks / wall, eng.device_uploads

    attempts = []
    ok = False
    for _attempt in range(3):
        tput_off, up_off = run(False)
        tput_on, up_on = run(True)
        if up_on != up_off:
            failures.append(
                f"profiling changed device uploads: {up_on} vs {up_off} "
                "(must be ZERO additional host→device uploads)"
            )
            break
        overhead = (tput_off / tput_on - 1.0) * 100 if tput_on > 0 else 1e9
        attempts.append(round(overhead, 2))
        if overhead <= budget_pct:
            ok = True
            break
    result["serve_overhead_attempts_pct"] = attempts
    result["serve_tokens_per_sec_on"] = round(tput_on, 1)
    result["serve_tokens_per_sec_off"] = round(tput_off, 1)
    result["serve_device_uploads"] = up_on
    if attempts and not ok:
        failures.append(
            f"decode throughput with profiling on over budget on every "
            f"attempt ({attempts}% vs {budget_pct}%)"
        )
    PROFILER.configure(sample=0.0)


def main() -> int:
    ops = 120
    skip_serve = skip_overhead = False
    for a in sys.argv[1:]:
        if a.startswith("--ops="):
            ops = int(a.split("=", 1)[1])
        elif a == "--skip-serve":
            skip_serve = True
        elif a == "--skip-overhead":
            skip_overhead = True
        else:
            print(f"unknown argument {a!r}", file=sys.stderr)
            return 2

    seed = int(os.environ.get("CHECK_PROFILE_SEED", "20260803"))
    rng = random.Random(seed)
    tmp = tempfile.mkdtemp(prefix="tpu-profile-check-")
    journal_dir = os.path.join(tmp, "journal")
    failures: list[str] = []
    result: dict = {"metric": "check_profile", "seed": seed, "ops": ops}
    PROFILER.configure(sample=1.0)
    PROFILER.reset()
    try:
        status = _soak(ops, rng, journal_dir)

        # phase 1: convergence to the injected rates
        profiles = PROFILER.profiles()
        result["classes"] = sorted(profiles)
        for (wclass, gen), rate in INJECTED.items():
            got = profiles.get(wclass, {}).get(
                "tokens_per_sec_per_chip", {}
            ).get(gen)
            if wclass == "serve" and gen == "v5e":
                # mixed solo/co-located regimes: the EWMA must land
                # BETWEEN the co-located and solo injected rates
                lo, hi = rate * COLOCATED_FACTOR * 0.9, rate * 1.1
            else:
                lo, hi = rate * 0.85, rate * 1.15
            if got is None:
                failures.append(f"no profile for ({wclass}, {gen})")
            elif not lo <= got <= hi:
                failures.append(
                    f"({wclass}, {gen}) did not converge: {got} tok/s/chip "
                    f"vs injected {rate} (accepting [{lo:.0f}, {hi:.0f}])"
                )

        # phase 2: interference detection
        matrix = PROFILER.interference_matrix()
        result["interference"] = matrix
        ratio = matrix.get("serve", {}).get("train")
        if ratio is None:
            failures.append("no (serve, train) interference pair observed")
        elif not 0.3 <= ratio <= 0.75:
            failures.append(
                f"interference ratio {ratio} missed the injected "
                f"{COLOCATED_FACTOR} slowdown (accepting [0.3, 0.75])"
            )

        # phase 3: journal round trip + profile-aware what-if
        JOURNAL.flush()
        JOURNAL.close()
        events = read_journal(journal_dir)
        result["records"] = len(events)
        res = replay(events)
        result["profile_records"] = res.profiles
        if res.violations:
            failures.append(f"replay violations: {res.violations[:5]}")
        if res.warnings:
            failures.append(
                f"replay warnings (profile records must not warn): "
                f"{res.warnings[:5]}"
            )
        if res.profiles < 1:
            failures.append("no profile record reached the journal")

        from elastic_gpu_scheduler_tpu.core.rater import ICILocality

        base = what_if(events, ICILocality())
        aware = what_if(events, ProfileAwareRater(ICILocality()))
        result["what_if_base_score"] = base["mean_score"]
        result["what_if_aware_score"] = aware["mean_score"]
        result["what_if_profiles_seen"] = aware["profile_records"]
        if aware["profile_records"] < 1:
            failures.append("what_if fed no profile records to the rater")
        if aware["binds"] != base["binds"]:
            failures.append(
                f"what-if bind counts diverged: {aware['binds']} vs "
                f"{base['binds']}"
            )
        # a different policy legitimately diverges the chip state, so a
        # few later binds may no longer fit where the recording put them
        # (what_if falls back to the recorded placement) — but wholesale
        # placement failure means the rater broke the search
        if aware["placed"] < 0.9 * base["binds"]:
            failures.append(
                f"profile-aware what-if placed only {aware['placed']}/"
                f"{aware['binds']} binds"
            )
        if aware["mean_score"] == base["mean_score"]:
            failures.append(
                "profile-aware rater produced the same mean score as its "
                "geometry base — recorded profiles were not applied"
            )
    finally:
        JOURNAL.close()
        PROFILER.reset()
        PROFILER.configure(sample=0.0)
        shutil.rmtree(tmp, ignore_errors=True)

    # phase 4a: bind-path overhead (bench estimator, 3 attempts)
    if not skip_overhead:
        from bench import profile_bench

        try:
            budget = float(
                os.environ.get("PROFILE_OVERHEAD_BUDGET_PCT", "5")
            )
        except ValueError:
            budget = 5.0
        attempts = []
        ok = False
        for _attempt in range(3):
            overhead = profile_bench()
            attempts.append(overhead["profile_overhead_pct"])
            ok = (
                overhead["profile_overhead_pct"] <= budget
                or overhead["profile_overhead_trimmed_pct"] <= budget
            )
            if ok:
                break
        result.update(overhead)
        result["overhead_budget_pct"] = budget
        result["overhead_attempts_pct"] = attempts
        if not ok:
            failures.append(
                f"profiled bind p99 over budget on every attempt "
                f"({attempts}% vs {budget}%; trimmed "
                f"{overhead['profile_overhead_trimmed_pct']}%)"
            )

    # phase 4b: decode-throughput overhead + zero-upload parity
    if not skip_serve:
        try:
            serve_budget = float(
                os.environ.get("PROFILE_SERVE_BUDGET_PCT", "10")
            )
        except ValueError:
            serve_budget = 10.0
        result["serve_budget_pct"] = serve_budget
        _serve_overhead(serve_budget, failures, result)

    result["failures"] = failures
    print(json.dumps(result))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
