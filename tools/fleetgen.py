"""Synthetic TPU fleet generator: O(10k)-node clusters for the bench's
cluster section and the check-cluster-scale gate.

Builds a mixed v5e/v5p/v6e fleet the way GKE would label it — hosts tile
ICI slices (slice topology + host topology + host offset labels), every
slice fully populated — plus seeded, deterministic churn and gang-arrival
traces.  Everything is keyed off one RNG seed so a failure reproduces
bit-for-bit.

Shared by bench.py (cluster section) and tools/check_cluster_scale.py so
the CI gate and the artifact can never measure different fleets.
"""

from __future__ import annotations

import random

# slice templates: (generation, slice topology, host topology, hbm GiB,
# hosts per slice).  Host = one k8s node (4 chips, the GKE shape).
SLICE_TEMPLATES = (
    ("v5e", "4x4", "2x2", 64, 4),
    ("v5e", "8x8", "2x2", 64, 16),
    ("v5p", "4x4x4", "2x2x1", 380, 16),
    ("v6e", "4x4", "2x2", 96, 4),
)
# relative weight of each template in the mix
SLICE_WEIGHTS = (5, 2, 2, 3)


def _host_offsets(slice_dims, host_dims):
    """Row-major origins of host tiles inside the slice."""
    steps = [range(0, s, h) for s, h in zip(slice_dims, host_dims)]
    out = [()]
    for axis in steps:
        out = [o + (v,) for o in out for v in axis]
    return out


def make_fleet(cluster, nodes: int = 10000, seed: int = 20260804) -> list:
    """Populate ``cluster`` (FakeCluster) with ~``nodes`` hosts of mixed
    generations; returns the node names in creation order.  The count is
    rounded up to whole slices so no slice is ever partially populated
    (a torn slice would make ICI-locality scores meaningless)."""
    from elastic_gpu_scheduler_tpu.k8s.objects import make_tpu_node

    rng = random.Random(seed)
    names: list[str] = []
    slice_serial = 0
    while len(names) < nodes:
        gen, slice_topo, host_topo, hbm, _hosts = rng.choices(
            SLICE_TEMPLATES, weights=SLICE_WEIGHTS
        )[0]
        slice_dims = tuple(int(d) for d in slice_topo.split("x"))
        host_dims = tuple(int(d) for d in host_topo.split("x"))
        chips_per_host = 1
        for d in host_dims:
            chips_per_host *= d
        slice_name = f"{gen}-slice-{slice_serial}"
        slice_serial += 1
        for hi, offset in enumerate(_host_offsets(slice_dims, host_dims)):
            name = f"{slice_name}-h{hi}"
            cluster.add_node(
                make_tpu_node(
                    name,
                    chips=chips_per_host,
                    hbm_gib=hbm * chips_per_host // 4,
                    accelerator=gen,
                    slice_topology=slice_topo,
                    host_topology=host_topo,
                    host_offset=".".join(map(str, offset)),
                    slice_name=slice_name,
                )
            )
            names.append(name)
    return names


def twin_fleet(nodes: int = 4, seed: int = 20260804) -> list:
    """Seeded node specs for the digital twin, in journal ``node_add``
    wire form (``{"node", "generation", "dims", "wrap", "chips"}``).

    Unlike ``make_fleet`` this builds no FakeCluster — the twin's
    simulated allocator domains are fed straight from these specs
    (``TwinScenario(fleet=twin_fleet(...))``).  Domains are whole ICI
    slices drawn from the same SLICE_TEMPLATES the cluster bench uses,
    so twin packing sees the real mesh shapes (4x4 v5e/v6e, 4x4x4
    v5p) rather than single-host 2x2 tiles."""
    rng = random.Random(seed)
    specs: list = []
    for i in range(nodes):
        gen, slice_topo, _host_topo, hbm, _hosts = rng.choices(
            SLICE_TEMPLATES, weights=SLICE_WEIGHTS
        )[0]
        dims = tuple(int(d) for d in slice_topo.split("x"))
        coords = [()]
        for d in dims:
            coords = [c + (v,) for c in coords for v in range(d)]
        specs.append({
            "node": f"twin-{gen}-{i}",
            "generation": gen,
            "dims": list(dims),
            "wrap": [False] * len(dims),
            "chips": [[list(c), 100, hbm // 4] for c in coords],
        })
    return specs


def churn_trace(node_names: list, ops: int, seed: int = 1,
                whole_pct: float = 0.6) -> list:
    """Seeded bind/forget op stream: ``("bind", pod_serial, core_units)``
    and ``("forget", bind_serial)`` tuples.  ~60% whole-chip pods (100 or
    200 core), the rest fractional — the tpushare mix.  Forgets reference
    earlier binds by serial; the consumer resolves them against whatever
    actually bound."""
    rng = random.Random(seed)
    trace: list = []
    live: list[int] = []
    for i in range(ops):
        if live and rng.random() < 0.35:
            victim = live.pop(rng.randrange(len(live)))
            trace.append(("forget", victim))
            continue
        if rng.random() < whole_pct:
            core = rng.choice((100, 100, 200, 400))
        else:
            core = rng.choice((30, 50, 60))
        trace.append(("bind", i, core))
        live.append(i)
    return trace


def gang_trace(count: int, seed: int = 2,
               sizes=(8, 16, 32, 64), chips=(4,)) -> list:
    """Seeded gang arrivals: ``(gang_serial, members, chips_per_member)``."""
    rng = random.Random(seed)
    return [
        (i, rng.choice(sizes), rng.choice(chips)) for i in range(count)
    ]
