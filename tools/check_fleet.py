#!/usr/bin/env python
"""CI gate for the elastic serving fleet (`make check-fleet`).

A multi-replica CPU soak over REAL engines (tiny model, real inference
HTTP servers, the real scheduler stack over a FakeCluster), all
HARD-FAIL:

1. **Affinity** — a churning sessioned request mix through the router:
   the prefix-affinity hit rate must beat the random-routing baseline
   (1/N) by a wide margin, and every repeat-prefix request must land on
   the replica that served its session before.
2. **Scale-up** — an injected queue-depth spike (burst of streaming
   requests against deliberately tiny slot pools) must drive the
   autoscaler to a journaled, EXECUTED scale-up through the scheduler's
   HTTP verbs; the burst must then drain and the fleet's queue signal
   fall back under the high watermark (the latency SLO restored).
3. **Scale-down** — with the fleet idle and streams in flight, the
   scale-down must drain the victim first: ZERO dropped streams (every
   request completes with a [DONE]), the victim's pod deleted and its
   chips released.
4. **Resize** — a live gang resize (grow + shrink) over serving pods
   bracketed by the drain/elastic-resume hooks: at most one in-flight
   chunk lost per moved pod (the engines' ``chunks_discarded`` delta)
   and greedy outputs token-identical to an undisturbed run.
5. **Journal** — every autoscaler evaluation and the resize commits are
   in the journal; replay reports ZERO violations (incl. the resize
   chip-conservation + all-or-nothing invariants) and the live diff is
   empty.
6. **Router overhead** — the router's hop p99 (selection + connect +
   forward) within FLEET_OVERHEAD_BUDGET_MS (default 50ms on CPU).

Usage:
    python tools/check_fleet.py

Environment:
    CHECK_FLEET_SEED             soak RNG seed (default 20260803)
    FLEET_OVERHEAD_BUDGET_MS     router hop p99 budget (default 50)

Wired into the Makefile as `make check-fleet`, next to `check-profile`.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from bench import _fleet_post, _make_cpu_replica, p99  # noqa: E402
from elastic_gpu_scheduler_tpu.cli import build_stack  # noqa: E402
from elastic_gpu_scheduler_tpu.fleet import (  # noqa: E402
    Autoscaler,
    FleetRouter,
    GangResizer,
    ReplicaSet,
    ScalingPolicy,
    SchedulerGangExecutor,
)
from elastic_gpu_scheduler_tpu.journal import JOURNAL, read_journal  # noqa: E402
from elastic_gpu_scheduler_tpu.journal.replay import diff_live, replay  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.client import FakeClientset  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.fake import FakeCluster  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.objects import (  # noqa: E402
    Container,
    ResourceRequirements,
    make_pod,
    make_tpu_node,
)
from elastic_gpu_scheduler_tpu.server.routes import ExtenderServer  # noqa: E402
from elastic_gpu_scheduler_tpu.utils import consts  # noqa: E402


class _NoRelay:
    up = None
    detail = ""


def serving_pod(name, core=100, gang=None):
    ann = {consts.ANNOTATION_WORKLOAD_CLASS: "serve"}
    if gang:
        ann[consts.ANNOTATION_GANG_NAME] = gang
        ann[consts.ANNOTATION_GANG_SIZE] = "1"
    return make_pod(
        name,
        containers=[Container(
            name="main",
            resources=ResourceRequirements(
                limits={consts.RESOURCE_TPU_CORE: core}
            ),
        )],
        annotations=ann,
    )


def stream_request(port, prompt, max_tokens, results, idx):
    """One streaming completion; records (tokens, done_clean)."""
    import socket as _socket

    raw = json.dumps(
        {"prompt": prompt, "max_tokens": max_tokens, "stream": True}
    ).encode()
    try:
        with _socket.create_connection(
            ("127.0.0.1", port), timeout=120
        ) as s:
            s.sendall((
                f"POST /v1/completions HTTP/1.1\r\nHost: x\r\n"
                f"Content-Length: {len(raw)}\r\n"
                "Connection: close\r\n\r\n"
            ).encode() + raw)
            buf = b""
            while True:
                b = s.recv(65536)
                if not b:
                    break
                buf += b
        results[idx] = (
            buf.count(b'"token"'), b"data: [DONE]" in buf,
        )
    except OSError as e:
        results[idx] = (0, False, str(e))


def main() -> int:
    seed = int(os.environ.get("CHECK_FLEET_SEED", "20260803"))
    try:
        budget_ms = float(os.environ.get("FLEET_OVERHEAD_BUDGET_MS", "50"))
    except ValueError:
        budget_ms = 50.0
    rng = random.Random(seed)
    tmp = tempfile.mkdtemp(prefix="tpu-fleet-check-")
    journal_dir = os.path.join(tmp, "journal")
    failures: list[str] = []
    result: dict = {"metric": "check_fleet", "seed": seed}

    import jax

    from elastic_gpu_scheduler_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        dtype="float32",
    )
    params = init_params(jax.random.key(0), cfg)

    JOURNAL.configure(journal_dir, fsync="off")
    cluster = FakeCluster()
    for i in range(2):
        cluster.add_node(make_tpu_node(
            f"v5e-{i}", chips=4, hbm_gib=64, accelerator="v5e",
        ))
    for i in range(2):
        cluster.add_node(make_tpu_node(
            f"v5p-{i}", chips=4, hbm_gib=96, accelerator="v5p",
        ))
    clientset = FakeClientset(cluster)
    registry, predicate, prioritize, bind, controller, status, gang = (
        build_stack(clientset, cluster=None, priority="binpack")
    )
    sched = registry[consts.RESOURCE_TPU_CORE]
    sched_server = ExtenderServer(
        predicate, prioritize, bind, status, host="127.0.0.1", port=0,
    )
    sched_port = sched_server.start()

    rs = ReplicaSet(interval_s=0.2, relay_monitor=_NoRelay())
    router = FleetRouter(rs, host="127.0.0.1", port=0, page_size=8)
    replicas: dict[str, dict] = {}
    serial = [0]

    def spawn(pod, node):
        # tiny slot pools so a burst actually queues (the spike phase)
        rep = _make_cpu_replica(
            pod.metadata.name, params, cfg,
            max_batch=2, max_len=128, page_size=8, fused_steps=4,
        )
        replicas[pod.metadata.name] = rep
        return rep["replica"]

    def release(name, pod):
        rep = replicas.pop(name, None)
        if rep is not None:
            rep["server"].shutdown()
            rep["loop"].stop()

    executor = SchedulerGangExecutor(
        cluster, ("127.0.0.1", sched_port), rs,
        pod_factory=lambda s: serving_pod(f"fleet-{s}"),
        spawner=spawn,
        releaser=release,
    )
    autoscaler = Autoscaler(
        rs, executor,
        policy=ScalingPolicy(
            # queue_low deliberately sits under queue_high but above the
            # residue a few in-flight streams leave (phase 3 scales down
            # WHILE streams drain — that is the zero-dropped-streams
            # property under test)
            min_replicas=2, max_replicas=4, queue_high=1.5,
            queue_low=0.75, occupancy_low=0.95, occupancy_high=5.0,
            page_high=5.0, hysteresis_rounds=1,
            up_cooldown_s=0.0, down_cooldown_s=0.0,
        ),
        interval_s=60.0,  # ticks driven explicitly below
    )

    try:
        # seed the fleet to the floor through the scheduler surface
        for _ in range(2):
            name = executor.scale_up("seed", [])
            if name is None:
                failures.append("seeding scale-up failed")
                raise SystemExit(1)
        router_port = router.start()
        rs.refresh()
        if len(rs.routable()) != 2:
            failures.append(
                f"expected 2 routable replicas, have {len(rs.routable())}"
            )

        # phase 1: prefix affinity vs random baseline ---------------------
        sessions = [
            [rng.randrange(64) for _ in range(16)] for _ in range(8)
        ]
        for turn in range(4):
            order = list(range(len(sessions)))
            rng.shuffle(order)  # churn: interleave sessions
            for si in order:
                prompt = sessions[si] + [
                    rng.randrange(64) for _ in range(turn)
                ]
                st, _ = _fleet_post(router_port, {
                    "prompt": prompt, "max_tokens": 2,
                })
                if st != 200:
                    failures.append(f"affinity soak request failed: {st}")
                    break
        dbg = router.debug_state()["affinity"]
        result["affinity_hit_pct"] = dbg["hit_pct"]
        random_pct = 100.0 / max(1, len(rs.routable()))
        result["affinity_random_pct"] = round(random_pct, 2)
        # 8 sessions × 4 turns: first turn misses, the rest must hit →
        # expected 75%; random routing would manage ~1/N
        if dbg["hit_pct"] <= random_pct + 10:
            failures.append(
                f"affinity hit rate {dbg['hit_pct']}% does not beat the "
                f"random baseline {random_pct:.0f}%"
            )

        # phase 2: queue-depth spike → journaled scale-up → SLO restored --
        n_before = len(rs.routable())
        burst_n = 12
        results_burst: dict[int, tuple] = {}
        threads = [
            threading.Thread(
                target=stream_request,
                args=(router_port, [rng.randrange(64) for _ in range(6)],
                      48, results_burst, i),
                daemon=True,
            )
            for i in range(burst_n)
        ]
        t_spike = time.perf_counter()
        for t in threads:
            t.start()
        # wait until the queues actually show the spike
        spike_seen = False
        for _ in range(200):
            rs.refresh()
            sig = autoscaler.signals()
            if sig["queue_per_replica"] >= 1.5:
                spike_seen = True
                break
            time.sleep(0.02)
        if not spike_seen:
            failures.append("queue-depth spike never materialized")
        decision = autoscaler.tick()
        result["spike_decision"] = {
            k: decision[k] for k in ("action", "reason", "executed")
        }
        if decision["action"] != "up" or not decision["executed"]:
            failures.append(
                f"spike did not trigger an executed scale-up: {decision}"
            )
        else:
            result["scale_up_latency_ms"] = round(
                (time.perf_counter() - t_spike) * 1000, 3
            )
        rs.refresh()
        if len(rs.routable()) != n_before + 1:
            failures.append("scale-up did not add a routable replica")
        for t in threads:
            t.join(timeout=120)
        dropped = [
            i for i, r in results_burst.items() if not r or not r[1]
        ]
        if dropped or len(results_burst) != burst_n:
            failures.append(
                f"burst streams dropped: {dropped} "
                f"({len(results_burst)}/{burst_n} finished)"
            )
        # SLO restored: the queue signal fell back under the watermark
        deadline = time.monotonic() + 30
        restored = False
        while time.monotonic() < deadline:
            rs.refresh()
            if autoscaler.signals()["queue_per_replica"] < 1.5:
                restored = True
                break
            time.sleep(0.05)
        if not restored:
            failures.append("queue depth never fell back under the "
                            "high watermark after the scale-up")

        # phase 3: scale-down drains with zero dropped streams ------------
        n_now = len(rs.routable())
        results_down: dict[int, tuple] = {}
        threads = [
            threading.Thread(
                target=stream_request,
                args=(router_port, [rng.randrange(64) for _ in range(6)],
                      32, results_down, i),
                daemon=True,
            )
            for i in range(4)
        ]
        for t in threads:
            t.start()
        time.sleep(0.1)  # streams in flight
        decision = autoscaler.tick()
        result["down_decision"] = {
            k: decision[k] for k in ("action", "reason", "executed")
        }
        if decision["action"] != "down" or not decision["executed"]:
            failures.append(
                f"idle fleet did not scale down cleanly: {decision}"
            )
        for t in threads:
            t.join(timeout=120)
        dropped = [
            i for i, r in results_down.items() if not r or not r[1]
        ]
        if dropped or len(results_down) != 4:
            failures.append(
                f"scale-down dropped streams: {dropped} "
                f"({len(results_down)}/4 finished)"
            )
        rs.refresh()
        if len(rs.routable()) != n_now - 1:
            failures.append("scale-down did not remove exactly one replica")

        # phase 4: live gang resize, ≤1 lost chunk per moved pod ----------
        from elastic_gpu_scheduler_tpu.defrag.hooks import ServingEngineHook

        baseline_eng = _make_cpu_replica(
            "baseline", params, cfg, max_batch=2, max_len=128,
            page_size=8, fused_steps=4,
        )
        from elastic_gpu_scheduler_tpu.models.serving import Request

        base_req = baseline_eng["engine"].submit(
            Request(prompt=[3, 9, 14], max_new_tokens=24)
        )
        base_req.done.wait(120)
        baseline_tokens = list(base_req.output)
        baseline_eng["server"].shutdown()
        baseline_eng["loop"].stop()

        gp = serving_pod("gang-0", gang="serve-gang")
        cluster.create_pod(gp)
        sched.bind("v5e-0", gp)
        gang_rep = _make_cpu_replica(
            "gang-0", params, cfg, max_batch=2, max_len=128,
            page_size=8, fused_steps=4,
        )
        hook = ServingEngineHook(gang_rep["loop"], timeout=60.0)

        class NamedHook:
            def drain(self, pod_key, node):
                return hook.drain(pod_key, node)

            def resume(self, pod_key, node):
                hook.resume(pod_key, node)

        resizer = GangResizer(sched, clientset, hooks=[NamedHook()])
        # a stream in flight on the gang's engine while it grows
        live_req = gang_rep["engine"].submit(
            Request(prompt=[3, 9, 14], max_new_tokens=24)
        )
        discarded_before = gang_rep["engine"].chunks_discarded
        g1 = serving_pod("gang-1", gang="serve-gang")
        cluster.create_pod(g1)
        out = resizer.grow("default/serve-gang", [g1])
        result["resize_grow_members"] = out["members"]
        live_req.done.wait(120)
        if live_req.error:
            failures.append(f"in-flight stream errored across resize: "
                            f"{live_req.error}")
        if list(live_req.output) != baseline_tokens:
            failures.append(
                "greedy stream not token-identical across the resize"
            )
        lost = gang_rep["engine"].chunks_discarded - discarded_before
        result["resize_lost_chunks"] = lost
        if lost > 1:
            failures.append(
                f"resize lost {lost} in-flight chunks for one moved pod "
                "(contract: at most one)"
            )
        out = resizer.shrink("default/serve-gang", ["default/gang-1"])
        if out["members"] != ["default/gang-0"]:
            failures.append(f"shrink left wrong membership: {out}")
        gang_rep["server"].shutdown()
        gang_rep["loop"].stop()

        # phase 6: router hop p99 budget ----------------------------------
        # dedicated QUIET probe: samples taken while the burst phases
        # had three engines decoding concurrently measure GIL pressure,
        # not routing cost — the budget applies to the router's own hop
        mark = len(router.overhead_samples)
        for i in range(40):
            st, _ = _fleet_post(router_port, {
                "prompt": [(5 * i) % 64, 3], "max_tokens": 1,
            })
            if st != 200:
                failures.append(f"overhead probe request failed: {st}")
                break
        quiet = sorted(router.overhead_samples[mark:])
        hop_p99_ms = p99(list(quiet)) * 1000 if quiet else 0.0
        # storm-trimmed estimate (check-journal's pattern): drop the top
        # 10% — on a small CPU box the engine threads' GIL holds land
        # ~40ms stalls on a few unlucky connects; that is box pressure,
        # not router cost (p50 here is ~1.5ms)
        trimmed = quiet[: max(1, int(len(quiet) * 0.9))]
        hop_trimmed_ms = p99(list(trimmed)) * 1000 if trimmed else 0.0
        result["router_hop_p99_ms"] = round(hop_p99_ms, 3)
        result["router_hop_p99_trimmed_ms"] = round(hop_trimmed_ms, 3)
        result["router_hop_p99_all_ms"] = round(
            p99(list(router.overhead_samples)) * 1000, 3
        ) if router.overhead_samples else 0.0
        result["router_budget_ms"] = budget_ms
        if hop_p99_ms > budget_ms and hop_trimmed_ms > budget_ms:
            failures.append(
                f"router hop p99 {hop_p99_ms:.1f}ms (trimmed "
                f"{hop_trimmed_ms:.1f}ms) over the {budget_ms}ms budget"
            )
    finally:
        try:
            router.stop()
        except Exception:
            pass
        for name in list(replicas):
            release(name, None)
        sched_server.stop()

    # phase 5: journal round trip ----------------------------------------
    if not JOURNAL.flush():
        failures.append("journal flush failed (write loss?)")
    live_status = status()
    JOURNAL.close()
    events = read_journal(journal_dir)
    result["journal_records"] = len(events)
    fleet_recs = [e for e in events if e.get("type") == "fleet"]
    resize_recs = [e for e in events if e.get("type") == "resize"]
    result["fleet_records"] = len(fleet_recs)
    result["resize_records"] = len(resize_recs)
    if len(fleet_recs) < 2:
        failures.append(
            f"expected every autoscaler evaluation journaled, found "
            f"{len(fleet_recs)} fleet records"
        )
    if not any(
        e.get("action") == "up" and e.get("executed") for e in fleet_recs
    ):
        failures.append("no executed scale-up reached the journal")
    if len(resize_recs) != 2:
        failures.append(
            f"expected 2 resize records (grow+shrink), found "
            f"{len(resize_recs)}"
        )
    res = replay(events)
    if res.violations:
        failures.append(f"replay violations: {res.violations[:5]}")
    diffs = diff_live(res, live_status)
    if diffs:
        failures.append(f"replay/live diff: {diffs[:5]}")

    shutil.rmtree(tmp, ignore_errors=True)
    result["failures"] = failures
    print(json.dumps(result))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
