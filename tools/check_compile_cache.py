#!/usr/bin/env python
"""CI gate for the warm-start compilation plane (`make check-compile-cache`).

Proves the persistent AOT cache's whole contract end-to-end, across
REAL process boundaries, and HARD-FAILS when any leg breaks:

1. **Cold fill.**  A fresh subprocess builds an engine on an empty
   ``--compile-cache-dir`` equivalent, runs the shape-lattice warm-up,
   serves real requests, and reports counters: every lattice shape must
   compile + persist (fills == lattice size), serving must hit the warm
   executables with zero jit fallbacks.
2. **Warm restart — zero new lowerings.**  A SECOND subprocess on the
   same dir must load every lattice shape from disk (fills == 0,
   misses == 0, loads == lattice size), its measured warm-up wall must
   come in well under the cold one (CHECK_CC_WARM_FRACTION, default
   0.5), its first-request admission latency must beat the cold
   process's, and its greedy output must be token-identical.
3. **Corruption is quarantined, not fatal.**  With one entry bit-
   flipped and one truncated, a third start must quarantine exactly the
   damaged entries, recompile them, still serve correctly, and leave
   ``.bad`` files for the operator.
4. **Single-flight.**  In-process: 8 threads missing on one key compile
   once (coalesced >= 1, misses == 1).

Runs on CPU (JAX_PLATFORMS=cpu recommended), a few minutes end-to-end.

Usage:
    python tools/check_compile_cache.py [--keep]

Environment:
    CHECK_CC_WARM_FRACTION  warm/cold warm-up wall ceiling (default 0.5)

Wired into the Makefile as `make check-compile-cache`, next to
`check-policy`.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

WORKER = r"""
import json, os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, %(repo)r)
import jax
from elastic_gpu_scheduler_tpu.compilecache import (
    CompileCache, WarmupState, warmup_engine)
from elastic_gpu_scheduler_tpu.models.serving import InferenceEngine, Request
from elastic_gpu_scheduler_tpu.models.transformer import (
    TransformerConfig, init_params)

cfg = TransformerConfig(vocab_size=64, d_model=32, n_layers=2, n_heads=2,
                        d_ff=64, dtype="float32")
params = init_params(jax.random.key(0), cfg)
cache = CompileCache(%(cache_dir)r)
eng = InferenceEngine(params, cfg, max_batch=2, max_len=64, page_size=8,
                      fused_steps=4, compile_cache=cache)
st = WarmupState()
t0 = time.perf_counter()
if %(do_warmup)r:
    warmup_engine(eng, st, journal=False)
warmup_wall = time.perf_counter() - t0

# admission latency: submit → first token out (the p99.9 cliff the
# lattice exists to remove; on a warm lattice no compile sits in it)
first_tok = [None]
req = Request(prompt=[3, 1, 4, 1, 5, 9, 2, 6], max_new_tokens=12)
t1 = time.perf_counter()
req.on_token = lambda tok: first_tok.__setitem__(
    0, first_tok[0] or (time.perf_counter() - t1))
eng.submit(req)
eng.run_until_idle()
assert not req.error, req.error
req2 = Request(prompt=[2, 7, 1, 8], max_new_tokens=8)
eng.submit(req2)
eng.run_until_idle()
assert not req2.error, req2.error

print("RESULT " + json.dumps({
    "warmup": st.to_dict(),
    "cache": cache.stats(),
    "warmup_wall_s": warmup_wall,
    "admit_first_token_s": first_tok[0],
    "tokens": list(req.output) + list(req2.output),
}), flush=True)
"""


def run_worker(repo: str, cache_dir: str, do_warmup: bool = True) -> dict:
    env = {k: v for k, v in os.environ.items() if not k.startswith("JAX")}
    env["JAX_PLATFORMS"] = "cpu"
    code = WORKER % {
        "repo": repo, "cache_dir": cache_dir, "do_warmup": do_warmup,
    }
    p = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=900, env=env,
    )
    if p.returncode != 0:
        raise SystemExit(
            f"FAIL: worker process died:\n{p.stderr[-3000:]}"
        )
    for line in p.stdout.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise SystemExit(f"FAIL: worker produced no RESULT:\n{p.stdout[-2000:]}")


def check(cond: bool, what: str) -> None:
    if not cond:
        raise SystemExit(f"FAIL: {what}")
    print(f"ok: {what}")


def single_flight_check() -> None:
    import threading

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp

    from elastic_gpu_scheduler_tpu.compilecache import (
        CompileCache,
        cache_key,
    )

    with tempfile.TemporaryDirectory() as d:
        cache = CompileCache(d)
        jf = jax.jit(lambda x: (x * x).sum())
        args = (jnp.ones(32),)
        key = cache_key("sf-gate", (32,))
        builds = []

        def build():
            builds.append(1)
            import time as _t

            _t.sleep(0.25)
            return jf.lower(*args).compile()

        outs = []
        threads = [
            threading.Thread(
                target=lambda: outs.append(cache.get_or_compile(key, build))
            )
            for _ in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        check(
            len(builds) == 1 and cache.misses == 1,
            f"single-flight: 8 concurrent misses → 1 compile "
            f"(coalesced={cache.coalesced})",
        )
        check(
            len(outs) == 8 and all(o is outs[0] for o in outs),
            "single-flight: every waiter adopted the winner's executable",
        )


def main() -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    keep = "--keep" in sys.argv
    warm_frac = float(os.environ.get("CHECK_CC_WARM_FRACTION", "0.5"))
    workdir = tempfile.mkdtemp(prefix="check-compile-cache-")
    cache_dir = os.path.join(workdir, "cc")
    try:
        # 1. cold fill
        cold = run_worker(repo, cache_dir)
        lat = cold["warmup"]["lattice_size"]
        check(lat > 0, f"cold start enumerated a {lat}-point lattice")
        check(
            cold["warmup"]["fills"] == lat and cold["cache"]["loads"] == 0,
            f"cold start compiled+persisted every lattice shape "
            f"({cold['warmup']['fills']}/{lat})",
        )
        check(
            cold["cache"]["fallbacks"] == 0,
            "cold serving dispatched through AOT executables "
            "(zero jit fallbacks)",
        )
        check(
            cold["warmup"]["errors"] == 0,
            "cold warm-up pre-lowered without errors",
        )

        # 2. warm restart: ZERO new lowerings, measured warm-up speedup
        warm = run_worker(repo, cache_dir)
        check(
            warm["cache"]["fills"] == 0 and warm["cache"]["misses"] == 0,
            "second process start on the same dir performed ZERO new "
            "lowerings (fills=0, misses=0)",
        )
        check(
            warm["warmup"]["loads"] == lat,
            f"warm start loaded every lattice entry ({lat})",
        )
        check(
            warm["warmup_wall_s"] <= cold["warmup_wall_s"] * warm_frac,
            f"warm warm-up {warm['warmup_wall_s']:.2f}s ≪ cold "
            f"{cold['warmup_wall_s']:.2f}s (≤ {warm_frac:.0%})",
        )
        check(
            warm["tokens"] == cold["tokens"],
            "greedy decode through loaded executables is token-identical",
        )
        # admission-path cliff: a process that SKIPS the warm-up pays
        # the prefill+chunk compiles on its first request's first token;
        # the warm-lattice process must admit far under that (2x floor —
        # the real ratio on CPU is ~20-50x, the margin absorbs CI noise)
        nowarm = run_worker(
            repo, os.path.join(workdir, "cc-nowarm"), do_warmup=False
        )
        check(
            nowarm["warmup"]["state"] == "none"
            and nowarm["cache"]["misses"] > 0,
            "no-warmup baseline compiled on the admission path",
        )
        check(
            warm["admit_first_token_s"]
            <= nowarm["admit_first_token_s"] / 2.0,
            f"warm admission first-token "
            f"{warm['admit_first_token_s'] * 1e3:.1f}ms ≪ cold-admission "
            f"{nowarm['admit_first_token_s'] * 1e3:.1f}ms",
        )

        # 3. corruption: flip one entry, truncate another → quarantined,
        # recompiled, still correct
        entries = sorted(
            n for n in os.listdir(cache_dir) if n.endswith(".aotx")
        )
        check(len(entries) == lat, f"{lat} entries on disk")
        flip = os.path.join(cache_dir, entries[0])
        blob = bytearray(open(flip, "rb").read())
        blob[-5] ^= 0xFF
        open(flip, "wb").write(bytes(blob))
        trunc = os.path.join(cache_dir, entries[1])
        open(trunc, "r+b").truncate(max(16, os.path.getsize(trunc) // 2))
        repaired = run_worker(repo, cache_dir)
        check(
            repaired["cache"]["quarantined"] == 2,
            "both damaged entries quarantined (not fatal)",
        )
        check(
            repaired["cache"]["misses"] == 2
            and repaired["cache"]["fills"] == 2
            and repaired["warmup"]["loads"] == lat - 2,
            "exactly the damaged entries recompiled; the rest loaded",
        )
        check(
            repaired["tokens"] == cold["tokens"],
            "post-quarantine serving still token-identical",
        )
        bads = [n for n in os.listdir(cache_dir) if n.endswith(".bad")]
        check(len(bads) == 2, "quarantined entries kept as .bad for triage")

        # 4. single-flight (in-process)
        single_flight_check()

        print(json.dumps({
            "lattice_size": lat,
            "cold_warmup_s": round(cold["warmup_wall_s"], 3),
            "warm_warmup_s": round(warm["warmup_wall_s"], 3),
            "warm_speedup": round(
                cold["warmup_wall_s"] / max(warm["warmup_wall_s"], 1e-9), 1
            ),
            "cold_admit_ms": round(nowarm["admit_first_token_s"] * 1e3, 2),
            "warm_admit_ms": round(warm["admit_first_token_s"] * 1e3, 2),
        }))
        print("check-compile-cache: PASS")
        return 0
    finally:
        if keep:
            print(f"kept workdir: {workdir}")
        else:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
