#!/usr/bin/env python
"""CI gate for the fleet SLO plane (`make check-slo`).

A seeded fleet soak over REAL engines — two in-process CPU replicas for
the overhead phases plus ONE true subprocess replica (serve.py) carrying
an injected latency fault — all HARD-FAIL:

1. **Burn-rate alert** — a deterministic `delay` fault plan at the
   replica's ``serve.request`` site (faultinject/) degrades TTFT/e2e
   without failing anything; the router's journey records must push the
   declared objective's multi-window burn rate past threshold and trip
   a breach.
2. **Journaled breach with a resolvable exemplar** — the breach lands
   as an ``slo`` journal record carrying exemplar trace ids, and the
   exemplar must resolve via the trace assembler
   (``GET /debug/trace/<id>``) into one causally-ordered journey with
   spans from AT LEAST TWO PROCESSES (the router's ``fleet.route`` span
   + the subprocess replica's ``serve.request``/``engine.step`` spans).
3. **SLO-proactive scaling** — a journaled autoscaler evaluation must
   carry the burn posture (``slo`` field) and decide ``up`` on it while
   the queue signal is still idle (budget burn leads queue depth).
4. **Replay** — journal replay reports ZERO violations and reconstructs
   the breach (count + exemplars).
5. **Router overhead** — hop p99 with the SLO plane ON stays within
   SLO_OVERHEAD_BUDGET_PCT of OFF (interleaved on/off chunks, pooled
   per-mode storm-trimmed p99s, ×3 attempts — every attempt must
   breach for the gate to fail, the check-journal stance on noisy CI
   boxes; deltas under SLO_OVERHEAD_FLOOR_MS pass outright).

Usage:
    python tools/check_slo.py

Environment:
    CHECK_SLO_SEED              soak RNG seed (default 20260804)
    SLO_OVERHEAD_BUDGET_PCT     hop-p99 on-vs-off budget (default 25)
    SLO_OVERHEAD_FLOOR_MS       absolute delta below which the budget
                                cannot fail (default 2.0)

Wired into the Makefile as `make check-slo`, next to `check-disagg`.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from bench import _fleet_post, _make_cpu_replica, p99  # noqa: E402
from elastic_gpu_scheduler_tpu.fleet import (  # noqa: E402
    Autoscaler,
    FleetRouter,
    Replica,
    ReplicaSet,
    ScalingPolicy,
)
from elastic_gpu_scheduler_tpu.journal import JOURNAL, read_journal  # noqa: E402
from elastic_gpu_scheduler_tpu.journal.replay import replay  # noqa: E402
from elastic_gpu_scheduler_tpu.slo import SLO  # noqa: E402
from elastic_gpu_scheduler_tpu.slo.assembly import TraceAssembler  # noqa: E402


class _NoRelay:
    up = None
    detail = ""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http_get(port: int, path: str, timeout=5.0):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _stream_once(port, prompt, max_tokens=8, timeout=120.0):
    """One streaming completion through the router; returns the raw
    bytes (the SLO journey is recorded router-side)."""
    raw = json.dumps({
        "prompt": prompt, "max_tokens": max_tokens, "stream": True,
    }).encode()
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=timeout) as s:
        s.sendall((
            f"POST /v1/completions HTTP/1.1\r\nHost: x\r\n"
            f"Content-Length: {len(raw)}\r\n"
            "Connection: close\r\n\r\n"
        ).encode() + raw)
        buf = b""
        while True:
            b = s.recv(65536)
            if not b:
                break
            buf += b
    return buf


def spawn_faulty_replica(port: int, delay_s: float, tmp: str):
    """A REAL serve.py subprocess (its spans live in ITS ring — the
    cross-process half of the trace-assembly contract) with a
    deterministic serve.request delay plan."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["TPU_FAULT_PLAN"] = json.dumps([{
        "site": "serve.request", "kind": "delay", "p": 1.0,
        "delay_s": delay_s,
    }])
    env["POD_NAME"] = "slow-replica"
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "elastic_gpu_scheduler_tpu.serve",
            "--init", "--cpu", "--port", str(port),
            "--host", "127.0.0.1",
            "--vocab-size", "64", "--d-model", "32", "--n-layers", "2",
            "--n-heads", "2", "--d-ff", "64", "--dtype", "float32",
            "--max-batch", "2", "--max-len", "128", "--page-size", "8",
            "--fused-steps", "4",
        ],
        stdout=open(os.path.join(tmp, "replica.log"), "wb"),
        stderr=subprocess.STDOUT,
        env=env,
    )
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"replica subprocess died (rc={proc.returncode}); see "
                f"{tmp}/replica.log"
            )
        try:
            st, _ = _http_get(port, "/healthz", timeout=1.0)
            if st == 200:
                return proc
        except OSError:
            pass
        time.sleep(0.25)
    proc.terminate()
    raise RuntimeError("replica subprocess never became healthy")


def main() -> int:
    seed = int(os.environ.get("CHECK_SLO_SEED", "20260804"))
    budget_pct = float(os.environ.get("SLO_OVERHEAD_BUDGET_PCT", "25"))
    floor_ms = float(os.environ.get("SLO_OVERHEAD_FLOOR_MS", "2.0"))
    rng = random.Random(seed)
    tmp = tempfile.mkdtemp(prefix="tpu-slo-check-")
    journal_dir = os.path.join(tmp, "journal")
    failures: list[str] = []
    result: dict = {"metric": "check_slo", "seed": seed}

    import jax

    from elastic_gpu_scheduler_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        dtype="float32",
    )
    params = init_params(jax.random.key(0), cfg)

    JOURNAL.configure(journal_dir, fsync="off")
    SLO.reset()
    SLO.load_config({
        # tight TTFT objective the delay fault will blow; generous
        # windows so the whole fault phase fits the short window
        "classes": {"default": {"ttft_p95_ms": 150,
                                "availability": 0.5}},
        "window_short_s": 30, "window_long_s": 60,
        "burn_threshold": 1.0, "min_samples": 4,
    })

    rs = ReplicaSet(interval_s=60.0, relay_monitor=_NoRelay())
    router = FleetRouter(rs, host="127.0.0.1", port=0, page_size=8)
    assembler = TraceAssembler(
        sources=lambda: [(r.name, (r.host, r.port)) for r in rs.all()],
    )
    router.assembler = assembler
    SLO.breach_hooks.append(assembler.on_breach)

    reps = [
        _make_cpu_replica(f"slo-rep-{i}", params, cfg,
                          max_batch=4, max_len=128, page_size=8,
                          fused_steps=4)
        for i in range(2)
    ]
    for r in reps:
        rs.add(r["replica"])
    rs.refresh()
    router_port = router.start()
    proc = None

    try:
        # phase 1: hop-p99 overhead, SLO plane on vs off ------------------
        # interleaved chunks (journal/profile gate pattern): per-mode
        # pools see the same box weather; ×3 storm-trimmed attempts
        def probe_chunk(n=20):
            out = []
            for _ in range(n):
                mark = len(router.overhead_samples)
                st, _ = _fleet_post(router_port, {
                    "prompt": [rng.randrange(64) for _ in range(4)],
                    "max_tokens": 1,
                })
                if st != 200:
                    failures.append(f"overhead probe failed: {st}")
                    return out
                out.extend(router.overhead_samples[mark:])
            return out

        attempts = []
        passed_budget = False
        for attempt in range(3):
            on_samples: list[float] = []
            off_samples: list[float] = []
            for chunk in range(6):
                if chunk % 2 == 0:
                    SLO.enabled = True
                    on_samples.extend(probe_chunk())
                else:
                    SLO.enabled = False
                    off_samples.extend(probe_chunk())
            SLO.enabled = True

            def trimmed_p99(xs):
                xs = sorted(xs)[: max(1, int(len(xs) * 0.9))]
                return p99(xs) * 1000 if xs else 0.0

            on_ms, off_ms = trimmed_p99(on_samples), trimmed_p99(off_samples)
            pct = (
                100.0 * (on_ms - off_ms) / off_ms if off_ms > 0 else 0.0
            )
            attempts.append({
                "on_p99_ms": round(on_ms, 3),
                "off_p99_ms": round(off_ms, 3),
                "overhead_pct": round(pct, 2),
            })
            if pct <= budget_pct or (on_ms - off_ms) <= floor_ms:
                passed_budget = True
                break
        result["overhead_attempts"] = attempts
        result["slo_record_overhead_pct"] = attempts[-1]["overhead_pct"]
        if not passed_budget:
            failures.append(
                f"router hop p99 with the SLO plane on exceeded the "
                f"{budget_pct}% budget in every attempt: {attempts}"
            )

        # phase 2: injected latency fault → burn-rate breach --------------
        slow_port = _free_port()
        proc = spawn_faulty_replica(slow_port, delay_s=0.4, tmp=tmp)
        rs.add(Replica("slow-replica", "127.0.0.1", slow_port))
        # the healthy in-process replicas leave rotation: every journey
        # now pays the injected delay
        rs.drain("slo-rep-0", reason="slo drill")
        rs.drain("slo-rep-1", reason="slo drill")
        rs.refresh()
        breaches_before = SLO.breaches
        t_fault0 = time.perf_counter()
        for i in range(6):
            buf = _stream_once(
                router_port,
                [rng.randrange(64) for _ in range(6)], max_tokens=8,
            )
            if b"data: [DONE]" not in buf:
                failures.append(f"fault-phase stream {i} did not finish")
        posture = SLO.evaluate(force=True)
        breach_ms = (time.perf_counter() - t_fault0) * 1000
        result["slo_breach_detect_ms"] = round(breach_ms, 1)
        result["posture"] = posture
        if not posture["burning"] or SLO.breaches <= breaches_before:
            failures.append(
                f"injected latency fault did not trip the burn-rate "
                f"alert: {posture}; state={SLO.debug_state()['burn']}"
            )

        # phase 3: the breach's exemplar resolves across processes --------
        state = SLO.debug_state()
        # exemplars dict: class → {objective: [trace ids]}
        exemplars = []
        for _cls, by_obj in state["exemplars"].items():
            for _obj_key, ids in by_obj.items():
                exemplars.extend(ids)
        if not exemplars:
            failures.append("breach produced no exemplar trace ids")
        else:
            ex = exemplars[-1]
            t_asm0 = time.perf_counter()
            rec = assembler.assemble(ex)
            result["slo_assembly_ms"] = round(
                (time.perf_counter() - t_asm0) * 1000, 2
            )
            result["exemplar_trace"] = {
                "trace_id": ex,
                "spans": rec["span_count"],
                "processes": rec["processes"],
                "sources": rec["sources"],
            }
            names = [s["name"] for s in rec["spans"]]
            if rec["processes"] < 2:
                failures.append(
                    f"exemplar trace {ex} did not assemble spans from "
                    f">=2 processes: {rec['sources']} ({names})"
                )
            if "fleet.route" not in names:
                failures.append(
                    f"exemplar trace missing the router span: {names}"
                )
            if "serve.request" not in names:
                failures.append(
                    f"exemplar trace missing the replica span: {names}"
                )
            # causal order: the router span precedes its replica child
            if (
                "fleet.route" in names and "serve.request" in names
                and names.index("fleet.route")
                > names.index("serve.request")
            ):
                failures.append(
                    f"assembled spans not in causal order: {names}"
                )

        # phase 4: SLO-proactive autoscaler evaluation, journaled ---------
        scaler = Autoscaler(
            rs, executor=None,  # advisory: the DECISION is the contract
            policy=ScalingPolicy(
                min_replicas=1, max_replicas=4, hysteresis_rounds=1,
                up_cooldown_s=0.0,
            ),
            slo_provider=SLO.scaling_input,
        )
        decision = scaler.tick()
        result["autoscaler_decision"] = {
            "action": decision["action"],
            "reason": decision["reason"],
            "slo_burning": bool((decision.get("slo") or {}).get("burning")),
        }
        if not (decision.get("slo") or {}).get("burning"):
            failures.append(
                f"autoscaler evaluation did not see the SLO burn "
                f"posture: {decision}"
            )
        if decision["action"] != "up":
            failures.append(
                f"burning budget did not drive a scale-up decision "
                f"while the queue was idle: {decision}"
            )
        if "slo burn" not in decision["reason"]:
            failures.append(
                f"scale-up reason does not name the slo burn: "
                f"{decision['reason']}"
            )
    finally:
        try:
            router.stop()
        except Exception:
            pass
        for r in reps:
            r["server"].shutdown()
            r["loop"].stop()
        if proc is not None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        assembler.stop()
        SLO.stop_ticker()

    # phase 5: journal round trip ----------------------------------------
    if not JOURNAL.flush():
        failures.append("journal flush failed (write loss?)")
    JOURNAL.close()
    events = read_journal(journal_dir)
    slo_recs = [e for e in events if e.get("type") == "slo"]
    fleet_recs = [e for e in events if e.get("type") == "fleet"]
    result["journal_slo_records"] = len(slo_recs)
    result["journal_fleet_records"] = len(fleet_recs)
    if not any(r.get("action") == "breach" for r in slo_recs):
        failures.append("no slo breach record reached the journal")
    else:
        breach = next(
            r for r in slo_recs if r.get("action") == "breach"
        )
        if not breach.get("exemplars"):
            failures.append("journaled breach carries no exemplars")
    if not any(
        (r.get("slo") or {}).get("burning") for r in fleet_recs
    ):
        failures.append(
            "no journaled autoscaler evaluation carries the SLO input"
        )
    res = replay(events)
    if res.violations:
        failures.append(f"replay violations: {res.violations[:5]}")
    if res.slo_breaches < 1:
        failures.append("replay did not reconstruct the slo breach")
    elif not (res.last_slo_breach or {}).get("exemplars"):
        failures.append("replayed breach lost its exemplar trace ids")

    SLO.reset()
    shutil.rmtree(tmp, ignore_errors=True)
    result["failures"] = failures
    print(json.dumps(result))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
