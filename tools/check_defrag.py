#!/usr/bin/env python
"""CI gate for the mesh defragmentation planner (`make check-defrag`).

Runs a randomized bind/forget soak (journal on) until the mesh is
fragmented — every node's free-chip count below the gang member size,
fragmentation index above a floor — then HARD-FAILS when any of:

- the target gang is NOT unplaceable at that point (the soak failed to
  fragment; raise --ops or change the seed),
- an `auto` defrag round does not make the previously-unplaceable gang
  bindable end-to-end through the real filter→bind path,
- the mean per-node fragmentation index does not drop across the round,
- any migration is missing from the journal, or replaying the journal
  trips an invariant (incl. the new per-pod chip-count conservation
  check on `migrate` records) or diverges from live /scheduler/status,
- bind p99 with the planner attached in `off` mode regresses more than
  DEFRAG_OVERHEAD_BUDGET_PCT vs the planner detached (interleaved
  chunks pool per-mode samples so the box's throttling storms hit both
  modes equally; 3 attempts — noise passes one, a real regression
  fails all).

Usage:
    python tools/check_defrag.py [--ops N] [--skip-overhead]

Environment:
    CHECK_DEFRAG_SEED             soak RNG seed (default 20260803)
    CHECK_DEFRAG_FRAG_FLOOR       frag-index floor the soak must reach
                                  on some node (default 0.2)
    DEFRAG_OVERHEAD_BUDGET_PCT    bind p99 overhead budget (default 5)

Wired into the Makefile as `make check-defrag`, next to `check-journal`.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from elastic_gpu_scheduler_tpu.cli import build_stack  # noqa: E402
from elastic_gpu_scheduler_tpu.journal import JOURNAL, read_journal  # noqa: E402
from elastic_gpu_scheduler_tpu.journal.replay import (  # noqa: E402
    diff_live,
    replay,
)
from elastic_gpu_scheduler_tpu.k8s.client import FakeClientset  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.extender import (  # noqa: E402
    ExtenderArgs,
    ExtenderBindingArgs,
)
from elastic_gpu_scheduler_tpu.k8s.fake import FakeCluster  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.objects import (  # noqa: E402
    Container,
    ResourceRequirements,
    make_pod,
    make_tpu_node,
)
from elastic_gpu_scheduler_tpu.utils import consts  # noqa: E402

GANG_CHIPS = 4  # member size the fragmented mesh must block
GANG_MEMBERS = 2


def _pod(name, core=0, hbm=0, gang=None, gang_size=0):
    ann = {}
    if gang:
        ann[consts.ANNOTATION_GANG_NAME] = gang
        ann[consts.ANNOTATION_GANG_SIZE] = str(gang_size)
    res = {}
    if core:
        res[consts.RESOURCE_TPU_CORE] = core
    if hbm:
        res[consts.RESOURCE_TPU_HBM] = hbm
    return make_pod(
        name,
        containers=[
            Container(name="main", resources=ResourceRequirements(limits=res))
        ],
        annotations=ann,
    )


def _stack(defrag_mode="auto"):
    cluster = FakeCluster()
    for i in range(4):
        cluster.add_node(
            make_tpu_node(
                f"node-{i}", chips=8, hbm_gib=128, accelerator="v5e",
                slice_topology="2x4", host_topology="2x4",
                slice_name=f"s{i}",
            )
        )
    clientset = FakeClientset(cluster)
    registry, predicate, prioritize, bind, controller, status, gang = (
        build_stack(
            clientset, cluster=None, priority="ici-locality",
            gang_timeout=20.0, defrag_mode=defrag_mode,
            defrag_min_interval=0.0, defrag_threshold=0.1,
            defrag_max_moves=12,
        )
    )
    return cluster, registry, predicate, bind, status, gang


def _mean_frag(sched) -> float:
    snap = sched.frag_snapshot(max_age_s=0.0)
    if not snap:
        return 0.0
    return sum(v[0] for v in snap.values()) / len(snap)


def _soak_until_fragmented(ops, rng, frag_floor):
    """Randomized churn, then a deterministic top-up that leaves every
    node with exactly GANG_CHIPS-1 free chips (the gang-blocking shape)
    while the scattered churn residue keeps the free sets non-contiguous.
    Returns (cluster, registry, predicate, bind, status, gang, live)."""
    cluster, registry, predicate, bind, status, gang = _stack()
    sched = registry[consts.RESOURCE_TPU_CORE]
    nodes = [f"node-{i}" for i in range(4)]
    live: dict[str, object] = {}
    serial = 0

    def try_bind(pod, target=None):
        nonlocal serial
        cluster.create_pod(pod)
        filt = predicate.handle(ExtenderArgs(pod=pod, node_names=nodes))
        if filt.error or not filt.node_names:
            return False
        node = target if target in filt.node_names else rng.choice(
            filt.node_names
        )
        res = bind.handle(
            ExtenderBindingArgs(
                pod_name=pod.metadata.name,
                pod_namespace=pod.metadata.namespace,
                pod_uid=pod.metadata.uid,
                node=node,
            )
        )
        if res.error:
            return False
        live[pod.key] = pod
        return True

    for _op in range(ops):
        if live and rng.random() < 0.45:
            key = rng.choice(sorted(live))
            sched.forget_pod(live.pop(key), source="soak_delete")
            continue
        serial += 1
        core = rng.choice([100, 100, 100, 200])
        try_bind(_pod(f"soak-{serial}", core=core))

    # top-up: every node down to GANG_CHIPS-1 free (gang unplaceable),
    # freeing/taking singles as needed — still journaled churn
    for node in nodes:
        na = sched._get_allocator(node)
        while True:
            with na.lock:
                free = na.chips.free_count()
            if free <= GANG_CHIPS - 1:
                break
            serial += 1
            if not try_bind(_pod(f"top-{serial}", core=100), target=node):
                break
        while True:
            with na.lock:
                free = na.chips.free_count()
            if free >= GANG_CHIPS - 1:
                break
            on_node = [
                k for k, p in live.items()
                if sched.pod_maps.get(k, ("",))[0] == node
            ]
            if not on_node:
                break
            key = rng.choice(sorted(on_node))
            sched.forget_pod(live.pop(key), source="soak_topup")
    return cluster, registry, predicate, bind, status, gang, live


def _run_gang(cluster, predicate, bind, name) -> list:
    nodes = [f"node-{i}" for i in range(4)]
    pods = [
        _pod(f"{name}-{j}", core=GANG_CHIPS * 100, gang=name,
             gang_size=GANG_MEMBERS)
        for j in range(GANG_MEMBERS)
    ]
    results = [None] * GANG_MEMBERS

    def member(i, p):
        cluster.create_pod(p)
        filt = predicate.handle(ExtenderArgs(pod=p, node_names=nodes))
        if filt.error or not filt.node_names:
            results[i] = f"filter: {filt.error or filt.failed_nodes}"
            return
        res = bind.handle(
            ExtenderBindingArgs(
                pod_name=p.metadata.name,
                pod_namespace=p.metadata.namespace,
                pod_uid=p.metadata.uid,
                node=filt.node_names[0],
            )
        )
        results[i] = res.error or "ok"

    threads = [
        threading.Thread(target=member, args=(i, p))
        for i, p in enumerate(pods)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    return results


def _p99(xs):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(len(xs) * 0.99))] if xs else 0.0


def defrag_off_overhead() -> dict:
    """Filter→bind p99 with the planner attached in `off` mode vs
    detached entirely, interleaved in chunks (same storm-cancelling
    methodology as bench.journal_overhead_bench).  The timed op is the
    FULL scheduling cycle — filter verb then bind — because that is
    where off mode's entire residual cost lives (the `cordoned` truthy
    check in assume, the planner attribute check in the gang filter);
    a bare sched.bind contains no defrag code in either mode and would
    measure nothing."""
    cluster, registry, predicate, bind, status, gang = _stack(
        defrag_mode="off"
    )
    sched = registry[consts.RESOURCE_TPU_CORE]
    planner = gang.defrag
    lats = {True: [], False: []}
    serial = 0
    for chunk in range(40):
        attached = bool(chunk % 2)
        gang.defrag = planner if attached else None
        for _ in range(30):
            serial += 1
            pod = _pod(f"ov-{serial}", core=50, hbm=2)
            cluster.create_pod(pod)
            t0 = time.perf_counter()
            filt = predicate.handle(
                ExtenderArgs(pod=pod, node_names=["node-0"])
            )
            sched.bind(filt.node_names[0], pod)
            lats[attached].append(time.perf_counter() - t0)
            sched.forget_pod(pod)
            time.sleep(0.002)
    gang.defrag = planner
    off_ms = _p99(lats[False]) * 1000
    on_ms = _p99(lats[True]) * 1000
    # storm-trimmed variant (p99 of the best 90%, same estimator as
    # bench.journal_overhead_bench): the raw p99 of ~600 samples/mode on
    # a cgroup-throttled box swings ±50% on freeze storms alone — and
    # the off-mode path differs from detached by single attribute
    # checks, so any persistent raw-p99 gap here IS throttling, not code
    trim_off = sorted(lats[False])[: int(len(lats[False]) * 0.9)]
    trim_on = sorted(lats[True])[: int(len(lats[True]) * 0.9)]
    off_best = _p99(trim_off) * 1000
    on_best = _p99(trim_on) * 1000
    return {
        "bind_p99_defrag_detached_ms": round(off_ms, 3),
        "bind_p99_defrag_off_ms": round(on_ms, 3),
        "defrag_off_overhead_pct": round(
            (on_ms / off_ms - 1.0) * 100, 2
        ) if off_ms > 0 else 0.0,
        "defrag_off_overhead_trimmed_pct": round(
            (on_best / off_best - 1.0) * 100, 2
        ) if off_best > 0 else 0.0,
    }


def main() -> int:
    ops = 120
    skip_overhead = False
    args = sys.argv[1:]
    i = 0
    while i < len(args):
        if args[i].startswith("--ops="):
            ops = int(args[i].split("=", 1)[1])
        elif args[i] == "--ops" and i + 1 < len(args):
            i += 1
            ops = int(args[i])
        elif args[i] == "--skip-overhead":
            skip_overhead = True
        else:
            print(f"unknown argument {args[i]!r}", file=sys.stderr)
            return 2
        i += 1

    seed = int(os.environ.get("CHECK_DEFRAG_SEED", "20260803"))
    frag_floor = float(os.environ.get("CHECK_DEFRAG_FRAG_FLOOR", "0.2"))
    rng = random.Random(seed)
    tmp = tempfile.mkdtemp(prefix="tpu-defrag-check-")
    journal_dir = os.path.join(tmp, "journal")
    failures: list[str] = []
    result: dict = {"metric": "check_defrag", "seed": seed, "ops": ops}
    try:
        JOURNAL.configure(
            journal_dir, fsync="off", max_segment_bytes=32 * 1024
        )
        cluster, registry, predicate, bind, status, gang, live = (
            _soak_until_fragmented(ops, rng, frag_floor)
        )
        sched = registry[consts.RESOURCE_TPU_CORE]
        planner = gang.defrag

        frag_before = _mean_frag(sched)
        max_frag = max(
            v[0] for v in sched.frag_snapshot(max_age_s=0.0).values()
        )
        result["mean_frag_before"] = round(frag_before, 4)
        result["max_frag_before"] = round(max_frag, 4)
        if max_frag < frag_floor:
            failures.append(
                f"soak did not fragment the mesh (max frag index "
                f"{max_frag:.3f} < floor {frag_floor}; change the seed "
                "or raise --ops)"
            )
        probe = planner.plan(sched, want=(GANG_CHIPS, GANG_MEMBERS))
        result["gang_unplaceable_before"] = probe.feasible_before is False
        if probe.feasible_before:
            failures.append(
                "target gang was still placeable after the soak — the "
                "fragmentation scenario never materialized"
            )

        # THE acceptance path: the previously-unplaceable gang binds via
        # the auto planner's filter retry
        t0 = time.perf_counter()
        gang_results = _run_gang(cluster, predicate, bind, "defraggang")
        result["gang_results"] = gang_results
        result["gang_wall_ms"] = round((time.perf_counter() - t0) * 1000, 3)
        if gang_results != ["ok"] * GANG_MEMBERS:
            failures.append(
                f"defrag round did not make the gang bindable: "
                f"{gang_results}"
            )
        # compaction pass (budget permitting) then re-measure the index
        planner.run_round(sched=sched)
        frag_after = _mean_frag(sched)
        result["mean_frag_after"] = round(frag_after, 4)
        if frag_after >= frag_before:
            failures.append(
                f"mean fragmentation index did not drop "
                f"({frag_before:.4f} -> {frag_after:.4f})"
            )

        JOURNAL.flush()
        JOURNAL.close()
        events = read_journal(journal_dir)
        migrates = [e for e in events if e["type"] == "migrate"]
        result["records"] = len(events)
        result["migrations_journaled"] = len(migrates)
        moved = planner._moves_executed
        result["moves_executed"] = moved
        if len(migrates) < moved:
            failures.append(
                f"{moved} moves executed but only {len(migrates)} "
                "migrate records journaled — a migration escaped the "
                "flight recorder"
            )
        if not migrates:
            failures.append("no journaled migrations — defrag never ran")
        res = replay(events)
        if res.violations:
            failures.append(f"replay invariants tripped: {res.violations[:5]}")
        diffs = diff_live(res, status())
        if diffs:
            failures.append(f"replay diverges from live: {diffs[:5]}")
    finally:
        JOURNAL.close()
        shutil.rmtree(tmp, ignore_errors=True)

    if not skip_overhead:
        try:
            budget = float(
                os.environ.get("DEFRAG_OVERHEAD_BUDGET_PCT", "5")
            )
        except ValueError:
            budget = 5.0
        attempts = []
        overhead: dict = {}
        ok = False
        for _attempt in range(3):
            overhead = defrag_off_overhead()
            attempts.append(overhead["defrag_off_overhead_pct"])
            ok = (
                overhead["defrag_off_overhead_pct"] <= budget
                or overhead["defrag_off_overhead_trimmed_pct"] <= budget
            )
            if ok:
                break
        result.update(overhead)
        result["overhead_budget_pct"] = budget
        result["overhead_attempts_pct"] = attempts
        if not ok:
            failures.append(
                f"bind p99 with --defrag=off over budget on every "
                f"attempt ({attempts}% vs {budget}%)"
            )

    result["failures"] = failures
    print(json.dumps(result))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
