#!/usr/bin/env python
"""CI gate for the overlapped decode pipeline (`make check-serve-overlap`).

Runs a randomized request soak — mixed prompt lengths, greedy and
seeded-sampled requests, stop tokens, top-k/top-p filters, logprobs,
cancels, staggered arrivals — through the SAME engine twice (overlap off,
then on) and HARD-FAILS when:

- any request's token stream (or its logprobs) differs between modes
  (the bit-identical parity bar that makes overlap shippable),
- steady-state decode dispatches re-upload batch state (the
  transfer-count probe: `engine.device_uploads` must stay flat while the
  batch composition is unchanged), or
- the measured host gap between consecutive chunk dispatches does not
  shrink with overlap on (pooled over interleaved off/on rounds, the
  check_journal trick, so a cgroup-throttling storm hits both modes).

Runs on CPU (JAX_PLATFORMS=cpu recommended); on-chip numbers come from
`bench.py --tpu-section=serveoverlap`.

Usage:
    python tools/check_serve_overlap.py [--requests N] [--rounds N]

Environment:
    CHECK_OVERLAP_SEED   soak RNG seed (default 20260803)

Wired into the Makefile as `make check-serve-overlap`, next to
`check-plan-budget` and `check-journal`.
"""

from __future__ import annotations

import json
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build(overlap, params, cfg):
    from elastic_gpu_scheduler_tpu.models.serving import InferenceEngine

    return InferenceEngine(
        params, cfg, max_batch=4, max_len=96, page_size=16,
        fused_steps=4, overlap=overlap, prefix_cache=True,
    )


def _requests(rng, n, vocab):
    from elastic_gpu_scheduler_tpu.models.serving import Request

    out = []
    for i in range(n):
        plen = rng.randint(2, 24)
        prompt = [rng.randrange(1, vocab) for _ in range(plen)]
        kw = dict(prompt=prompt, max_new_tokens=rng.randint(4, 24))
        style = rng.random()
        if style < 0.35:
            pass  # greedy
        elif style < 0.7:
            kw.update(temperature=0.5 + rng.random(),
                      seed=rng.randrange(1 << 16))
            if rng.random() < 0.5:
                kw.update(top_k=rng.randint(4, 16),
                          top_p=0.85 + 0.1 * rng.random())
        else:
            kw.update(stop_tokens=(rng.randrange(1, vocab),))
        if rng.random() < 0.2:
            kw.update(logprobs=2)
        if rng.random() < 0.25:
            kw.update(priority=rng.choice([-1, 0, 5]))
        out.append(Request(**kw))
    return out


def _soak(overlap, seed, n_requests, params, cfg):
    """One soak round: returns (streams, mean host-gap ms, upload audit).

    The request mix, arrival order, and cancel points are all derived
    from ``seed`` so the off and on rounds see an identical workload."""
    rng = random.Random(seed)
    eng = _build(overlap, params, cfg)
    reqs = _requests(rng, n_requests, cfg.vocab_size)
    cancel_at = {
        i: rng.randint(2, 6) for i in range(n_requests) if rng.random() < 0.1
    }
    pending = list(enumerate(reqs))
    rng.shuffle(pending)
    submitted = []
    steps = 0
    upload_violations = 0
    prev_sig = None
    while pending or any(s is not None for s in eng.slots) or not eng.queue.empty():
        for _ in range(rng.randint(1, 3)):  # staggered arrivals
            if pending:
                k, r = pending.pop()
                eng.submit(r)
                submitted.append((k, r))
        eng._admit()
        # transfer-count probe: the device mirrors reflect the PREVIOUS
        # dispatch's inputs, so an upload at this step is legitimate iff
        # anything the dispatch consumes changed since then — tenants
        # (admission/release/spill), page tables (growth/scratch reset),
        # the stall/prefilling sets (the active mask), or host-dirtied
        # carry rows.  Two consecutive dispatches with identical
        # signatures and a climbing upload counter = a real regression.
        sig = (
            tuple(id(s) for s in eng.slots),
            eng.tables.tobytes(),
            eng.stalled.tobytes(),
            eng.prefilling.tobytes(),
            not eng._carry_dirty,
        )
        uploads_before = eng.device_uploads
        if any(s is not None for s in eng.slots):
            eng.step()
            steps += 1
            # ...and unchanged ACROSS the step too: _prepare_step grows
            # page tables (and releases/spills slots) inside step(), and
            # those mutations legitimately refresh the view at the very
            # dispatch they happen in
            post_sig = (
                tuple(id(s) for s in eng.slots),
                eng.tables.tobytes(),
                eng.stalled.tobytes(),
                eng.prefilling.tobytes(),
            )
            if (
                sig == prev_sig
                and sig[4]
                and post_sig == sig[:4]
                and steps > 2
                and eng.device_uploads != uploads_before
            ):
                upload_violations += 1
            prev_sig = sig
        for k, r in submitted:
            if k in cancel_at and len(r.output) >= cancel_at[k]:
                r.cancel()
                del cancel_at[k]
        if steps > 50_000:
            raise RuntimeError("soak did not converge")
    streams = []
    for k, r in sorted((k, r) for k, r in submitted):
        if r.error:
            raise RuntimeError(f"request {k} failed: {r.error}")
        streams.append(
            (k, list(r.output), list(r.token_logprobs), bool(r.cancelled))
        )
    gap = eng.host_gap_stats()
    return streams, gap["mean_ms"], gap["chunks"], upload_violations


def main() -> int:
    n_requests = 24
    rounds = 3
    args = sys.argv[1:]
    i = 0
    while i < len(args):
        if args[i].startswith("--requests="):
            n_requests = int(args[i].split("=", 1)[1])
        elif args[i].startswith("--rounds="):
            rounds = int(args[i].split("=", 1)[1])
        else:
            print(f"unknown argument {args[i]!r}", file=sys.stderr)
            return 2
        i += 1
    seed = int(os.environ.get("CHECK_OVERLAP_SEED", "20260803"))

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from elastic_gpu_scheduler_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        dtype="float32",
    )
    params = init_params(jax.random.key(0), cfg)

    failures = []
    off_gaps, on_gaps = [], []
    chunks = 0
    for r in range(rounds):
        # interleaved off/on rounds on the same workload: a throttling
        # storm spanning a round hits both modes' gap measurements
        off_streams, off_gap, off_chunks, _ = _soak(
            False, seed + r, n_requests, params, cfg
        )
        on_streams, on_gap, on_chunks, violations = _soak(
            True, seed + r, n_requests, params, cfg
        )
        off_gaps.append(off_gap)
        on_gaps.append(on_gap)
        chunks += off_chunks + on_chunks
        for (k, toks_off, lps_off, c_off), (k2, toks_on, lps_on, c_on) in zip(
            off_streams, on_streams
        ):
            assert k == k2
            if c_off or c_on:
                # a cancelled request's stream is timing-dependent in BOTH
                # modes (the cancel lands at a host-chosen step boundary);
                # parity bar: what WAS emitted agrees up to the shorter
                n = min(len(toks_off), len(toks_on))
                if toks_off[:n] != toks_on[:n]:
                    failures.append(
                        f"round {r} req {k}: cancelled-stream prefix "
                        f"mismatch {toks_off[:n]} vs {toks_on[:n]}"
                    )
                continue
            if toks_off != toks_on:
                failures.append(
                    f"round {r} req {k}: token stream mismatch "
                    f"{toks_off} vs {toks_on}"
                )
            elif lps_off != lps_on:
                failures.append(f"round {r} req {k}: logprob mismatch")
        if violations:
            failures.append(
                f"round {r}: {violations} steady-state decode steps "
                "re-uploaded batch state (transfer-count probe)"
            )
    # pooled gap comparison: min-of-rounds each side drops storms
    off_best, on_best = min(off_gaps), min(on_gaps)
    gap_ok = on_best < off_best
    if not gap_ok:
        failures.append(
            f"host gap did not shrink: overlap-on {on_best:.4f}ms vs "
            f"overlap-off {off_best:.4f}ms (min of {rounds} rounds)"
        )
    result = {
        "requests": n_requests * rounds,
        "decode_chunks": chunks,
        "serve_host_gap_ms": round(on_best, 4),
        "serve_host_gap_off_ms": round(off_best, 4),
        "gap_trials_on_ms": [round(g, 4) for g in on_gaps],
        "gap_trials_off_ms": [round(g, 4) for g in off_gaps],
        "parity": not any("mismatch" in f for f in failures),
        "ok": not failures,
    }
    print(json.dumps(result))
    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
