#!/usr/bin/env python
"""Sanitizer + differential-fuzz gate for the native placement kernels
(``make check-native-san``).

Two claims the ordinary test suite cannot make:

1. **Memory/UB safety**: placement.cc is hand-written CPython C API —
   refcount slips, OOB reads on the odometer walks, signed overflow on
   big meshes would all pass a parity test silently.  The gate rebuilds
   the extension with ``-fsanitize=address,undefined
   -fno-sanitize-recover=all`` (core/native.build_sanitized) and runs
   every fuzz iteration under it: any violation aborts the child
   process, which fails the gate.

2. **Differential parity at fuzz scale**: the curated parity tests in
   tests/test_native.py pin known shapes; this gate hammers randomized
   topologies / free-set partitions / gang specs and requires
   ``plan_gang``, ``plan_gang_batch`` and ``enumerate_free_boxes`` to
   be BIT-identical (order included) to their Python fallbacks on every
   iteration — the acceptance contract, under the sanitizer.

Env knobs: ``NATIVE_FUZZ_SEED`` (default 20260804),
``NATIVE_FUZZ_ITERS`` (default 120).  A failure prints the seed +
iteration + full inputs for offline reproduction.

Mechanics: the parent builds the sanitized .so, locates libasan
(``g++ -print-file-name=libasan.so``) and re-execs itself ``--child``
with ``LD_PRELOAD`` set — ASan must be the first runtime in a process
that dlopens instrumented code.  ``detect_leaks=0`` because the leak
checker would report CPython's own arenas, not the kernel's.
"""

from __future__ import annotations

import importlib.util
import os
import random
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SEED = int(os.environ.get("NATIVE_FUZZ_SEED", "20260804"))
ITERS = int(os.environ.get("NATIVE_FUZZ_ITERS", "120"))


def _load_san_module(so_path: str):
    # the init symbol is PyInit__placement regardless of the file name,
    # so the spec must use the C module's own name
    spec = importlib.util.spec_from_file_location("_placement", so_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _random_topo(rng):
    nd = rng.randint(1, 3)
    while True:
        dims = tuple(rng.randint(1, 6) for _ in range(nd))
        n = 1
        for d in dims:
            n *= d
        if n <= 256:
            break
    wrap = tuple(rng.random() < 0.5 for _ in range(nd))
    return dims, wrap


def _random_nodes(topo, rng, free_p=0.8):
    """Random host partition of the mesh.  Deliberately WIDER than
    tests/test_native.py's generator (k starts at 1: single-cell hosts
    are in-contract and worth fuzzing) — the distributions differ on
    purpose; the shared contract lives in reference_free_boxes and the
    fallback kernels, not in the generators."""
    cells = list(range(topo.num_chips))
    rng.shuffle(cells)
    nodes, i = [], 0
    while i < len(cells):
        k = rng.randint(1, 8)
        nodes.append(
            tuple(sorted(c for c in cells[i:i + k] if rng.random() < free_p))
        )
        i += k
    return nodes


def _fail(msg: str, **ctx):
    print(f"NATIVE-SAN PARITY FAILURE: {msg}", file=sys.stderr)
    for k, v in ctx.items():
        print(f"  {k} = {v!r}", file=sys.stderr)
    print(f"  repro: NATIVE_FUZZ_SEED={SEED} NATIVE_FUZZ_ITERS={ITERS} "
          "make check-native-san", file=sys.stderr)
    sys.exit(2)


def run_child() -> int:
    from elastic_gpu_scheduler_tpu.core.allocator import (
        plan_gang_batch_fallback,
        plan_gang_fallback,
    )
    from elastic_gpu_scheduler_tpu.core.native import build_sanitized
    from elastic_gpu_scheduler_tpu.core.topology import (
        Topology,
        reference_free_boxes,
    )

    so = build_sanitized()
    if so is None:
        print("sanitized build unavailable", file=sys.stderr)
        return 3
    native = _load_san_module(so)
    rng = random.Random(SEED)
    boxes_checked = plans_checked = batches_checked = 0
    for it in range(ITERS):
        dims, wrap = _random_topo(rng)
        topo = Topology(dims, wrap)
        nodes = _random_nodes(topo, rng)
        # edge shapes ride iteration 0 deterministically
        if it == 0:
            nodes = [(), tuple(range(topo.num_chips))] + nodes

        # enumerate_free_boxes parity on one random free mask
        free = {c for c in topo.coords() if rng.random() < 0.7}
        mask = bytearray(topo.num_chips)
        for c in free:
            mask[topo.index(c)] = 1
        for count in (1, 2, 4):
            for max_out in (1, 8, 64):
                nat = [
                    frozenset(topo.coord_of(i) for i in box)
                    for box in native.enumerate_free_boxes(
                        topo.dims, topo.wrap, bytes(mask), count, max_out
                    )
                ]
                py = reference_free_boxes(topo, free, count, max_out)
                if nat != py:
                    _fail("enumerate_free_boxes diverged",
                          iteration=it, dims=dims, wrap=wrap, count=count,
                          max_out=max_out, free=sorted(free))
                boxes_checked += 1

        # plan_gang parity
        for count in (1, 2, 4, 8):
            members = rng.randint(0, topo.num_chips // count + 2)
            max_c = rng.choice((1, 8, 64))
            nat = native.plan_gang(
                topo.dims, topo.wrap, nodes, count, members, max_c
            )
            py = plan_gang_fallback(topo, nodes, count, members, max_c)
            if nat != py:
                _fail("plan_gang diverged",
                      iteration=it, dims=dims, wrap=wrap, count=count,
                      members=members, max_candidates=max_c, nodes=nodes)
            plans_checked += 1

        # plan_gang_batch parity (a queue of specs, all-or-nothing each)
        specs = [
            (rng.choice((1, 2, 4, 8)), rng.randint(1, 6))
            for _ in range(rng.randint(0, 5))
        ]
        nat = native.plan_gang_batch(topo.dims, topo.wrap, nodes, specs, 64)
        py = plan_gang_batch_fallback(topo, nodes, specs, 64)
        if nat != py:
            _fail("plan_gang_batch diverged",
                  iteration=it, dims=dims, wrap=wrap, specs=specs,
                  nodes=nodes)
        batches_checked += 1
    print(
        f"native-san: {ITERS} iterations clean under ASan/UBSan — "
        f"{boxes_checked} enumerations, {plans_checked} plans, "
        f"{batches_checked} batch sweeps, all bit-identical to the "
        "Python fallback"
    )
    return 0


def main() -> int:
    if "--child" in sys.argv:
        return run_child()
    from elastic_gpu_scheduler_tpu.core.native import (
        build_sanitized,
        sanitizer_preload,
    )

    so = build_sanitized()
    if so is None:
        print("FAIL: could not build the sanitized extension (g++ with "
              "-fsanitize=address,undefined required)", file=sys.stderr)
        return 1
    preload = sanitizer_preload()
    env = dict(os.environ)
    if preload:
        env["LD_PRELOAD"] = preload
    env["ASAN_OPTIONS"] = env.get(
        "ASAN_OPTIONS", "detect_leaks=0:abort_on_error=1"
    )
    env["UBSAN_OPTIONS"] = env.get(
        "UBSAN_OPTIONS", "print_stacktrace=1:halt_on_error=1"
    )
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child"],
        env=env, timeout=1800,
    )
    if proc.returncode != 0:
        print(f"FAIL: sanitized differential fuzz exited "
              f"{proc.returncode} (parity break, sanitizer abort, or "
              "missing toolchain — see output above)", file=sys.stderr)
        return 1
    print("check-native-san OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
