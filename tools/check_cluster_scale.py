#!/usr/bin/env python
"""CI gate for the cluster-scale placement path (`make check-cluster-scale`).

Seeded 10k-node fleet soak (capacity index + batch admission sweep +
journal on).  HARD-FAILS when any of:

- **index/oracle divergence** — after the churn soak, any index entry
  differs from a fresh recomputation off live chip state
  (CapacityIndex.verify), any sampled filter/score verb answers
  differently with the index on vs the full-rescan oracle, or the batch
  sweep's plans are not placement-for-placement identical to the
  per-gang loop's;
- **journal/index drift** — replaying the journal trips a violation,
  diverges from live /scheduler/status, or the index rebuilt from the
  REPLAYED chip state (ReplayResult.index_snapshot) differs from the
  live index's snapshot;
- **bind-p99 budget breach** — the filter→score→bind cycle p99 over the
  full candidate list exceeds CLUSTER_BIND_BUDGET_MS (storm-trimmed
  p99-of-best-90% may save a throttled attempt; 3 attempts like
  check-defrag — noise passes one, a real regression fails all);
- **a batch sweep slower than the per-gang loop it replaces** (best of
  3 interleaved attempts each).

Usage:
    python tools/check_cluster_scale.py [--nodes N] [--cycles N]

Environment:
    CLUSTER_SCALE_NODES      fleet size (default 10000)
    CLUSTER_SCALE_SEED       RNG seed (default 20260804)
    CLUSTER_SCALE_CYCLES     measured schedule cycles/attempt (default 120)
    CLUSTER_BIND_BUDGET_MS   cycle-p99 budget (default 50, scaled by the
                             per-box CPU reference like check-plan-budget)

Wired into the Makefile as `make check-cluster-scale`, next to
`check-fleet`.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402  (plan_reference_trial_ms / calibrated budget)
from tools.fleetgen import make_fleet  # noqa: E402
from elastic_gpu_scheduler_tpu.cli import build_stack  # noqa: E402
from elastic_gpu_scheduler_tpu.core.request import (  # noqa: E402
    TPURequest,
    TPUUnit,
)
from elastic_gpu_scheduler_tpu.journal import (  # noqa: E402
    JOURNAL,
    read_journal,
)
from elastic_gpu_scheduler_tpu.journal.replay import (  # noqa: E402
    diff_live,
    replay,
)
from elastic_gpu_scheduler_tpu.k8s.client import FakeClientset  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.fake import FakeCluster  # noqa: E402
from elastic_gpu_scheduler_tpu.utils import consts  # noqa: E402

FAILURES: list[str] = []


def fail(msg: str) -> None:
    print(f"FAIL: {msg}")
    FAILURES.append(msg)


def note(msg: str) -> None:
    print(f"  {msg}")


def p99(xs):
    xs = sorted(xs)
    return xs[max(0, int(0.99 * len(xs)) - 1)] if xs else 0.0


def trimmed_p99(xs):
    xs = sorted(xs)
    return p99(xs[: max(1, int(len(xs) * 0.9))])


def gang_req(tag: str, members: int, chips: int) -> TPURequest:
    return TPURequest(
        pod_uid=f"chk-{tag}", pod_key=f"chk/{tag}",
        units=(TPUUnit(core=0, hbm=0, chip_count=chips),),
        container_names=("main",),
        gang_name=tag, gang_size=members,
    )


def main() -> int:
    nodes_n = int(os.environ.get("CLUSTER_SCALE_NODES", "10000"))
    seed = int(os.environ.get("CLUSTER_SCALE_SEED", "20260804"))
    cycles = int(os.environ.get("CLUSTER_SCALE_CYCLES", "120"))
    for a in sys.argv[1:]:
        if a.startswith("--nodes"):
            nodes_n = int(a.split("=", 1)[1])
        elif a.startswith("--cycles"):
            cycles = int(a.split("=", 1)[1])
    rng = random.Random(seed)

    shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
    jdir = tempfile.mkdtemp(prefix="check-cluster-", dir=shm)
    JOURNAL.configure(jdir, fsync="off")
    try:
        return run(nodes_n, seed, cycles, rng, jdir)
    finally:
        JOURNAL.close()
        shutil.rmtree(jdir, ignore_errors=True)


def run(nodes_n, seed, cycles, rng, jdir) -> int:
    print(f"== cluster-scale gate: {nodes_n} nodes, seed {seed} ==")
    cluster = FakeCluster()
    names = make_fleet(cluster, nodes=nodes_n, seed=seed)
    clientset = FakeClientset(cluster)
    registry, _pred, _prio, _bind, _ctl, _status, gang = build_stack(
        clientset, cluster=None, priority="binpack", gang_timeout=300.0,
    )
    sched = registry[consts.RESOURCE_TPU_CORE]
    t0 = time.perf_counter()
    sched.get_allocators(names)
    sched.index.fold()
    note(f"prewarm: {len(names)} allocators in "
         f"{(time.perf_counter() - t0) * 1000:.0f}ms")

    serial = [0]

    def mkpod(core):
        serial[0] += 1
        p = bench.tpu_pod(f"chk-{serial[0]}", core=core)
        cluster.create_pod(p)
        return p

    # -- churn soak: binds/forgets through the real verbs ------------------
    bound = []
    for n in rng.sample(names, int(len(names) * 0.5)):
        na = sched.allocators.get(n)
        chips = na.chips.num_chips if na is not None else 4
        p = mkpod(chips * 100)
        try:
            sched.bind(n, p)
            bound.append(p)
        except Exception as e:
            fail(f"load bind on {n}: {e}")
            break
    for _ in range(len(names) // 10):
        if bound and rng.random() < 0.4:
            sched.forget_pod(bound.pop(rng.randrange(len(bound))))
            continue
        p = mkpod(rng.choice((50, 100, 200)))
        ok, _failed = sched.assume(rng.sample(names, 512), p)
        if ok:
            try:
                sched.bind(ok[0], p)
                bound.append(p)
            except Exception:
                pass
    note(f"soak: {serial[0]} pods churned, {len(bound)} live")

    # -- 1. index/oracle divergence ----------------------------------------
    problems = sched.index.verify()
    if problems:
        for pr in problems[:10]:
            fail(f"index divergence: {pr}")
    else:
        note(f"index.verify clean over {len(names)} nodes")

    for trial in range(8):
        cand = rng.sample(names, 768)
        p = bench.tpu_pod(f"par-{trial}", core=rng.choice((50, 100, 400)))
        ok_i, failed_i = sched.assume(cand, p)
        scores_i = sched.score(cand, p)
        saved, sched.index = sched.index, None
        try:
            ok_o, failed_o = sched.assume(cand, p)
            scores_o = sched.score(cand, p)
        finally:
            sched.index = saved
        if ok_i != ok_o or set(failed_i) != set(failed_o):
            fail(
                f"filter parity: trial {trial}: index ok={len(ok_i)} "
                f"oracle ok={len(ok_o)} (diff "
                f"{set(ok_i) ^ set(ok_o) or set(failed_i) ^ set(failed_o)})"
            )
        if scores_i != scores_o:
            bad = [i for i, (a, b) in enumerate(zip(scores_i, scores_o))
                   if a != b]
            fail(f"score parity: trial {trial}: {len(bad)} nodes differ "
                 f"(first: {cand[bad[0]]})")
    if not FAILURES:
        note("filter/score parity: 8 sampled verbs identical index vs oracle")

    # -- 2. batch sweep vs per-gang loop -----------------------------------
    sweep_best = pergang_best = None
    for attempt in range(3):
        queue = [
            (f"chk/sw{attempt}-{i}",
             gang_req(f"sw{attempt}-{i}", rng.choice((8, 16, 32)), 4),
             list(names))
            for i in range(6)
        ]
        t0 = time.perf_counter()
        for gkey, req, cand in queue:
            planned = gang._plan(sched, req, cand)
            if planned is not None:
                planned.created = time.monotonic()
                planned.member_units = req.units
                planned.member_containers = req.container_names
                planned.slot_units = [req.units] * len(planned.slots)
                planned.slot_containers = (
                    [req.container_names] * len(planned.slots)
                )
                with gang._lock:
                    gang._plans[gkey] = planned
        pergang_ms = (time.perf_counter() - t0) * 1000
        with gang._lock:
            loop_slots = {k: list(p.slots) for k, p in gang._plans.items()}
            loop_opts = {
                k: [o.coords_by_container() for o in p.options]
                for k, p in gang._plans.items()
            }
            gang._plans.clear()
        t0 = time.perf_counter()
        swept = gang.plan_batch(sched, queue)
        sweep_ms = (time.perf_counter() - t0) * 1000
        sweep_slots = {
            k: list(p.slots) for k, p in swept.items() if p is not None
        }
        sweep_opts = {
            k: [o.coords_by_container() for o in p.options]
            for k, p in swept.items() if p is not None
        }
        with gang._lock:
            gang._plans.clear()
        if loop_slots != sweep_slots or loop_opts != sweep_opts:
            fail(
                f"sweep parity: attempt {attempt}: batch plans differ from "
                f"the per-gang loop (slots equal: "
                f"{loop_slots == sweep_slots})"
            )
        sweep_best = min(sweep_ms, sweep_best or sweep_ms)
        pergang_best = min(pergang_ms, pergang_best or pergang_ms)
    note(f"sweep {sweep_best:.0f}ms vs per-gang loop {pergang_best:.0f}ms "
         f"(best of 3)")
    if sweep_best > pergang_best:
        fail(
            f"batch sweep slower than the per-gang loop it replaces "
            f"({sweep_best:.0f}ms > {pergang_best:.0f}ms)"
        )

    # -- 3. bind-p99 budget (storm-trimmed, 3 attempts) --------------------
    base = float(os.environ.get("CLUSTER_BIND_BUDGET_MS", "50"))
    attempts = []
    passed = False
    for attempt in range(3):
        ref = [bench.plan_reference_trial_ms()]
        cycle_ms = []
        for i in range(cycles):
            if bound and rng.random() < 0.3:
                sched.forget_pod(bound.pop(rng.randrange(len(bound))))
            p = mkpod(100)
            t0 = time.perf_counter()
            ok, _failed = sched.assume(names, p)
            if not ok:
                continue
            scores = sched.score(ok[:256], p)
            best = ok[max(range(len(scores)), key=scores.__getitem__)]
            sched.bind(best, p)
            cycle_ms.append((time.perf_counter() - t0) * 1000)
            bound.append(p)
        ref.append(bench.plan_reference_trial_ms())
        budget, _refmin, scale = bench.calibrated_plan_budget(base, ref)
        raw = p99(cycle_ms)
        trimmed = trimmed_p99(cycle_ms)
        attempts.append(round(raw, 2))
        note(
            f"attempt {attempt}: bind p99 {raw:.1f}ms "
            f"(trimmed {trimmed:.1f}ms) vs budget {budget:.0f}ms "
            f"(scale {scale:.2f})"
        )
        if raw <= budget or trimmed <= budget:
            passed = True
            break
    if not passed:
        fail(
            f"cluster bind p99 over budget on every attempt "
            f"({attempts}ms vs {base}ms base)"
        )

    # -- 4. journal replay rebuilds the index ------------------------------
    JOURNAL.flush()
    events = read_journal(jdir)
    res = replay(events)
    if res.violations:
        for v in res.violations[:10]:
            fail(f"replay violation: {v}")
    live_status = sched.status()
    diffs = diff_live(res, live_status)
    if diffs:
        for d in diffs[:10]:
            fail(f"replay/live diff: {d}")
    sched.index.fold()
    live_idx = sched.index.snapshot()
    replayed_idx = res.index_snapshot()
    if replayed_idx != live_idx:
        bad = [
            n for n in set(live_idx) | set(replayed_idx)
            if live_idx.get(n) != replayed_idx.get(n)
        ]
        fail(
            f"replayed index != live index: {len(bad)} node(s) differ "
            f"(first: {bad[0]}: live={live_idx.get(bad[0])} "
            f"replayed={replayed_idx.get(bad[0])})"
        )
    else:
        note(
            f"journal replay: {res.records} records, index rebuilt "
            f"identical over {len(replayed_idx)} nodes"
        )

    print()
    summary = {
        "nodes": len(names),
        "index_stats": sched.index.stats(),
        "sweep_ms": round(sweep_best, 1),
        "pergang_ms": round(pergang_best, 1),
        "bind_p99_attempts_ms": attempts,
        "failures": len(FAILURES),
    }
    print(json.dumps(summary))
    if FAILURES:
        print(f"check-cluster-scale: {len(FAILURES)} failure(s)")
        return 1
    print("check-cluster-scale: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
