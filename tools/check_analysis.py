#!/usr/bin/env python
"""Invariant-analysis gate (``make check-analysis``).

Two halves, both must pass:

1. **Tree check** — run every analysis pass over the live package and
   diff against tools/analysis_baseline.json: any NEW finding, STALE
   baseline entry, or entry without a written justification fails.

2. **Injection self-test** — copy the package to a temp dir, inject one
   synthetic violation per core rule (a lock-order inversion, an
   unjournaled ``_set_slot`` caller, a journal record type with no
   replay handler, an off-lock global mutation, an unindexed /debug
   endpoint) and assert the analyzer flags EXACTLY those keys as new.
   This is the guard against the analyzer rotting into a no-op: a pass
   that silently stops seeing its violation class fails the gate even
   though the tree check stays green.
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from elastic_gpu_scheduler_tpu.analysis import (  # noqa: E402
    AnalysisConfig,
    default_ops_text,
    package_root,
    run_all,
)
from elastic_gpu_scheduler_tpu.analysis.baseline import (  # noqa: E402
    diff_baseline,
    load_baseline,
)

BASELINE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools", "analysis_baseline.json",
)

INJECTIONS = {
    # rule expected in the new findings → injected module source
    "lockdep-inversion": '''
from ..metrics import TimedLock

class _SynthInversion:
    def __init__(self):
        self._node_lk = TimedLock("synth-node", rank=30)
        self._gang_lk = TimedLock("synth-gang", rank=10)

    def bad(self):
        with self._node_lk:
            with self._gang_lk:
                return 1
''',
    "journal-setslot-outside-core": '''
def synth_unjournaled(cs):
    cs._set_slot(0, 0, 0)
    return cs
''',
    "journal-unhandled-type": '''
from ..journal import JOURNAL

def synth_emit():
    JOURNAL.record("synth_unreplayed_record")
''',
    "conformance-offlock-mutation": '''
_SYNTH_BUFFER: list = []

def synth_offlock(v):
    _SYNTH_BUFFER.append(v)
''',
}


def tree_check() -> int:
    cfg = AnalysisConfig(ops_text=default_ops_text())
    findings = run_all(package_root(), cfg)
    try:
        baseline = load_baseline(BASELINE)
    except ValueError as e:
        print(f"FAIL: invalid baseline: {e}", file=sys.stderr)
        return 1
    diff = diff_baseline(findings, baseline)
    for f in diff.new:
        print(f"NEW: {f.render()}", file=sys.stderr)
    for k in diff.stale:
        print(f"STALE: {k}", file=sys.stderr)
    for m in diff.invalid:
        print(f"INVALID: {m}", file=sys.stderr)
    if not diff.ok:
        print(
            f"FAIL: tree check — {len(diff.new)} new / {len(diff.stale)} "
            f"stale / {len(diff.invalid)} invalid", file=sys.stderr,
        )
        return 1
    print(f"tree check OK: {len(findings)} finding(s), all baselined with "
          "justification")
    return 0


def injection_check() -> int:
    cfg = AnalysisConfig(ops_text=default_ops_text())
    root = package_root()
    failures = 0
    with tempfile.TemporaryDirectory(prefix="analysis-inject-") as tmp:
        copy = os.path.join(tmp, "pkg")
        shutil.copytree(
            root, copy,
            ignore=shutil.ignore_patterns("__pycache__", "_native_build"),
        )
        clean = {f.key for f in run_all(copy, cfg)}
        for i, (rule, src) in enumerate(sorted(INJECTIONS.items())):
            path = os.path.join(copy, "core", f"_synth_{i}.py")
            with open(path, "w", encoding="utf-8") as fh:
                fh.write(src)
        # a served-but-unindexed /debug endpoint (string constant in the
        # routes module, absent from the index page)
        with open(os.path.join(copy, "server", "routes.py"), "a",
                  encoding="utf-8") as fh:
            fh.write('\n_SYNTH_ENDPOINT = "/debug/synthunlisted"\n')
        expected_rules = set(INJECTIONS) | {"conformance-debug-index"}
        new = [f for f in run_all(copy, cfg) if f.key not in clean]
        got_rules = {f.rule for f in new}
        for rule in sorted(expected_rules):
            if rule in got_rules:
                print(f"injection OK: {rule} flagged")
            else:
                print(f"FAIL: injected {rule} violation NOT flagged — the "
                      "pass went blind", file=sys.stderr)
                failures += 1
        # and the baseline must NOT be able to silently absorb them: a
        # diff against the real baseline reports them as new
        diff = diff_baseline(new, load_baseline(BASELINE))
        if len(diff.new) != len(new):
            print("FAIL: baseline absorbed injected findings", file=sys.stderr)
            failures += 1
    return 1 if failures else 0


def main() -> int:
    rc = tree_check()
    rc |= injection_check()
    print("check-analysis", "FAILED" if rc else "OK")
    return rc


if __name__ == "__main__":
    sys.exit(main())
