#!/usr/bin/env python
"""CI gate for the digital twin (`make check-twin`).

Records a seeded live soak (binds/forgets with workload classes, SLO
objectives + request journeys, profile EWMAs), runs the twin over the
recording, and HARD-FAILS on:

1. **Replay violations** — the twin journal must replay through the
   existing journal/replay.py invariant checks with ZERO violations
   (conservation included): simulated decisions obey the same physics
   as live ones.
2. **Nondeterminism** — two same-seed twin runs over the same recording
   must produce BYTE-IDENTICAL twin journals and identical SLO-burn
   scores.  Virtual time means there is nothing left to be flaky.
3. **Time-warp floor** — a >=30-sim-minute scenario must fold into
   wall time at >=CHECK_TWIN_MIN_SPEEDUP x (default 100).
4. **Model drift** — the fitted workload model's per-class tokens/s/chip
   must stay within CHECK_TWIN_DRIFT (default 0.20) of the recorded
   profile EWMAs, and the twin's SIMULATED effective throughput must
   stay within the same bound of the model it was given.
5. **Burn disagreement** — the twin's simulated SLO posture must agree
   with the live-recorded posture: same burning verdict, per-objective
   bad-request fraction within CHECK_TWIN_BURN_TOL (default 0.15).
6. **Gate dishonesty** — no autosearch candidate whose replay gate
   FAILED may be surfaced as promotable (ranked or beats-incumbent),
   and the seeded fixture must yield >=1 gate-passed candidate that
   strictly beats the incumbent binpack on a rater-neutral metric.

Usage:
    python tools/check_twin.py [--ops N]

Environment:
    CHECK_TWIN_SEED         soak + twin RNG seed (default 20260804)
    CHECK_TWIN_MIN_SPEEDUP  time-warp floor (default 100)
    CHECK_TWIN_DRIFT        model tokens/s drift bound (default 0.20)
    CHECK_TWIN_BURN_TOL     burn bad-frac agreement bound (default 0.15)
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import random
import shutil
import sys
import tempfile

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from elastic_gpu_scheduler_tpu.cli import build_stack  # noqa: E402
from elastic_gpu_scheduler_tpu.journal import (  # noqa: E402
    JOURNAL,
    read_journal,
    segment_paths,
)
from elastic_gpu_scheduler_tpu.k8s.client import FakeClientset  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.fake import FakeCluster  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.objects import (  # noqa: E402
    Container,
    ResourceRequirements,
    make_pod,
    make_tpu_node,
)
from elastic_gpu_scheduler_tpu.profile import PROFILER  # noqa: E402
from elastic_gpu_scheduler_tpu.slo import SLO  # noqa: E402
from elastic_gpu_scheduler_tpu.utils import consts  # noqa: E402

SEED = int(os.environ.get("CHECK_TWIN_SEED", "20260804"))
MIN_SPEEDUP = float(os.environ.get("CHECK_TWIN_MIN_SPEEDUP", "100"))
DRIFT_BOUND = float(os.environ.get("CHECK_TWIN_DRIFT", "0.20"))
BURN_TOL = float(os.environ.get("CHECK_TWIN_BURN_TOL", "0.15"))

SLO_SPEC = {
    "classes": {
        "serve": {"e2e_p95_ms": 2000.0, "availability": 0.99},
        "batch": {"e2e_p95_ms": 8000.0, "availability": 0.95},
    },
    "window_short_s": 60.0,
    "window_long_s": 300.0,
    "min_samples": 20,
    "default_class": "serve",
}


def _pod(name: str, core: int = 0, chips: int = 0, wclass: str = "serve"):
    res = {consts.RESOURCE_TPU_CORE: core or chips * 100}
    return make_pod(
        name,
        containers=[
            Container(
                name="main",
                resources=ResourceRequirements(limits=res),
            )
        ],
        annotations={consts.ANNOTATION_WORKLOAD_CLASS: wclass},
    )


def record_soak(journal_dir: str, seed: int, ops: int):
    """Seeded live soak on 4x4-mesh v5e nodes: the 12-chip/4-chip/
    fractional mix that makes the incumbent's compact-box preference
    CONTESTABLE (a 2x2-first placement can strand a later 12-chip pod
    non-contiguous where a row-first one does not) — the workload the
    autosearch yield gate needs.  Returns (events, live_slo_state,
    live_posture)."""
    JOURNAL.configure(journal_dir, fsync="off")
    SLO.load_config(SLO_SPEC)
    PROFILER.configure(sample=1.0)
    PROFILER.reset()
    cluster = FakeCluster()
    names = []
    for i in range(4):
        name = f"n{i}"
        names.append(name)
        cluster.add_node(
            make_tpu_node(
                name, chips=16, hbm_gib=256, accelerator="v5e",
                slice_topology="4x4",
            )
        )
    clientset = FakeClientset(cluster)
    registry, *_ = build_stack(clientset, cluster=None, priority="binpack")
    sched = registry[consts.RESOURCE_TPU_CORE]
    rng = random.Random(seed)
    live: list = []
    serial = 0
    for _ in range(ops):
        if live and rng.random() < 0.35:
            victim = live.pop(rng.randrange(len(live)))
            sched.forget_pod(victim, source="soak")
            continue
        serial += 1
        r = rng.random()
        if r < 0.2:
            pod = _pod(f"s-{serial}", chips=12, wclass="batch")
            chips = 12
        elif r < 0.55:
            pod = _pod(f"s-{serial}", chips=4, wclass="batch")
            chips = 4
        else:
            pod = _pod(f"s-{serial}", core=rng.choice((50, 100)),
                       wclass="serve")
            chips = 1
        cluster.create_pod(pod)
        ok, _failed = sched.assume(list(names), pod)
        if not ok:
            continue
        sched.bind(rng.choice(ok), pod)
        live.append(pod)
        wclass = pod.metadata.annotations[consts.ANNOTATION_WORKLOAD_CLASS]
        # per-bind serving telemetry: profile EWMAs (~900 tokens/s/chip,
        # the v5e default scale) and healthy request journeys well under
        # the objectives — the live posture the twin must reproduce
        for _step in range(3):
            PROFILER.record_step(
                tokens=9 * chips, wall_s=0.01, pod=pod.key,
                wclass=wclass, generation="v5e", chips=chips,
            )
        for j in range(3):
            SLO.record_journey(
                wclass=wclass,
                ok=rng.random() < 0.995,
                ttft_ms=rng.uniform(20.0, 80.0),
                tpot_ms=rng.uniform(5.0, 15.0),
                e2e_ms=rng.uniform(200.0, 900.0),
                queue_ms=rng.uniform(1.0, 10.0),
                hop_ms=rng.uniform(0.5, 2.0),
                tokens=64,
                trace_id=f"soak-{serial}-{j}",
            )
    for pod in live:
        sched.forget_pod(pod, source="drain")
    PROFILER.maybe_journal(force=True)
    SLO.evaluate(force=True)
    live_state = SLO.debug_state()
    live_posture = SLO.posture()
    JOURNAL.flush()
    JOURNAL.close()
    events = read_journal(journal_dir)
    return events, live_state, live_posture


def _journal_digest(dirpath: str) -> str:
    h = hashlib.sha256()
    for path in segment_paths(dirpath):
        with open(path, "rb") as f:
            h.update(f.read())
    return h.hexdigest()


def _bad_fracs(burn: dict) -> dict:
    """{"cls:key": bad_short/total_short} from either burn shape (the
    live plane's nested dict or the twin report's flattened one)."""
    out = {}
    for k, v in burn.items():
        if isinstance(v, dict) and "total_short" in v:
            total = v.get("total_short") or 0
            out[k] = (v.get("bad_short", 0) / total) if total else 0.0
        elif isinstance(v, dict):
            for key, rec in v.items():
                total = rec.get("total_short") or 0
                out[f"{k}:{key}"] = (
                    (rec.get("bad_short", 0) / total) if total else 0.0
                )
    return out


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--ops", type=int, default=200)
    args = parser.parse_args()

    failures: list[str] = []
    result: dict = {"check": "twin", "seed": SEED, "ops": args.ops}
    soak_dir = tempfile.mkdtemp(prefix="check-twin-soak-")
    twin_dirs = [
        tempfile.mkdtemp(prefix="check-twin-run-a-"),
        tempfile.mkdtemp(prefix="check-twin-run-b-"),
    ]
    try:
        events, live_state, live_posture = record_soak(
            soak_dir, SEED, args.ops
        )
        result["soak_records"] = len(events)

        from elastic_gpu_scheduler_tpu.twin import (
            TwinScenario,
            autosearch,
            fit_workload_model,
            run_scenario,
        )

        # ---- phase 1+2+3: two same-seed recorded twin runs ------------
        live_seq_before = JOURNAL.last_seq()
        reports = []
        for out_dir in twin_dirs:
            scenario = TwinScenario(
                name="check", mode="recorded", seed=SEED,
                duration_s=1800.0, out_dir=out_dir,
            )
            reports.append(run_scenario(
                scenario, events=events, slo_state=live_state,
            ))
        report = reports[0]
        result["sim_duration_s"] = report["sim_duration_s"]
        result["speedup_vs_wall"] = round(report["speedup_vs_wall"], 1)
        result["replay_violations"] = len(report["replay"]["violations"])
        result["twin_journal_records"] = report["replay"]["records"]
        for i, rep in enumerate(reports):
            if rep["replay"]["violations"]:
                failures.append(
                    f"run {i}: twin journal replay violations: "
                    f"{rep['replay']['violations'][:3]}"
                )
        if report["sim_duration_s"] < 1800.0:
            failures.append(
                f"scenario simulated only {report['sim_duration_s']}s "
                "(need >=1800)"
            )
        if report["speedup_vs_wall"] < MIN_SPEEDUP:
            failures.append(
                f"time-warp {report['speedup_vs_wall']:.1f}x below the "
                f"{MIN_SPEEDUP:.0f}x floor"
            )
        if JOURNAL.last_seq() != live_seq_before:
            failures.append(
                "twin run advanced the LIVE journal sequence "
                f"({live_seq_before} -> {JOURNAL.last_seq()})"
            )

        digests = [_journal_digest(d) for d in twin_dirs]
        result["journal_digest"] = digests[0][:16]
        if digests[0] != digests[1]:
            failures.append(
                "nondeterministic: same-seed twin journals differ "
                f"({digests[0][:12]} vs {digests[1][:12]})"
            )
        if reports[0]["slo"]["burn"] != reports[1]["slo"]["burn"]:
            failures.append(
                "nondeterministic: same-seed SLO-burn scores differ"
            )
        if reports[0]["packing"] != reports[1]["packing"]:
            failures.append(
                "nondeterministic: same-seed packing scores differ"
            )

        # ---- phase 4: model drift -------------------------------------
        model = fit_workload_model(events, slo_state=live_state)
        last_profile = None
        for rec in events:
            if rec.get("type") == "profile":
                last_profile = rec
        recorded_tput = (last_profile or {}).get("profiles") or {}
        drift_report = {}
        for wclass, cm in sorted(model.classes.items()):
            rec_tput = (recorded_tput.get(wclass) or {}).get("tput") or {}
            for gen, rec_v in sorted(rec_tput.items()):
                fit_v = cm.tokens_per_sec_per_chip.get(gen)
                if not rec_v or fit_v is None:
                    continue
                drift = abs(fit_v - rec_v) / rec_v
                drift_report[f"{wclass}:{gen}"] = round(drift, 4)
                if drift > DRIFT_BOUND:
                    failures.append(
                        f"fitted tokens/s for {wclass}/{gen} drifts "
                        f"{drift:.1%} from the recorded profile "
                        f"(bound {DRIFT_BOUND:.0%})"
                    )
        for wclass, d in sorted((report.get("model_drift") or {}).items()):
            drift = d.get("drift")
            if drift is None:
                continue
            drift_report[f"sim:{wclass}"] = round(drift, 4)
            if drift > DRIFT_BOUND:
                failures.append(
                    f"simulated throughput for {wclass} drifts "
                    f"{drift:.1%} from the fitted model "
                    f"(bound {DRIFT_BOUND:.0%})"
                )
        result["model_drift"] = drift_report

        # ---- phase 5: burn agreement ----------------------------------
        live_burning = bool(live_posture.get("burning"))
        twin_burning = bool(report["slo"]["posture"].get("burning"))
        result["live_burning"] = live_burning
        result["twin_burning"] = twin_burning
        if live_burning != twin_burning:
            failures.append(
                f"burn posture disagrees: live burning={live_burning}, "
                f"twin burning={twin_burning}"
            )
        live_bad = _bad_fracs(live_state.get("burn") or {})
        twin_bad = _bad_fracs(report["slo"].get("burn") or {})
        burn_compare = {}
        for key in sorted(set(live_bad) & set(twin_bad)):
            delta = abs(live_bad[key] - twin_bad[key])
            burn_compare[key] = {
                "live": round(live_bad[key], 4),
                "twin": round(twin_bad[key], 4),
                "delta": round(delta, 4),
            }
            if delta > BURN_TOL:
                failures.append(
                    f"burn disagreement on {key}: live bad-frac "
                    f"{live_bad[key]:.3f} vs twin {twin_bad[key]:.3f} "
                    f"(tolerance {BURN_TOL})"
                )
        result["burn_compare"] = burn_compare

        # ---- phase 6: autosearch honesty + yield ----------------------
        search = autosearch(events, seed=SEED, rounds=3, population=10)
        result["autosearch_evaluated"] = search["evaluated"]
        result["autosearch_beats"] = len(search["beats_incumbent"])
        rejected_sources = {
            r["source"] for r in search["rejected"]
        }
        for bucket in ("candidates", "beats_incumbent"):
            for row in search[bucket]:
                gate = row.get("gate")
                if gate is None or not gate.get("pass"):
                    failures.append(
                        f"autosearch surfaced a gate-rejected candidate "
                        f"in {bucket}: {row['source'][:80]}"
                    )
                if row["source"] in rejected_sources:
                    failures.append(
                        f"autosearch ranked a rejected candidate: "
                        f"{row['source'][:80]}"
                    )
        if not search["beats_incumbent"]:
            failures.append(
                "autosearch found no candidate beating the incumbent "
                "on rater-neutral metrics in the seeded fixture"
            )
        else:
            best = search["beats_incumbent"][0]
            result["autosearch_best"] = {
                "source": best["source"],
                "wins": best["wins"],
                "fitness": best["fitness"],
            }
    finally:
        SLO.reset()
        PROFILER.reset()
        PROFILER.configure(sample=0.0)
        JOURNAL.close()
        shutil.rmtree(soak_dir, ignore_errors=True)
        for d in twin_dirs:
            shutil.rmtree(d, ignore_errors=True)

    result["failures"] = failures
    result["ok"] = not failures
    print(json.dumps(result, sort_keys=True))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
