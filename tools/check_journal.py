#!/usr/bin/env python
"""CI gate for the scheduling flight recorder (`make check-journal`).

Runs a short randomized schedule/unschedule soak (fractional + whole-chip
pods + a gang commit) with the journal enabled, then HARD-FAILS when:

- replaying the journal does not reconstruct allocator state identical
  to the live `/scheduler/status` snapshot,
- any replay invariant trips (double-booked chip, capacity conservation,
  gang all-or-nothing),
- crash recovery misbehaves (a copy of the journal truncated mid-record
  must replay clean up to the tear), or
- the journaled bind p99 regresses more than the overhead budget vs
  journal-off (bench.journal_overhead_bench — one source of truth with
  the BENCH artifact keys).

Usage:
    python tools/check_journal.py [--ops N] [--skip-overhead]

Environment:
    CHECK_JOURNAL_SEED            soak RNG seed (default 20260803)
    JOURNAL_OVERHEAD_BUDGET_PCT   bind p99 overhead budget (default 5)

Wired into the Makefile as `make check-journal`, next to
`check-plan-budget`.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import sys
import tempfile
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from elastic_gpu_scheduler_tpu.cli import build_stack  # noqa: E402
from elastic_gpu_scheduler_tpu.journal import JOURNAL, read_journal  # noqa: E402
from elastic_gpu_scheduler_tpu.journal.replay import diff_live, replay  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.client import FakeClientset  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.extender import (  # noqa: E402
    ExtenderArgs,
    ExtenderBindingArgs,
)
from elastic_gpu_scheduler_tpu.k8s.fake import FakeCluster  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.objects import (  # noqa: E402
    Container,
    ResourceRequirements,
    make_pod,
    make_tpu_node,
)
from elastic_gpu_scheduler_tpu.utils import consts  # noqa: E402


def _pod(name, core=0, hbm=0, gang=None, gang_size=0):
    ann = {}
    if gang:
        ann[consts.ANNOTATION_GANG_NAME] = gang
        ann[consts.ANNOTATION_GANG_SIZE] = str(gang_size)
    res = {}
    if core:
        res[consts.RESOURCE_TPU_CORE] = core
    if hbm:
        res[consts.RESOURCE_TPU_HBM] = hbm
    return make_pod(
        name,
        containers=[
            Container(name="main", resources=ResourceRequirements(limits=res))
        ],
        annotations=ann,
    )


def _soak(ops: int, rng: random.Random):
    """Randomized schedule/unschedule churn + one gang, journal on.
    Returns (status_snapshot, live_pod_keys)."""
    cluster = FakeCluster()
    for i in range(4):
        cluster.add_node(
            make_tpu_node(f"plain-{i}", chips=4, hbm_gib=64, accelerator="v5e")
        )
    i = 0
    for x in range(0, 4, 2):
        for y in range(0, 4, 2):
            cluster.add_node(
                make_tpu_node(
                    f"mesh-{i}", chips=4, hbm_gib=64, accelerator="v5e",
                    slice_topology="4x4", host_topology="2x2",
                    host_offset=f"{x}.{y}", slice_name="v5e-16",
                )
            )
            i += 1
    clientset = FakeClientset(cluster)
    registry, predicate, prioritize, bind, controller, status, gang = (
        build_stack(clientset, cluster=None, priority="ici-locality",
                    gang_timeout=20.0)
    )
    sched = registry[consts.RESOURCE_TPU_CORE]
    nodes = [n.metadata.name for n in cluster.list_nodes()]

    live: dict[str, object] = {}
    serial = 0
    for _op in range(ops):
        if live and rng.random() < 0.35:
            key = rng.choice(sorted(live))
            sched.forget_pod(live.pop(key), source="soak_delete")
            continue
        serial += 1
        shape = rng.random()
        if shape < 0.4:
            pod = _pod(f"soak-{serial}", core=100)
        elif shape < 0.6:
            pod = _pod(f"soak-{serial}", core=200)
        else:
            pod = _pod(
                f"soak-{serial}",
                core=rng.randrange(10, 61),
                hbm=rng.randrange(1, 5),
            )
        cluster.create_pod(pod)
        filt = predicate.handle(ExtenderArgs(pod=pod, node_names=nodes))
        if filt.error or not filt.node_names:
            continue  # cluster full for this shape: fine, churn on
        target = rng.choice(filt.node_names)
        res = bind.handle(
            ExtenderBindingArgs(
                pod_name=pod.metadata.name,
                pod_namespace=pod.metadata.namespace,
                pod_uid=pod.metadata.uid,
                node=target,
            )
        )
        if not res.error:
            live[pod.key] = pod

    # drain most of the churn residue so the gang has room (the soak can
    # legitimately run the cluster full) — every forget is journaled too
    for key in sorted(live)[: max(0, len(live) - 3)]:
        sched.forget_pod(live.pop(key), source="soak_drain")

    # one gang through the barrier commit (all-or-nothing → journal admit)
    gang_pods = [
        _pod(f"gmember-{j}", core=200, gang="soakgang", gang_size=3)
        for j in range(3)
    ]
    errors = []

    def member(p):
        cluster.create_pod(p)
        filt = predicate.handle(ExtenderArgs(pod=p, node_names=nodes))
        if filt.error or not filt.node_names:
            errors.append(f"{p.key}: filter {filt.error or filt.failed_nodes}")
            return
        res = bind.handle(
            ExtenderBindingArgs(
                pod_name=p.metadata.name,
                pod_namespace=p.metadata.namespace,
                pod_uid=p.metadata.uid,
                node=filt.node_names[0],
            )
        )
        if res.error:
            errors.append(f"{p.key}: bind {res.error}")
        else:
            live[p.key] = p
    threads = [threading.Thread(target=member, args=(p,)) for p in gang_pods]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    if errors:
        raise RuntimeError(f"gang soak failed: {errors}")
    # and release one gang member afterwards (individual teardown is legal;
    # all-or-nothing is an ADMISSION property)
    sched.forget_pod(live.pop(gang_pods[0].key), source="soak_delete")
    # the REGISTRY rides along as a keep-alive: the journal's checkpoint
    # provider weak-refs the engine, and rotations triggered by the final
    # flush (after this function returns) must still find it alive
    return status(), sorted(live), registry


def _truncated_copy_recovers(journal_dir: str, all_events: list) -> str:
    """Crash-recovery drill on a COPY: tear the last record mid-line and
    assert replay recovers a clean prefix.  Returns '' or an error."""
    from elastic_gpu_scheduler_tpu.journal import read_segment, segment_paths

    copy = journal_dir.rstrip("/") + "-torn"
    shutil.copytree(journal_dir, copy)
    try:
        # tear the last RECORD-BEARING segment: rotation eagerly opens the
        # next segment, so the newest file can legitimately be empty
        segs = [p for p in segment_paths(copy) if os.path.getsize(p) >= 8]
        if not segs:
            return "no record-bearing segment to tear"
        last = segs[-1]
        size = os.path.getsize(last)
        with open(last, "r+b") as f:
            f.truncate(size - 7)
        recs, torn, _good = read_segment(last)
        if not torn:
            return "truncated segment did not read as torn"
        recovered = read_journal(copy)
        if len(recovered) != len(all_events) - 1:
            return (
                f"expected {len(all_events) - 1} recovered records, "
                f"got {len(recovered)}"
            )
        res = replay(recovered)
        if res.violations:
            return f"torn-prefix replay tripped invariants: {res.violations}"
        return ""
    finally:
        shutil.rmtree(copy, ignore_errors=True)


def _pruned_prefix_recovers(journal_dir: str, status: dict) -> str:
    """Prune drill on a COPY: drop the oldest segment; the next segment's
    head checkpoint must boot replay to a state matching live."""
    copy = journal_dir.rstrip("/") + "-pruned"
    shutil.copytree(journal_dir, copy)
    try:
        from elastic_gpu_scheduler_tpu.journal import segment_paths

        segs = segment_paths(copy)
        if len(segs) < 2:
            return "not enough segments to prune"
        os.unlink(segs[0])
        events = read_journal(copy)
        if not events:
            return "pruned journal recovered no records"
        if events[0].get("type") != "checkpoint":
            return (
                "pruned journal does not start with a segment-head "
                "checkpoint — long-lived journals would be unreplayable"
            )
        res = replay(events)
        if res.violations:
            return f"pruned replay tripped invariants: {res.violations[:5]}"
        diffs = diff_live(res, status)
        if diffs:
            return f"pruned replay diverges from live: {diffs[:5]}"
        return ""
    finally:
        shutil.rmtree(copy, ignore_errors=True)


def main() -> int:
    ops = 150
    skip_overhead = False
    args = sys.argv[1:]
    i = 0
    while i < len(args):
        if args[i].startswith("--ops="):
            ops = int(args[i].split("=", 1)[1])
        elif args[i] == "--ops" and i + 1 < len(args):
            i += 1
            ops = int(args[i])
        elif args[i] == "--skip-overhead":
            skip_overhead = True
        else:
            print(f"unknown argument {args[i]!r}", file=sys.stderr)
            return 2
        i += 1

    seed = int(os.environ.get("CHECK_JOURNAL_SEED", "20260803"))
    rng = random.Random(seed)
    tmp = tempfile.mkdtemp(prefix="tpu-journal-check-")
    journal_dir = os.path.join(tmp, "journal")
    failures: list[str] = []
    result: dict = {"metric": "check_journal", "seed": seed, "ops": ops}
    try:
        # small segments force rotation mid-soak; the replay must stitch
        # the stream back together across every boundary
        JOURNAL.configure(
            journal_dir, fsync="interval", max_segment_bytes=16 * 1024
        )
        status, live_pods, engines = _soak(ops, rng)
        JOURNAL.flush()
        JOURNAL.close()
        del engines  # engine may die only after the journal is closed

        events = read_journal(journal_dir)
        result["records"] = len(events)
        result["segments"] = len(
            [n for n in os.listdir(journal_dir) if n.startswith("journal-")]
        )
        res = replay(events)
        result["live_pods"] = len(res.pods)
        result["gangs"] = res.summary()["gangs"]
        result["warnings"] = res.warnings
        if res.violations:
            failures.append(f"invariants tripped: {res.violations}")
        diffs = diff_live(res, status)
        if diffs:
            failures.append(f"replay diverges from live snapshot: {diffs[:8]}")
        if not res.gangs or all(
            g["admits"] == 0 for g in res.gangs.values()
        ):
            failures.append("soak journaled no gang_admit record")
        if result["segments"] < 2:
            failures.append(
                "soak produced a single segment — rotation untested "
                "(raise --ops or lower max_segment_bytes)"
            )
        err = _truncated_copy_recovers(journal_dir, events)
        if err:
            failures.append(f"crash recovery: {err}")
        err = _pruned_prefix_recovers(journal_dir, status)
        if err:
            failures.append(f"prune recovery: {err}")
    finally:
        JOURNAL.close()
        shutil.rmtree(tmp, ignore_errors=True)

    if not skip_overhead:
        from bench import journal_overhead_bench

        try:
            budget = float(os.environ.get("JOURNAL_OVERHEAD_BUDGET_PCT", "5"))
        except ValueError:
            budget = 5.0
        # interleaved-chunk measurement cancels throttling storms, but the
        # residual run-to-run noise on this box's p99 is still ~±15%
        # against a 5% budget — so the gate RETRIES: random noise passes
        # within an attempt or two, a real regression fails all three
        # (bench.journal_overhead_bench documents the estimators)
        attempts = []
        for _attempt in range(3):
            overhead = journal_overhead_bench()
            attempts.append(overhead["journal_overhead_pct"])
            ok = (
                overhead["journal_overhead_pct"] <= budget
                or overhead["journal_overhead_trimmed_pct"] <= budget
            )
            if ok:
                break
        result.update(overhead)
        result["overhead_budget_pct"] = budget
        result["overhead_attempts_pct"] = attempts
        if not ok:
            failures.append(
                f"journaled bind p99 over budget on every attempt "
                f"({attempts}% vs {budget}%; trimmed "
                f"{overhead['journal_overhead_trimmed_pct']}%; on "
                f"{overhead['bind_p99_journal_on_ms']}ms, off "
                f"{overhead['bind_p99_journal_off_ms']}ms)"
            )

    result["failures"] = failures
    print(json.dumps(result))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
