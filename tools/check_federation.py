#!/usr/bin/env python
"""CI gate for the federated control plane (`make check-federation`).

Seeded multi-shard soak: a 3-shard federation (one SchedulerShard per
(region, generation, topology-class) key, each with its own journal
stream) routes a pod churn through the front door and admits
cross-shard gangs via two-phase admission while a deterministic fault
plan fires at the ``fed.prepare`` / ``fed.commit`` sites.  HARD-FAILS
when:

- any cross-shard gang admits partially (all-or-nothing broken): an
  injected phase-1 fault must leave ZERO members charged anywhere and
  a compensating ``fed_gang`` abort in every prepared shard's journal,
- a shard leader killed mid-commit (prepare sealed + decision=commit,
  death before its commit record) does not resolve FORWARD from the
  decision log on revive, or leaves any chip double-booked,
- any shard's journal replays with violations or a non-empty live
  diff, or the cross-shard conservation audit (federation/audit.py)
  reports disagreement / silent participants / unresolved prepares,
- the federated ``status_summary`` fold ever drifts from the direct sum of
  the shards' own summaries (aggregate capacity conservation), or
- the front-door route p99 exceeds CHECK_FED_ROUTE_BUDGET_MS
  (default 6.8 ms = 2x BENCH_r09's schedule_bind_p99_ms of 3.404 —
  the federation tier may at most double the single-scheduler bind).

Usage:
    python tools/check_federation.py

Environment:
    CHECK_FED_SEED             soak RNG seed (default 20260804)
    CHECK_FED_NODES            fleetgen nodes per shard (default 48)
    CHECK_FED_OPS              routed pods (default 120)
    CHECK_FED_GANGS            cross-shard gangs (default 12)
    CHECK_FED_ROUTE_BUDGET_MS  front-door route p99 ceiling (default 6.8)

Wired into the Makefile as `make check-federation`, next to check-twin.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from elastic_gpu_scheduler_tpu.faultinject import FAULTS  # noqa: E402
from elastic_gpu_scheduler_tpu.federation import (  # noqa: E402
    FederationFrontDoor,
    SchedulerShard,
)
from elastic_gpu_scheduler_tpu.federation.audit import (  # noqa: E402
    audit_federation,
)
from elastic_gpu_scheduler_tpu.journal import read_journal  # noqa: E402
from elastic_gpu_scheduler_tpu.journal.replay import (  # noqa: E402
    diff_live,
    replay,
)
from elastic_gpu_scheduler_tpu.k8s.client import FakeClientset  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.fake import FakeCluster  # noqa: E402
from elastic_gpu_scheduler_tpu.k8s.objects import (  # noqa: E402
    Container,
    ResourceRequirements,
    make_pod,
)
from elastic_gpu_scheduler_tpu.utils import consts  # noqa: E402
from tools.fleetgen import make_fleet  # noqa: E402

SEED = int(os.environ.get("CHECK_FED_SEED", "20260804"))
NODES = int(os.environ.get("CHECK_FED_NODES", "48"))
OPS = int(os.environ.get("CHECK_FED_OPS", "120"))
GANGS = int(os.environ.get("CHECK_FED_GANGS", "12"))
ROUTE_BUDGET_MS = float(os.environ.get("CHECK_FED_ROUTE_BUDGET_MS", "6.8"))

SHARD_IDS = ["eu/v6e/4x4", "us/v5e/4x4", "us/v5p/4x4x4"]


def _pod(name, core=0, gang=None, gang_size=0):
    ann = {}
    if gang:
        ann[consts.ANNOTATION_GANG_NAME] = gang
        ann[consts.ANNOTATION_GANG_SIZE] = str(gang_size)
    res = {consts.RESOURCE_TPU_CORE: core} if core else {}
    return make_pod(
        name,
        containers=[
            Container(name="main", resources=ResourceRequirements(limits=res))
        ],
        annotations=ann,
    )


def _build(tmp):
    fd = FederationFrontDoor()
    shards = {}
    for i, sid in enumerate(SHARD_IDS):
        cluster = FakeCluster()
        names = make_fleet(cluster, nodes=NODES, seed=SEED + i)
        sh = SchedulerShard(
            sid, FakeClientset(cluster),
            os.path.join(tmp, sid), node_names=names,
        )
        sh.cluster = cluster
        sh.warm()
        shards[sid] = sh
        fd.add_shard(sh)
    fd.refresh_summaries()
    return fd, shards


def _free_core(shards) -> int:
    return sum(
        sh.engine.status_summary()["capacity"]["core_avail"]
        for sh in shards.values()
    )


def _fold_drift(fd, shards) -> int:
    """Federated capacity fold vs the direct per-shard sum — zero or
    the aggregation layer is inventing/losing chips."""
    fd.refresh_summaries()
    folded = fd.federated_summary()["capacity"]["core_avail"]
    return folded - _free_core(shards)


def _fit_node(sh, pod, rng) -> str:
    """A node on this shard that can actually host the member (the
    front door's gang planner would run the same assume filter)."""
    fit, _errors = sh.engine.assume(sh.node_names, pod)
    if not fit:
        raise RuntimeError(f"shard {sh.shard_id}: no node fits {pod.key}")
    return rng.choice(fit)


def _p99(samples_ms: list) -> float:
    if not samples_ms:
        return 0.0
    s = sorted(samples_ms)
    return s[min(len(s) - 1, int(0.99 * len(s)))]


def main() -> int:
    failures: list = []
    result: dict = {
        "seed": SEED, "shards": len(SHARD_IDS),
        "nodes_per_shard": NODES, "ops": OPS, "gangs": GANGS,
    }
    rng = random.Random(SEED)
    tmp = tempfile.mkdtemp(prefix="check_fed_")
    try:
        fd, shards = _build(tmp)
        sids = sorted(shards)
        base_free = _free_core(shards)
        result["free_core_baseline"] = base_free
        charged = 0  # core we EXPECT to be held at any point

        # -- phase 1: routed pod churn, front-door latency ---------------
        route_ms = []
        for i in range(OPS):
            core = rng.choice([50, 100, 200])
            p = _pod(f"soak-{i}", core=core)
            for sh in shards.values():
                sh.cluster.create_pod(p)
            t0 = time.perf_counter()
            r = fd.route_pod(p)
            route_ms.append((time.perf_counter() - t0) * 1000.0)
            if not r["ok"]:
                failures.append(f"route {p.key} failed: {r['error']}")
                break
            charged += core
            if i % 40 == 0:
                drift = _fold_drift(fd, shards)
                if drift:
                    failures.append(
                        f"op {i}: federated capacity fold drifts "
                        f"{drift} core from sum of shards"
                    )
        result["fed_route_p99_ms"] = round(_p99(route_ms), 3)
        result["fed_route_budget_ms"] = ROUTE_BUDGET_MS
        if result["fed_route_p99_ms"] > ROUTE_BUDGET_MS:
            failures.append(
                f"front-door route p99 {result['fed_route_p99_ms']}ms "
                f"over budget {ROUTE_BUDGET_MS}ms (2x single-scheduler "
                "bind p99)"
            )

        # -- phase 2: cross-shard gangs, all-or-nothing under faults -----
        # every 3rd admission runs with an injected phase-1 fault on a
        # participating shard: the whole transaction must abort and
        # free EXACTLY what it reserved
        admitted = aborted = 0
        for g in range(GANGS):
            pair = rng.sample(sids, 2)
            gname = f"fg-{g}"
            members = []
            for j, sid in enumerate(sorted(pair)):
                sh = shards[sid]
                gp = _pod(f"{gname}-m{j}", core=100,
                          gang=gname, gang_size=2)
                sh.cluster.create_pod(gp)
                members.append((sid, _fit_node(sh, gp, rng), gp))
            inject = (g % 3 == 2)
            if inject:
                FAULTS.configure(
                    [{"site": "fed.prepare", "kind": "error",
                      "nth": 2, "count": 1}],
                    seed=SEED + g,
                )
            pre_free = _free_core(shards)
            res = fd.admit_gang(f"default/{gname}", members)
            if inject:
                FAULTS.clear()
                if res["ok"]:
                    failures.append(
                        f"gang {gname}: admitted through an injected "
                        "phase-1 fault"
                    )
                elif _free_core(shards) != pre_free:
                    failures.append(
                        f"gang {gname}: aborted but "
                        f"{pre_free - _free_core(shards)} core still "
                        "held — all-or-nothing broken"
                    )
                else:
                    aborted += 1
            elif not res["ok"]:
                failures.append(
                    f"gang {gname}: clean admission failed: "
                    f"{res.get('error')}"
                )
            else:
                admitted += 1
                charged += 200
        result["gangs_admitted"] = admitted
        result["gangs_aborted"] = aborted
        drift = _fold_drift(fd, shards)
        if drift:
            failures.append(
                f"post-gang federated fold drifts {drift} core"
            )
        if _free_core(shards) != base_free - charged:
            failures.append(
                f"capacity drift: free {_free_core(shards)} != baseline "
                f"{base_free} - charged {charged}"
            )

        # -- phase 3: shard-leader kill mid-commit -----------------------
        # prepare seals everywhere, decision=commit, the FIRST shard's
        # commit record faults; kill that leader, revive it against the
        # decision log — it must resolve FORWARD (members stay charged)
        pair = sorted(rng.sample(sids, 2))
        victim = pair[0]
        members = []
        for j, sid in enumerate(pair):
            sh = shards[sid]
            gp = _pod(f"kill-m{j}", core=100, gang="kill", gang_size=2)
            sh.cluster.create_pod(gp)
            members.append((sid, _fit_node(sh, gp, rng), gp))
        FAULTS.configure(
            [{"site": "fed.commit", "kind": "error", "nth": 1,
              "count": 1}],
            seed=SEED,
        )
        res = fd.admit_gang("default/kill", members)
        FAULTS.clear()
        if not (res["ok"] and res.get("unresolved") == [victim]):
            failures.append(
                f"mid-commit fault: expected commit with {victim} "
                f"unresolved, got {res}"
            )
        else:
            charged += 200
            shards[victim].kill()
            rec = shards[victim].revive(fd.decisions)
            if rec["committed"] != [res["txn"]]:
                failures.append(
                    f"revive resolved {rec}, expected forward-commit "
                    f"of {res['txn']}"
                )
            if _free_core(shards) != base_free - charged:
                failures.append(
                    f"post-revive drift: free {_free_core(shards)} != "
                    f"baseline {base_free} - charged {charged} "
                    "(double-book or lost charge)"
                )
        result["free_core_final"] = _free_core(shards)
        result["charged_core"] = charged

        # -- phase 4: every journal replays clean, cross-shard audit -----
        for sid in sids:
            sh = shards[sid]
            if not sh.JOURNAL.flush():
                failures.append(f"{sid}: journal flush failed")
                continue
            r = replay(read_journal(sh.journal_dir))
            if r.violations:
                failures.append(
                    f"{sid}: replay violations: {r.violations[:3]}"
                )
            d = diff_live(r, sh.engine.status())
            if d:
                failures.append(f"{sid}: live diff non-empty: {d[:3]}")
        audit = audit_federation(tmp)
        result["fed_gang_txns"] = len(audit["fed_gangs"])
        if audit["violations"]:
            failures.append(
                f"cross-shard audit: {audit['violations'][:3]}"
            )
    finally:
        FAULTS.clear()
        shutil.rmtree(tmp, ignore_errors=True)

    result["failures"] = failures
    print(json.dumps(result))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
