"""Opportunistic TPU validation: probe the relay all round, capture a green
artifact the moment it comes up.

Rounds 1-3 bet every on-chip number on the driver's end-of-round bench run,
and the relay was down at every round boundary (VERDICT r3 Missing #1).  This
script inverts the strategy: started at round BEGIN (``make tpu-validate`` or
``make tpu-validate-bg``), it probes the accelerator every PROBE_INTERVAL
seconds for up to DEADLINE hours.  Each attempt is appended to
``TPU_PROBE_LOG.jsonl`` (the committed proof-of-attempts the verdict asks
for).  On the first successful probe it runs every TPU bench section via the
shared ``bench.run_tpu_section`` runner, writes ``BENCH_TPU_validation.json``,
and commits both files.  Sections that fail are retried on later green
probes; the script exits once every section has produced real metrics (or
the deadline passes).

Reference slot: /root/reference/README.md:47-89 (the reference exists to run
live); SURVEY §6 (this repo's own measured numbers are the baseline).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "TPU_PROBE_LOG.jsonl")
ARTIFACT = os.path.join(REPO, "BENCH_TPU_validation.json")

sys.path.insert(0, REPO)
from bench import probe_tpu, run_tpu_section, tpu_section_table  # noqa: E402

SECTIONS = tpu_section_table()


def log_attempt(entry: dict) -> None:
    entry["ts"] = round(time.time(), 1)
    entry["iso"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(LOG, "a") as f:
        f.write(json.dumps(entry) + "\n")


def git_commit(paths: list[str], msg: str) -> bool:
    """Commit ONLY these paths from the background without racing the
    foreground session: ``git add`` tracks them, ``commit --only -- paths``
    never sweeps files the foreground may have staged concurrently.
    Retries through transient index.lock contention."""
    for _ in range(6):
        try:
            subprocess.run(["git", "add", "--", *paths], cwd=REPO,
                           capture_output=True, timeout=60)
            p = subprocess.run(
                ["git", "commit", "--only", "-m", msg, "--", *paths],
                cwd=REPO, capture_output=True, timeout=60,
            )
            blob = p.stdout + p.stderr
            if p.returncode == 0 or b"nothing" in blob:  # clean no-op is ok
                return True
        except Exception:
            pass
        time.sleep(10)
    return False


def main() -> int:
    interval = float(os.environ.get("TPU_PROBE_INTERVAL", "180"))
    deadline = time.time() + float(
        os.environ.get("TPU_PROBE_DEADLINE_H", "11")
    ) * 3600
    results: dict = {}
    done: set[str] = set()
    committed: set[str] = set()
    timeouts: dict[str, int] = {}  # section -> full-timeout count
    green_runs = 0
    n = 0
    def settled():
        """Every section green or given up on (2 full timeouts)."""
        return done | {
            s for s, c in timeouts.items() if c >= 2
        } == set(SECTIONS)

    while time.time() < deadline and not settled():
        n += 1
        up, detail = probe_tpu()
        log_attempt({"attempt": n, "up": up, "detail": detail})
        if not up:
            if "NOT_TPU:" in detail:
                # deterministic non-TPU backend (CPU-only box), not a relay
                # flake — retrying cannot change the answer
                break
            time.sleep(interval)
            continue
        # relay is up: run every not-yet-green section now, while it lasts
        green_runs += 1
        results["tpu_chip_kind_probe"] = detail
        for name, timeout in SECTIONS.items():
            if name in done:
                continue
            if timeouts.get(name, 0) >= 2:
                continue  # deterministically slow — rerunning wastes wall
            if name != next(iter(SECTIONS)):
                # cheap re-probe between sections: if the relay dropped
                # mid-window, don't burn the remaining sections' full
                # timeouts against a dead relay
                still_up, _d = probe_tpu(timeout=60)
                if not still_up:
                    log_attempt({"window": green_runs,
                                 "relay_dropped_mid_window": True})
                    break
            out = run_tpu_section(name, timeout)
            if out.pop(f"tpu_{name}_timed_out", None):
                timeouts[name] = timeouts.get(name, 0) + 1
            results.update(out)
            if f"tpu_{name}_error" not in out:
                done.add(name)
                results.pop(f"tpu_{name}_error", None)
            log_attempt({"section": name,
                         "ok": f"tpu_{name}_error" not in out})
        # commit only on PROGRESS (a new section went green) — an artifact
        # with zero green sections proves nothing, and re-committing an
        # unchanged one every probe interval would spam history
        if done and done != committed:
            results["validated_at"] = time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            )
            results["sections_green"] = sorted(done)
            with open(ARTIFACT, "w") as f:
                json.dump(results, f, indent=1, sort_keys=True)
                f.write("\n")
            if git_commit(
                [ARTIFACT, LOG],
                f"On-chip TPU validation artifact: {len(done)}/"
                f"{len(SECTIONS)} sections green ({', '.join(sorted(done))})",
            ):  # on failure leave `committed` stale so the next green
                # window retries the commit
                committed = set(done)
        if not settled():
            time.sleep(interval)
    # deadline or full success: commit the attempt log either way
    git_commit([LOG], f"TPU relay probe log: {n} attempts, "
                      f"{green_runs} green windows")
    return 0 if done == set(SECTIONS) else 1


if __name__ == "__main__":
    sys.exit(main())
