exec python tools/tpu_validate.py
