"""Workload launcher: from a bound pod's annotations to a running SPMD job.

The north-star end-to-end path (BASELINE.json): "a JAX/XLA workload
requesting ``tpu-chip: N`` is placed, bound, and launched" — this module is
the *launched* part.  Inside the pod, the launcher:

1. reads the scheduler's coordinate annotation for its container
   (``elasticgpu.io/container-<name>``, written at bind time) — or the
   device plugin's ``TPU_VISIBLE_CHIPS`` env, which carries the same
   coordinates on-node;
2. builds a ``jax.sharding.Mesh`` whose layout follows those ICI coordinates
   (parallel/mesh.py);
3. runs the training loop (models/train.py) with optional orbax
   checkpoint/resume (models/checkpoint.py).

The reference has no workload side at all (SURVEY §2 #19) — its pods are
launched by kubelet + the sibling GPU agent; the capability parity here is
that scheduler placement *translates into* the job's collective layout.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field
from typing import Optional

import jax

from .models.train import (
    init_sharded_state,
    make_jitted_train_step,
    make_optimizer,
)
from .models.transformer import TransformerConfig
from .parallel.mesh import MeshSpec, coords_from_annotations, mesh_from_allocation

log = logging.getLogger("tpu-launcher")


@dataclass
class JobSpec:
    model: TransformerConfig = field(default_factory=TransformerConfig)
    mesh: MeshSpec = field(default_factory=MeshSpec)
    steps: int = 10
    batch_size: int = 8
    seq_len: int = 128
    lr: float = 3e-4
    seed: int = 0
    checkpoint_dir: str = ""
    checkpoint_every: int = 0


def coords_for_container(
    annotations: Optional[dict[str, str]], container: str
) -> list:
    """Scheduler annotation first, device-plugin env as on-node fallback."""
    if annotations:
        coords = coords_from_annotations(annotations, container)
        if coords:
            return coords
    env = os.environ.get("TPU_VISIBLE_CHIPS", "")
    if env:
        from .core.topology import parse_coord

        return [parse_coord(p) for p in env.split(",") if p]
    return []


def run_job(
    spec: JobSpec,
    pod_annotations: Optional[dict[str, str]] = None,
    container: str = "main",
    devices=None,
) -> list[float]:
    """Train for spec.steps; returns per-step losses."""
    ann = dict(pod_annotations or {})
    coords = coords_for_container(ann, container)
    if coords:
        # rewrite into the annotation shape mesh_from_allocation expects
        from .utils import consts
        from .core.topology import format_coord

        ann[consts.ANNOTATION_CONTAINER_PREFIX + container] = ",".join(
            format_coord(c) for c in coords
        )
    mesh = mesh_from_allocation(ann, container, spec.mesh, devices=devices)
    log.info("mesh: %s over %d devices", spec.mesh.sizes, spec.mesh.num_devices)

    opt = make_optimizer(lr=spec.lr)
    params, opt_state = init_sharded_state(
        jax.random.key(spec.seed), spec.model, opt, mesh
    )
    step_fn = make_jitted_train_step(spec.model, opt, mesh)

    start_step = 0
    ckpt = None
    if spec.checkpoint_dir:
        from .models.checkpoint import CheckpointManager

        ckpt = CheckpointManager(spec.checkpoint_dir)
        restored = ckpt.restore(params, opt_state)
        if restored is not None:
            params, opt_state, start_step = restored
            log.info("resumed from step %d", start_step)

    losses = []
    key = jax.random.key(spec.seed + 1)
    for step in range(start_step, spec.steps):
        key, sub = jax.random.split(key)
        tokens = jax.random.randint(
            sub,
            (spec.batch_size, spec.seq_len + 1),
            0,
            spec.model.vocab_size,
        )
        params, opt_state, loss = step_fn(params, opt_state, tokens)
        losses.append(float(loss))
        if ckpt and spec.checkpoint_every and (step + 1) % spec.checkpoint_every == 0:
            ckpt.save(params, opt_state, step + 1)
    if ckpt and spec.checkpoint_every:
        ckpt.save(params, opt_state, spec.steps)
    return losses
