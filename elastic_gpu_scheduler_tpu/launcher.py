"""Workload launcher: from a bound pod's annotations to a running SPMD job.

The north-star end-to-end path (BASELINE.json): "a JAX/XLA workload
requesting ``tpu-chip: N`` is placed, bound, and launched" — this module is
the *launched* part.  Inside the pod, the launcher:

1. reads the scheduler's coordinate annotation for its container
   (``elasticgpu.io/container-<name>``, written at bind time) — or the
   device plugin's ``TPU_VISIBLE_CHIPS`` env, which carries the same
   coordinates on-node;
2. builds a ``jax.sharding.Mesh`` whose layout follows those ICI coordinates
   (parallel/mesh.py);
3. runs the training loop (models/train.py) with optional orbax
   checkpoint/resume (models/checkpoint.py).

The reference has no workload side at all (SURVEY §2 #19) — its pods are
launched by kubelet + the sibling GPU agent; the capability parity here is
that scheduler placement *translates into* the job's collective layout.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field
from typing import Optional

import jax

from .models.train import (
    init_sharded_state,
    make_jitted_train_step,
    make_optimizer,
)
from .models.transformer import TransformerConfig
from .parallel.mesh import (
    MeshSpec,
    coords_from_annotations,
    gang_slices_from_annotations,
    hierarchical_mesh,
    mesh_from_allocation,
)

log = logging.getLogger("tpu-launcher")


@dataclass
class JobSpec:
    model: TransformerConfig = field(default_factory=TransformerConfig)
    mesh: MeshSpec = field(default_factory=MeshSpec)
    steps: int = 10
    batch_size: int = 8  # global batch (split across processes when multi-host)
    seq_len: int = 128
    lr: float = 3e-4
    seed: int = 0
    checkpoint_dir: str = ""
    checkpoint_every: int = 0
    dataset_path: str = ""  # memmap token file; empty → synthetic motifs
    warmup_steps: int = 0
    grad_clip: float = 1.0


def coords_for_container(
    annotations: Optional[dict[str, str]], container: str
) -> list:
    """Scheduler annotation first, device-plugin env as on-node fallback."""
    if annotations:
        coords = coords_from_annotations(annotations, container)
        if coords:
            return coords
    env = os.environ.get("TPU_VISIBLE_CHIPS", "")
    if env:
        from .core.topology import parse_coord

        return [parse_coord(p) for p in env.split(",") if p]
    return []


def run_job(
    spec: JobSpec,
    pod_annotations: Optional[dict[str, str]] = None,
    container: str = "main",
    devices=None,
) -> list[float]:
    """Train for spec.steps; returns per-step losses."""
    from .parallel.distributed import maybe_initialize_distributed, process_info

    maybe_initialize_distributed()
    proc_idx, proc_count = process_info()

    ann = dict(pod_annotations or {})
    coords = coords_for_container(ann, container)
    if coords:
        # rewrite into the annotation shape mesh_from_allocation expects
        from .utils import consts
        from .core.topology import format_coord

        ann[consts.ANNOTATION_CONTAINER_PREFIX + container] = ",".join(
            format_coord(c) for c in coords
        )
    slices = gang_slices_from_annotations(ann)
    if len(slices) > 1 and spec.mesh.data % len(slices) == 0:
        # straddling gang (scheduler/gang.py wrote the DCN boundary):
        # hierarchical mesh — data axis spans slices over DCN, every
        # other axis stays inside one slice on ICI.  Same device
        # selection as the flat path: first num_devices of the given
        # (or all) devices — per-pod coords cover only THIS member's
        # chips, never the whole gang.
        devs = list(devices) if devices is not None else list(jax.devices())
        mesh = hierarchical_mesh(
            spec.mesh, len(slices), devices=devs[: spec.mesh.num_devices]
        )
        log.info(
            "hierarchical mesh: %s across %d slices (DCN on the data "
            "axis) over %d devices",
            spec.mesh.sizes, len(slices), spec.mesh.num_devices,
        )
    else:
        if len(slices) > 1:
            # a valid placement must still LAUNCH: a spec whose data axis
            # can't host the DCN boundary (e.g. pure-FSDP data=1) falls
            # back to the flat mesh — loudly, because its fsdp/tensor
            # collectives will ride DCN
            log.warning(
                "gang spans %d slices but mesh data axis %d is not "
                "divisible by the slice count; building a FLAT mesh — "
                "intra-slice collectives will cross the DCN boundary. "
                "Set MeshSpec(data=k*%d, ...) to get the hierarchical "
                "layout.",
                len(slices), spec.mesh.data, len(slices),
            )
        mesh = mesh_from_allocation(ann, container, spec.mesh, devices=devices)
        log.info(
            "mesh: %s over %d devices", spec.mesh.sizes, spec.mesh.num_devices
        )

    opt = make_optimizer(
        lr=spec.lr,
        warmup_steps=spec.warmup_steps,
        total_steps=spec.steps if spec.warmup_steps else 0,
        grad_clip=spec.grad_clip,
    )
    params, opt_state = init_sharded_state(
        jax.random.key(spec.seed), spec.model, opt, mesh
    )
    step_fn = make_jitted_train_step(spec.model, opt, mesh)

    from .models.data import MemmapTokenDataset, SyntheticTokenDataset, batches

    source = (
        MemmapTokenDataset(spec.dataset_path)
        if spec.dataset_path
        else SyntheticTokenDataset(spec.model.vocab_size, seed=spec.seed)
    )
    # batch_iter is created after the checkpoint restore below so a resumed
    # run fast-forwards the stream to start_step for free (per-index RNG)

    start_step = 0
    ckpt = None
    if spec.checkpoint_dir:
        from .models.checkpoint import CheckpointManager

        ckpt = CheckpointManager(spec.checkpoint_dir)
        restored = ckpt.restore(params, opt_state)
        if restored is not None:
            params, opt_state, start_step = restored
            log.info("resumed from step %d", start_step)

    # a resumed run continues the batch stream, not replays it
    batch_iter = batches(
        source,
        batch_size=spec.batch_size,
        seq_len=spec.seq_len,
        seed=spec.seed + 1,
        process_index=proc_idx,
        process_count=proc_count,
        start_batch=start_step,
    )

    losses = []
    for step in range(start_step, spec.steps):
        tokens = jax.numpy.asarray(next(batch_iter))
        params, opt_state, loss = step_fn(params, opt_state, tokens)
        losses.append(float(loss))
        if ckpt and spec.checkpoint_every and (step + 1) % spec.checkpoint_every == 0:
            ckpt.save(params, opt_state, step + 1)
    if ckpt and spec.checkpoint_every:
        # the job's FINAL save must be durable before the pod exits
        ckpt.save(params, opt_state, spec.steps, block=True)
    return losses


def main(argv=None) -> int:
    """In-pod entrypoint: ``python -m elastic_gpu_scheduler_tpu.launcher``.

    Reads the scheduler's allocation from the downward-API annotations file
    (``--annotations``; a k8s "metadata.annotations" fieldRef volume) or the
    device plugin's TPU_VISIBLE_CHIPS env, builds the mesh, trains."""
    import argparse
    import json as _json

    p = argparse.ArgumentParser("tpu-launcher")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--data", default="", help="memmap token file (else synthetic)")
    p.add_argument("--checkpoint-dir", default="")
    p.add_argument("--checkpoint-every", type=int, default=0)
    p.add_argument("--container", default="main")
    p.add_argument(
        "--mesh", default="",
        help="axis sizes, e.g. 'tensor=2,seq=2' (product must match devices)",
    )
    p.add_argument(
        "--annotations", default="",
        help="downward-API file with pod annotations (key=\"value\" lines)",
    )
    p.add_argument("--profile-dir", default="", help="write a jax profiler trace")
    p.add_argument(
        "--compile-cache", default=os.environ.get("JAX_COMPILE_CACHE", ""),
        help="persistent XLA compilation cache dir (fast pod restarts)",
    )
    p.add_argument(
        "--metrics-log", default="",
        help="append per-step {step, loss} JSONL records to this file",
    )
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if args.compile_cache:
        jax.config.update("jax_compilation_cache_dir", args.compile_cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    import sys as _sys

    from .parallel.mesh import AXES, MeshSpec

    spec_sizes = {a: 1 for a in AXES}
    if args.mesh:
        for part in args.mesh.split(","):
            k, _, v = part.partition("=")
            k = k.strip()
            if k not in spec_sizes:
                print(
                    f"error: unknown mesh axis {k!r}; choose from {list(AXES)}",
                    file=_sys.stderr,
                )
                return 2
            try:
                spec_sizes[k] = int(v)
            except ValueError:
                print(
                    f"error: mesh axis {k}={v!r} is not an integer",
                    file=_sys.stderr,
                )
                return 2
    n_dev = len(jax.devices())
    prod = 1
    for v in spec_sizes.values():
        prod *= v
    if prod != n_dev:  # absorb the remainder into data parallelism
        if prod > 0 and n_dev % prod == 0:
            spec_sizes["data"] *= n_dev // prod
        else:
            print(
                f"error: mesh product {prod} incompatible with {n_dev} devices",
                file=_sys.stderr,
            )
            return 2
    spec = MeshSpec(**spec_sizes)

    annotations = {}
    if args.annotations and os.path.exists(args.annotations):
        # downward-API format: one `key="value"` per line
        for line in open(args.annotations):
            line = line.strip()
            if not line or "=" not in line:
                continue
            k, _, v = line.partition("=")
            annotations[k] = _json.loads(v) if v.startswith('"') else v

    job = JobSpec(
        mesh=spec,
        steps=args.steps,
        batch_size=args.batch_size,
        seq_len=args.seq_len,
        lr=args.lr,
        dataset_path=args.data,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every,
    )
    if args.profile_dir:
        jax.profiler.start_trace(args.profile_dir)
    losses = run_job(job, pod_annotations=annotations, container=args.container)
    if args.profile_dir:
        jax.profiler.stop_trace()
        log.info("profiler trace written to %s", args.profile_dir)
    if args.metrics_log:
        with open(args.metrics_log, "a") as f:
            start = job.steps - len(losses)
            for i, loss in enumerate(losses):
                f.write(_json.dumps({"step": start + i, "loss": loss}) + "\n")
    if losses:
        print(f"trained {len(losses)} steps; final loss {losses[-1]:.4f}")
    else:
        print("no steps to run (already complete or --steps 0)")
    return 0


if __name__ == "__main__":  # pragma: no cover
    import sys as _sys

    _sys.exit(main())
