"""kube-scheduler extender v1 wire types.

JSON shapes of ``k8s.io/kube-scheduler/extender/v1`` — the protocol the stock
kube-scheduler speaks to an extender webhook (reference: pkg/routes/routes.go
(de)serializes these at 46-49, 94-99, 126-129; schema documented in the
reference README.md:47-89).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .objects import Pod


@dataclass
class ExtenderArgs:
    """filter / priorities request body."""

    pod: Pod
    node_names: Optional[list[str]] = None  # requires nodeCacheCapable=true

    def to_dict(self) -> dict:
        return {"Pod": self.pod.to_dict(), "NodeNames": self.node_names}

    @classmethod
    def from_dict(cls, d: dict) -> "ExtenderArgs":
        pod_d = d.get("Pod") or d.get("pod") or {}
        names = d.get("NodeNames", d.get("nodeNames"))
        return cls(pod=Pod.from_dict(pod_d), node_names=names)


@dataclass
class ExtenderFilterResult:
    node_names: Optional[list[str]] = None
    failed_nodes: dict[str, str] = field(default_factory=dict)
    error: str = ""

    def to_dict(self) -> dict:
        return {
            "NodeNames": self.node_names,
            "FailedNodes": dict(self.failed_nodes),
            "Error": self.error,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ExtenderFilterResult":
        return cls(
            node_names=d.get("NodeNames"),
            failed_nodes=dict(d.get("FailedNodes") or {}),
            error=d.get("Error", ""),
        )


@dataclass
class HostPriority:
    host: str
    score: int

    def to_dict(self) -> dict:
        return {"Host": self.host, "Score": self.score}

    @classmethod
    def from_dict(cls, d: dict) -> "HostPriority":
        return cls(host=d.get("Host", ""), score=int(d.get("Score", 0)))


@dataclass
class ExtenderBindingArgs:
    pod_name: str
    pod_namespace: str
    pod_uid: str
    node: str

    def to_dict(self) -> dict:
        return {
            "PodName": self.pod_name,
            "PodNamespace": self.pod_namespace,
            "PodUID": self.pod_uid,
            "Node": self.node,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ExtenderBindingArgs":
        return cls(
            pod_name=d.get("PodName", ""),
            pod_namespace=d.get("PodNamespace", "default"),
            pod_uid=d.get("PodUID", ""),
            node=d.get("Node", ""),
        )


@dataclass
class ExtenderBindingResult:
    error: str = ""

    def to_dict(self) -> dict:
        return {"Error": self.error}

    @classmethod
    def from_dict(cls, d: dict) -> "ExtenderBindingResult":
        return cls(error=d.get("Error", ""))
