"""kube-scheduler extender v1 wire types.

JSON shapes of ``k8s.io/kube-scheduler/extender/v1`` — the protocol the stock
kube-scheduler speaks to an extender webhook (reference: pkg/routes/routes.go
(de)serializes these at 46-49, 94-99, 126-129; schema documented in the
reference README.md:47-89).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .objects import Pod


@dataclass
class ExtenderArgs:
    """filter / priorities request body.

    ``traceparent`` is a wire extension (tracing/__init__.py): our own
    clients and tests can thread a W3C trace context through the verb
    body; kube-scheduler never sends the key and ``to_dict`` only emits
    it when set, so the reference wire shape is unchanged."""

    pod: Pod
    node_names: Optional[list[str]] = None  # requires nodeCacheCapable=true
    traceparent: str = ""

    def to_dict(self) -> dict:
        d = {"Pod": self.pod.to_dict(), "NodeNames": self.node_names}
        if self.traceparent:
            d["Traceparent"] = self.traceparent
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ExtenderArgs":
        pod_d = d.get("Pod") or d.get("pod") or {}
        names = d.get("NodeNames", d.get("nodeNames"))
        return cls(
            pod=Pod.from_dict(pod_d),
            node_names=names,
            traceparent=str(d.get("Traceparent", "") or ""),
        )


@dataclass
class ExtenderFilterResult:
    node_names: Optional[list[str]] = None
    failed_nodes: dict[str, str] = field(default_factory=dict)
    error: str = ""

    def to_dict(self) -> dict:
        return {
            "NodeNames": self.node_names,
            "FailedNodes": dict(self.failed_nodes),
            "Error": self.error,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ExtenderFilterResult":
        return cls(
            node_names=d.get("NodeNames"),
            failed_nodes=dict(d.get("FailedNodes") or {}),
            error=d.get("Error", ""),
        )


@dataclass
class HostPriority:
    host: str
    score: int

    def to_dict(self) -> dict:
        return {"Host": self.host, "Score": self.score}

    @classmethod
    def from_dict(cls, d: dict) -> "HostPriority":
        return cls(host=d.get("Host", ""), score=int(d.get("Score", 0)))


@dataclass
class ExtenderBindingArgs:
    pod_name: str
    pod_namespace: str
    pod_uid: str
    node: str
    # wire extension, emitted only when set (see ExtenderArgs.traceparent)
    traceparent: str = ""

    def to_dict(self) -> dict:
        d = {
            "PodName": self.pod_name,
            "PodNamespace": self.pod_namespace,
            "PodUID": self.pod_uid,
            "Node": self.node,
        }
        if self.traceparent:
            d["Traceparent"] = self.traceparent
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ExtenderBindingArgs":
        return cls(
            pod_name=d.get("PodName", ""),
            pod_namespace=d.get("PodNamespace", "default"),
            pod_uid=d.get("PodUID", ""),
            node=d.get("Node", ""),
            traceparent=str(d.get("Traceparent", "") or ""),
        )


@dataclass
class ExtenderBindingResult:
    error: str = ""

    def to_dict(self) -> dict:
        return {"Error": self.error}

    @classmethod
    def from_dict(cls, d: dict) -> "ExtenderBindingResult":
        return cls(error=d.get("Error", ""))


# -- preemption verb ---------------------------------------------------------
# k8s.io/kube-scheduler/extender/v1 ProcessPreemption types.  The reference
# never implements preemptVerb (its extender stanza has only filter/
# priorities/bind, README.md:47-89); this build does, so high-priority TPU
# jobs can evict lower-priority ones when the cluster is full.


@dataclass
class MetaPod:
    """Victim pod identified by UID only (nodeCacheCapable=true form)."""

    uid: str

    def to_dict(self) -> dict:
        return {"UID": self.uid}

    @classmethod
    def from_dict(cls, d: dict) -> "MetaPod":
        return cls(uid=d.get("UID", ""))


@dataclass
class MetaVictims:
    pods: list[MetaPod] = field(default_factory=list)
    num_pdb_violations: int = 0

    def to_dict(self) -> dict:
        return {
            "Pods": [p.to_dict() for p in self.pods],
            "NumPDBViolations": self.num_pdb_violations,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "MetaVictims":
        return cls(
            pods=[MetaPod.from_dict(p) for p in d.get("Pods") or []],
            num_pdb_violations=int(d.get("NumPDBViolations", 0)),
        )


@dataclass
class Victims:
    """Victim pods carried whole (nodeCacheCapable=false form)."""

    pods: list[Pod] = field(default_factory=list)
    num_pdb_violations: int = 0

    def to_dict(self) -> dict:
        return {
            "Pods": [p.to_dict() for p in self.pods],
            "NumPDBViolations": self.num_pdb_violations,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Victims":
        return cls(
            pods=[Pod.from_dict(p) for p in d.get("Pods") or []],
            num_pdb_violations=int(d.get("NumPDBViolations", 0)),
        )


@dataclass
class ExtenderPreemptionArgs:
    pod: Pod
    # kube-scheduler sends exactly one of these two maps depending on
    # nodeCacheCapable; we accept both.
    node_name_to_victims: dict[str, Victims] = field(default_factory=dict)
    node_name_to_meta_victims: dict[str, MetaVictims] = field(default_factory=dict)
    # wire extension, emitted only when set (see ExtenderArgs.traceparent)
    traceparent: str = ""

    def to_dict(self) -> dict:
        d: dict = {"Pod": self.pod.to_dict()}
        if self.traceparent:
            d["Traceparent"] = self.traceparent
        if self.node_name_to_victims:
            d["NodeNameToVictims"] = {
                n: v.to_dict() for n, v in self.node_name_to_victims.items()
            }
        if self.node_name_to_meta_victims:
            d["NodeNameToMetaVictims"] = {
                n: v.to_dict() for n, v in self.node_name_to_meta_victims.items()
            }
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ExtenderPreemptionArgs":
        pod_d = d.get("Pod") or d.get("pod") or {}
        return cls(
            pod=Pod.from_dict(pod_d),
            node_name_to_victims={
                n: Victims.from_dict(v)
                for n, v in (d.get("NodeNameToVictims") or {}).items()
            },
            node_name_to_meta_victims={
                n: MetaVictims.from_dict(v)
                for n, v in (d.get("NodeNameToMetaVictims") or {}).items()
            },
            traceparent=str(d.get("Traceparent", "") or ""),
        )


@dataclass
class ExtenderPreemptionResult:
    """Nodes that remain preemption candidates, with the (possibly reduced)
    victim set actually required on each.  Always keyed by UID — the
    kube-scheduler converts back from meta form itself."""

    node_name_to_meta_victims: dict[str, MetaVictims] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "NodeNameToMetaVictims": {
                n: v.to_dict() for n, v in self.node_name_to_meta_victims.items()
            }
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ExtenderPreemptionResult":
        return cls(
            node_name_to_meta_victims={
                n: MetaVictims.from_dict(v)
                for n, v in (d.get("NodeNameToMetaVictims") or {}).items()
            }
        )
