"""In-memory fake Kubernetes cluster (pods, nodes, bindings, watch).

Stands in for the API server in tests and benchmarks — the "kind cluster with
fake TPU nodes" pattern from BASELINE config 1 without needing kind: TPU nodes
are fake in the reference's benchmarks too, since capacity is just
``status.allocatable`` numbers (reference: pkg/scheduler/node.go:24-26).

Semantics modeled after the real API server where the scheduler depends on
them:

- ``resourceVersion`` bumps on every write; ``update_pod`` with a stale
  version fails with a Conflict — the optimistic-lock path the reference
  retries on (reference: pkg/scheduler/scheduler.go:199-213).
- ``bind`` sets ``spec.nodeName`` via the pods/binding subresource.
- watches deliver ADDED/MODIFIED/DELETED events to subscriber queues.
"""

from __future__ import annotations

import json
import queue
import threading
from typing import Callable, Iterator, Optional

from .objects import Binding, Node, Pod


class ApiError(Exception):
    def __init__(self, reason: str, message: str, code: int = 500):
        super().__init__(f"{reason}: {message}")
        self.reason = reason
        self.code = code
        self.message = message


def conflict(msg: str) -> ApiError:
    return ApiError("Conflict", msg, 409)


def not_found(msg: str) -> ApiError:
    return ApiError("NotFound", msg, 404)


def is_conflict(e: Exception) -> bool:
    return isinstance(e, ApiError) and e.reason == "Conflict"


def is_not_found(e: Exception) -> bool:
    return isinstance(e, ApiError) and e.reason == "NotFound"


class FakeCluster:
    def __init__(self):
        self._lock = threading.RLock()
        self._pods: dict[str, Pod] = {}  # ns/name → Pod
        self._nodes: dict[str, Node] = {}
        self._rv = 0
        self._watchers: list[queue.Queue] = []
        self.events: list[dict] = []  # recorded k8s Events (append-only)
        # coordination.k8s.io/Lease analogues: ns/name → lease dict with
        # metadata.resourceVersion enforcing optimistic concurrency — the
        # substrate for leader election (scheduler/leader.py)
        self._leases: dict[str, dict] = {}

    # -- internals -----------------------------------------------------------

    def _next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    def _notify(self, event: str, pod: Pod) -> None:
        for q in list(self._watchers):
            q.put((event, pod.clone()))

    # -- nodes ---------------------------------------------------------------

    def add_node(self, node: Node) -> None:
        with self._lock:
            node.metadata.resource_version = self._next_rv()
            self._nodes[node.metadata.name] = node.clone()

    def remove_node(self, name: str) -> None:
        """Decommission a node (it stops appearing in list_nodes; the
        controller's resync then prunes its allocator via the journaled
        remove_node path)."""
        with self._lock:
            self._nodes.pop(name, None)

    def get_node(self, name: str) -> Node:
        with self._lock:
            n = self._nodes.get(name)
            if n is None:
                raise not_found(f"node {name}")
            return n.clone()

    def list_nodes(self) -> list[Node]:
        with self._lock:
            return [n.clone() for n in self._nodes.values()]

    # -- pods ----------------------------------------------------------------

    def create_pod(self, pod: Pod) -> Pod:
        with self._lock:
            key = pod.key
            if key in self._pods:
                raise ApiError("AlreadyExists", f"pod {key}", 409)
            p = pod.clone()
            p.metadata.resource_version = self._next_rv()
            self._pods[key] = p
            self._notify("ADDED", p)
            return p.clone()

    def get_pod(self, namespace: str, name: str) -> Pod:
        with self._lock:
            p = self._pods.get(f"{namespace}/{name}")
            if p is None:
                raise not_found(f"pod {namespace}/{name}")
            return p.clone()

    def list_pods(
        self,
        label_selector: Optional[dict[str, str]] = None,
        field_selector: Optional[Callable[[Pod], bool]] = None,
        node_name: Optional[str] = None,
    ) -> list[Pod]:
        with self._lock:
            out = []
            for p in self._pods.values():
                if node_name and p.spec.node_name != node_name:
                    continue
                if label_selector and any(
                    (p.metadata.labels or {}).get(k) != v
                    for k, v in label_selector.items()
                ):
                    continue
                if field_selector and not field_selector(p):
                    continue
                out.append(p.clone())
            return out

    def update_pod(self, pod: Pod) -> Pod:
        with self._lock:
            key = pod.key
            cur = self._pods.get(key)
            if cur is None:
                raise not_found(f"pod {key}")
            if pod.metadata.resource_version != cur.metadata.resource_version:
                raise conflict(
                    f"pod {key}: resourceVersion {pod.metadata.resource_version} "
                    f"!= {cur.metadata.resource_version}"
                )
            p = pod.clone()
            p.metadata.resource_version = self._next_rv()
            self._pods[key] = p
            self._notify("MODIFIED", p)
            return p.clone()

    def delete_pod(self, namespace: str, name: str) -> None:
        with self._lock:
            key = f"{namespace}/{name}"
            p = self._pods.pop(key, None)
            if p is None:
                raise not_found(f"pod {key}")
            self._notify("DELETED", p)

    def bind(self, binding: Binding) -> None:
        with self._lock:
            key = f"{binding.pod_namespace}/{binding.pod_name}"
            cur = self._pods.get(key)
            if cur is None:
                raise not_found(f"pod {key}")
            if binding.pod_uid and cur.metadata.uid != binding.pod_uid:
                raise conflict(f"pod {key}: uid mismatch")
            if cur.spec.node_name and cur.spec.node_name != binding.node:
                raise conflict(f"pod {key}: already bound to {cur.spec.node_name}")
            cur.spec.node_name = binding.node
            cur.metadata.resource_version = self._next_rv()
            self._notify("MODIFIED", cur)

    def set_pod_phase(self, namespace: str, name: str, phase: str) -> None:
        with self._lock:
            key = f"{namespace}/{name}"
            cur = self._pods.get(key)
            if cur is None:
                raise not_found(f"pod {key}")
            cur.status.phase = phase
            cur.metadata.resource_version = self._next_rv()
            self._notify("MODIFIED", cur)

    # -- leases (coordination.k8s.io analogue) -------------------------------

    def get_lease(self, namespace: str, name: str) -> dict:
        with self._lock:
            lease = self._leases.get(f"{namespace}/{name}")
            if lease is None:
                raise not_found(f"lease {namespace}/{name}")
            return json.loads(json.dumps(lease))

    def create_lease(self, lease: dict) -> dict:
        md = lease.get("metadata") or {}
        key = f"{md.get('namespace', 'default')}/{md.get('name', '')}"
        with self._lock:
            if key in self._leases:
                raise ApiError("AlreadyExists", f"lease {key}", 409)
            lease = json.loads(json.dumps(lease))
            lease["metadata"]["resourceVersion"] = self._next_rv()
            self._leases[key] = lease
            return json.loads(json.dumps(lease))

    def update_lease(self, lease: dict) -> dict:
        md = lease.get("metadata") or {}
        key = f"{md.get('namespace', 'default')}/{md.get('name', '')}"
        with self._lock:
            cur = self._leases.get(key)
            if cur is None:
                raise not_found(f"lease {key}")
            if str(md.get("resourceVersion", "")) != str(
                cur["metadata"]["resourceVersion"]
            ):
                raise conflict(f"lease {key}: stale resourceVersion")
            lease = json.loads(json.dumps(lease))
            lease["metadata"]["resourceVersion"] = self._next_rv()
            self._leases[key] = lease
            return json.loads(json.dumps(lease))

    def create_event(self, event: dict) -> None:
        with self._lock:
            self.events.append(dict(event))

    # -- watch ---------------------------------------------------------------

    def watch_pods(self) -> queue.Queue:
        """Subscribe to pod events; returns the subscriber queue."""
        q: queue.Queue = queue.Queue()
        with self._lock:
            self._watchers.append(q)
        return q

    def stop_watch(self, q: queue.Queue) -> None:
        with self._lock:
            if q in self._watchers:
                self._watchers.remove(q)
