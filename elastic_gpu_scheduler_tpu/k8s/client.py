"""Clientset abstraction: fake (in-memory) and REST (real API server).

The reference builds a client-go clientset from in-cluster config or a
kubeconfig path (reference: pkg/utils/utils.go:44-68).  Here the scheduler
core is written against the small ``Clientset`` protocol below; tests and
benchmarks inject ``FakeClientset`` and a real deployment uses
``RestClientset`` (stdlib urllib against the API server, bearer-token auth —
no external kubernetes package in this environment).
"""

from __future__ import annotations

import json
import os
import ssl
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Optional

from ..faultinject import FAULTS
from ..utils.backoff import Backoff
from .fake import ApiError, FakeCluster
from .objects import Binding, Node, Pod


class Clientset:
    """The API surface the scheduler needs (reference usage:
    scheduler.go:66,70,200,214; controller.go:55)."""

    def get_pod(self, namespace: str, name: str) -> Pod:
        raise NotImplementedError

    def list_pods(
        self,
        label_selector: Optional[dict[str, str]] = None,
        field_selector: Optional[Callable[[Pod], bool]] = None,
        node_name: Optional[str] = None,  # server-side spec.nodeName filter
    ) -> list[Pod]:
        raise NotImplementedError

    def update_pod(self, pod: Pod) -> Pod:
        raise NotImplementedError

    def bind(self, binding: Binding) -> None:
        raise NotImplementedError

    def get_node(self, name: str) -> Node:
        raise NotImplementedError

    def list_nodes(self) -> list[Node]:
        raise NotImplementedError

    def create_event(self, event: dict) -> None:
        """Record a k8s Event.  The reference creates an event broadcaster but
        never records anything (controller.go:57-60, SURVEY §5 quirk); here
        scheduling outcomes are actually recorded."""
        raise NotImplementedError

    # -- leases (coordination.k8s.io; leader election) -----------------------

    def get_lease(self, namespace: str, name: str) -> dict:
        raise NotImplementedError

    def create_lease(self, lease: dict) -> dict:
        raise NotImplementedError

    def update_lease(self, lease: dict) -> dict:
        raise NotImplementedError


class FakeClientset(Clientset):
    def __init__(self, cluster: FakeCluster):
        self.cluster = cluster

    def get_pod(self, namespace, name):
        return self.cluster.get_pod(namespace, name)

    def list_pods(self, label_selector=None, field_selector=None, node_name=None):
        # fault sites on the verbs chaos drills exercise (ledger reads,
        # annotation writes, Binding POSTs) — the in-memory fake is what
        # the deterministic soak (tools/check_ha.py) schedules against,
        # so the injection must live here too, not only on the REST path
        if FAULTS.enabled:
            FAULTS.maybe_fire("k8s.list_pods")
        return self.cluster.list_pods(label_selector, field_selector, node_name)

    def update_pod(self, pod):
        if FAULTS.enabled:
            FAULTS.maybe_fire("k8s.update_pod")
        return self.cluster.update_pod(pod)

    def bind(self, binding):
        if FAULTS.enabled:
            FAULTS.maybe_fire("k8s.bind")
        return self.cluster.bind(binding)

    def get_node(self, name):
        return self.cluster.get_node(name)

    def list_nodes(self):
        return self.cluster.list_nodes()

    def create_event(self, event):
        return self.cluster.create_event(event)

    def get_lease(self, namespace, name):
        return self.cluster.get_lease(namespace, name)

    def create_lease(self, lease):
        return self.cluster.create_lease(lease)

    def update_lease(self, lease):
        return self.cluster.update_lease(lease)


class RestClientset(Clientset):
    """Minimal REST client for a real API server.

    In-cluster config discovery mirrors client-go: the service-account token
    and CA at /var/run/secrets/kubernetes.io/serviceaccount, API host from
    KUBERNETES_SERVICE_HOST/PORT (reference: utils.go:46-56 uses
    rest.InClusterConfig).  Out-of-cluster, pass ``base_url`` + ``token``.
    """

    SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

    def __init__(
        self,
        base_url: str = "",
        token: str = "",
        ca_file: str = "",
        insecure: bool = False,
    ):
        if not base_url:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "not in-cluster and no base_url given for RestClientset"
                )
            base_url = f"https://{host}:{port}"
            token_path = os.path.join(self.SA_DIR, "token")
            if not token and os.path.exists(token_path):
                with open(token_path) as f:
                    token = f.read().strip()
            ca = os.path.join(self.SA_DIR, "ca.crt")
            if not ca_file and os.path.exists(ca):
                ca_file = ca
        self.base_url = base_url.rstrip("/")
        self.token = token
        if insecure:
            self.ctx = ssl._create_unverified_context()
        elif ca_file:
            self.ctx = ssl.create_default_context(cafile=ca_file)
        else:
            self.ctx = ssl.create_default_context()

    def prepare(
        self, path: str, method: str = "GET", body: Optional[dict] = None
    ) -> tuple[urllib.request.Request, Optional[ssl.SSLContext]]:
        """Build an authenticated request + TLS context for an API path
        (shared by unary calls and the streaming watch)."""
        url = self.base_url + path
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        ctx = self.ctx if url.startswith("https") else None
        return req, ctx

    def _req(self, method: str, path: str, body: Optional[dict] = None) -> dict:
        if FAULTS.enabled:
            FAULTS.maybe_fire("k8s.request")
        req, ctx = self.prepare(path, method, body)
        try:
            with urllib.request.urlopen(req, context=ctx, timeout=30) as resp:
                payload = resp.read()
                return json.loads(payload) if payload else {}
        except urllib.error.HTTPError as e:
            try:
                status = json.loads(e.read())
                reason = status.get("reason", "Unknown")
                msg = status.get("message", str(e))
            except Exception:
                reason, msg = "Unknown", str(e)
            raise ApiError(reason, msg, e.code) from None

    def get_pod(self, namespace, name):
        return Pod.from_dict(
            self._req("GET", f"/api/v1/namespaces/{namespace}/pods/{name}")
        )

    def list_pods(self, label_selector=None, field_selector=None, node_name=None):
        path = "/api/v1/pods"
        params = []
        if label_selector:
            sel = ",".join(f"{k}={v}" for k, v in label_selector.items())
            params.append("labelSelector=" + urllib.parse.quote(sel))
        if node_name:
            # server-side field selector: only this node's pods cross the wire
            params.append(
                "fieldSelector=" + urllib.parse.quote(f"spec.nodeName={node_name}")
            )
        if params:
            path += "?" + "&".join(params)
        items = self._req("GET", path).get("items", [])
        pods = [Pod.from_dict(i) for i in items]
        if node_name:
            # re-filter client-side too: correct even against servers that
            # ignore unknown query params (e.g. the test mini apiserver)
            pods = [p for p in pods if p.spec.node_name == node_name]
        if field_selector:
            pods = [p for p in pods if field_selector(p)]
        return pods

    def update_pod(self, pod):
        return Pod.from_dict(
            self._req(
                "PUT",
                f"/api/v1/namespaces/{pod.metadata.namespace}/pods/"
                f"{pod.metadata.name}",
                pod.to_dict(),
            )
        )

    def bind(self, binding):
        self._req(
            "POST",
            f"/api/v1/namespaces/{binding.pod_namespace}/pods/"
            f"{binding.pod_name}/binding",
            binding.to_dict(),
        )

    def get_node(self, name):
        return Node.from_dict(self._req("GET", f"/api/v1/nodes/{name}"))

    def list_nodes(self):
        items = self._req("GET", "/api/v1/nodes").get("items", [])
        return [Node.from_dict(i) for i in items]

    def create_event(self, event):
        ns = (event.get("involvedObject") or {}).get("namespace", "default")
        self._req("POST", f"/api/v1/namespaces/{ns}/events", event)

    _LEASE_BASE = "/apis/coordination.k8s.io/v1/namespaces"

    def get_lease(self, namespace, name):
        return self._req("GET", f"{self._LEASE_BASE}/{namespace}/leases/{name}")

    def create_lease(self, lease):
        md = lease.get("metadata") or {}
        ns = md.get("namespace", "default")
        return self._req("POST", f"{self._LEASE_BASE}/{ns}/leases", lease)

    def update_lease(self, lease):
        md = lease.get("metadata") or {}
        ns = md.get("namespace", "default")
        return self._req(
            "PUT", f"{self._LEASE_BASE}/{ns}/leases/{md.get('name', '')}", lease
        )


class RestClusterView:
    """Controller-facing view of a real API server: the same
    watch_pods/stop_watch/list_pods/get_pod surface FakeCluster provides,
    backed by RestClientset with a streaming watch
    (GET /api/v1/pods?watch=true), so controller/controller.py runs unchanged
    against either (the reference's SharedInformerFactory analogue,
    controller.go:55-102)."""

    def __init__(self, rest: "RestClientset", reconnect_delay: float = 1.0):
        self.rest = rest
        self.reconnect_delay = reconnect_delay
        self._stops: dict[int, "threading.Event"] = {}

    # -- reads delegate ------------------------------------------------------

    def list_pods(self, label_selector=None, field_selector=None, node_name=None):
        return self.rest.list_pods(label_selector, field_selector, node_name)

    def get_pod(self, namespace, name):
        return self.rest.get_pod(namespace, name)

    def list_nodes(self):
        # the controller's vanished-node prune (journaled node_remove)
        # needs the node listing through the SAME view surface
        # FakeCluster provides — without this delegation the prune only
        # ever ran in tests
        return self.rest.list_nodes()

    # -- streaming watch -----------------------------------------------------

    def watch_pods(self):
        import queue as _queue
        import threading as _threading

        q: _queue.Queue = _queue.Queue()
        stop = _threading.Event()
        self._stops[id(q)] = stop
        t = _threading.Thread(
            target=self._watch_loop, args=(q, stop), daemon=True,
            name="rest-watch",
        )
        t.start()
        return q

    def stop_watch(self, q):
        stop = self._stops.pop(id(q), None)
        if stop is not None:
            stop.set()

    def _watch_loop(self, q, stop):
        # jittered-exponential reconnect (utils/backoff): a fixed delay
        # here meant an apiserver flap re-connected EVERY watcher in the
        # fleet in lockstep — the synchronized-retry-storm failure mode
        # the shared policy exists to kill.  base = the old fixed delay;
        # a healthy stream resets the run.
        bo = Backoff(base_s=self.reconnect_delay, max_s=30.0)
        while not stop.is_set():
            try:
                req, ctx = self.rest.prepare("/api/v1/pods?watch=true")
                with urllib.request.urlopen(req, context=ctx, timeout=330) as resp:
                    for raw in resp:
                        if stop.is_set():
                            return
                        raw = raw.strip()
                        if not raw:
                            continue
                        bo.reset()  # a live event = the stream is healthy
                        evt = json.loads(raw)
                        etype = evt.get("type", "")
                        obj = evt.get("object") or {}
                        if etype in ("ADDED", "MODIFIED", "DELETED"):
                            q.put((etype, Pod.from_dict(obj)))
            except Exception:
                if stop.is_set():
                    return
                if stop.wait(bo.next_delay()):
                    return
