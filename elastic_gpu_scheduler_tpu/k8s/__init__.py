"""k8s subpackage of elastic_gpu_scheduler_tpu."""
