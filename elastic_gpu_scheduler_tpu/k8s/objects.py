"""Minimal typed Kubernetes object model (Pod / Node / Binding).

The reference links client-go and the full k8s API machinery; this build keeps
a deliberately small typed core speaking the real API JSON (camelCase wire
names), because (a) only pods, nodes, and bindings matter to the scheduler,
and (b) the scheduling core must be constructible from plain objects with no
API server — the unit-test pattern the reference gestures at
(pkg/scheduler/scheduler_test.go:26-43) hardened into a design rule.

``from_dict``/``to_dict`` round-trip the subset we model and preserve unknown
fields verbatim in ``extra`` so a real API server's objects survive a
read-modify-write cycle.
"""

from __future__ import annotations

import copy
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    resource_version: str = "0"
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "namespace": self.namespace,
            "uid": self.uid,
            "resourceVersion": self.resource_version,
            "labels": dict(self.labels),
            "annotations": dict(self.annotations),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ObjectMeta":
        return cls(
            name=d.get("name", ""),
            namespace=d.get("namespace", "default"),
            uid=d.get("uid", ""),
            resource_version=str(d.get("resourceVersion", "0")),
            labels=dict(d.get("labels") or {}),
            annotations=dict(d.get("annotations") or {}),
        )


@dataclass
class ResourceRequirements:
    requests: dict[str, Any] = field(default_factory=dict)
    limits: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"requests": dict(self.requests), "limits": dict(self.limits)}

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ResourceRequirements":
        d = d or {}
        return cls(
            requests=dict(d.get("requests") or {}), limits=dict(d.get("limits") or {})
        )


@dataclass
class Container:
    name: str
    image: str = ""
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "image": self.image,
            "resources": self.resources.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Container":
        return cls(
            name=d.get("name", ""),
            image=d.get("image", ""),
            resources=ResourceRequirements.from_dict(d.get("resources")),
        )


@dataclass
class PodSpec:
    containers: list[Container] = field(default_factory=list)
    node_name: str = ""
    scheduler_name: str = ""
    # pod priority (scheduling.k8s.io PriorityClass value) — drives victim
    # selection in the preemption verb; absent means 0, like kube-scheduler's
    # treatment of priority-less pods
    priority: Optional[int] = None

    def to_dict(self) -> dict:
        d = {
            "containers": [c.to_dict() for c in self.containers],
            "nodeName": self.node_name,
            "schedulerName": self.scheduler_name,
        }
        if self.priority is not None:
            d["priority"] = self.priority
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "PodSpec":
        d = d or {}
        prio = d.get("priority")
        return cls(
            containers=[Container.from_dict(c) for c in d.get("containers") or []],
            node_name=d.get("nodeName", ""),
            scheduler_name=d.get("schedulerName", ""),
            priority=int(prio) if prio is not None else None,
        )


@dataclass
class PodStatus:
    phase: str = "Pending"  # Pending | Running | Succeeded | Failed

    def to_dict(self) -> dict:
        return {"phase": self.phase}

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "PodStatus":
        return cls(phase=(d or {}).get("phase", "Pending"))


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)
    extra: dict = field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def is_completed(self) -> bool:
        """Reference: pkg/scheduler/pod.go:16-25."""
        return self.status.phase in ("Succeeded", "Failed")

    def to_dict(self) -> dict:
        d = dict(self.extra)
        d.update(
            {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": self.metadata.to_dict(),
                "spec": self.spec.to_dict(),
                "status": self.status.to_dict(),
            }
        )
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Pod":
        extra = {
            k: v for k, v in d.items() if k not in ("metadata", "spec", "status")
        }
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=PodSpec.from_dict(d.get("spec")),
            status=PodStatus.from_dict(d.get("status")),
            extra=extra,
        )

    def clone(self) -> "Pod":
        """Structural copy (hot path: every fake/REST read+write clones).
        Explicit field copies are ~10x cheaper than a to_dict→deepcopy→
        from_dict round-trip; only ``extra`` (arbitrary JSON) needs deepcopy
        and it is empty unless an external API server added fields."""
        m = self.metadata
        return Pod(
            metadata=ObjectMeta(
                name=m.name,
                namespace=m.namespace,
                uid=m.uid,
                resource_version=m.resource_version,
                labels=dict(m.labels),
                annotations=dict(m.annotations),
            ),
            spec=PodSpec(
                containers=[
                    Container(
                        name=c.name,
                        image=c.image,
                        resources=ResourceRequirements(
                            requests=dict(c.resources.requests),
                            limits=dict(c.resources.limits),
                        ),
                    )
                    for c in self.spec.containers
                ],
                node_name=self.spec.node_name,
                scheduler_name=self.spec.scheduler_name,
                priority=self.spec.priority,
            ),
            status=PodStatus(phase=self.status.phase),
            extra=copy.deepcopy(self.extra) if self.extra else {},
        )


@dataclass
class NodeStatus:
    capacity: dict[str, Any] = field(default_factory=dict)
    allocatable: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "capacity": dict(self.capacity),
            "allocatable": dict(self.allocatable),
        }

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "NodeStatus":
        d = d or {}
        return cls(
            capacity=dict(d.get("capacity") or {}),
            allocatable=dict(d.get("allocatable") or {}),
        )


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    status: NodeStatus = field(default_factory=NodeStatus)
    extra: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        d = dict(self.extra)
        d.update(
            {
                "apiVersion": "v1",
                "kind": "Node",
                "metadata": self.metadata.to_dict(),
                "status": self.status.to_dict(),
            }
        )
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Node":
        extra = {k: v for k, v in d.items() if k not in ("metadata", "status")}
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            status=NodeStatus.from_dict(d.get("status")),
            extra=extra,
        )

    def clone(self) -> "Node":
        m = self.metadata
        return Node(
            metadata=ObjectMeta(
                name=m.name,
                namespace=m.namespace,
                uid=m.uid,
                resource_version=m.resource_version,
                labels=dict(m.labels),
                annotations=dict(m.annotations),
            ),
            status=NodeStatus(
                capacity=dict(self.status.capacity),
                allocatable=dict(self.status.allocatable),
            ),
            extra=copy.deepcopy(self.extra) if self.extra else {},
        )


@dataclass
class Binding:
    """pods/binding subresource payload (reference: scheduler.go:214-222)."""

    pod_name: str
    pod_namespace: str
    pod_uid: str
    node: str

    def to_dict(self) -> dict:
        return {
            "apiVersion": "v1",
            "kind": "Binding",
            "metadata": {
                "name": self.pod_name,
                "namespace": self.pod_namespace,
                "uid": self.pod_uid,
            },
            "target": {"apiVersion": "v1", "kind": "Node", "name": self.node},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Binding":
        md = d.get("metadata") or {}
        return cls(
            pod_name=md.get("name", ""),
            pod_namespace=md.get("namespace", "default"),
            pod_uid=md.get("uid", ""),
            node=(d.get("target") or {}).get("name", ""),
        )


def new_uid() -> str:
    return str(uuid.uuid4())


def make_pod(
    name: str,
    namespace: str = "default",
    containers: Optional[list[Container]] = None,
    annotations: Optional[dict[str, str]] = None,
    labels: Optional[dict[str, str]] = None,
    uid: str = "",
    priority: Optional[int] = None,
) -> Pod:
    """Test/bench convenience constructor."""
    return Pod(
        metadata=ObjectMeta(
            name=name,
            namespace=namespace,
            uid=uid or new_uid(),
            annotations=dict(annotations or {}),
            labels=dict(labels or {}),
        ),
        spec=PodSpec(containers=containers or [], priority=priority),
    )


def make_tpu_node(
    name: str,
    chips: int,
    hbm_gib: int,
    accelerator: str = "v5e",
    slice_topology: str = "",
    host_topology: str = "",
    host_offset: str = "",
    slice_name: str = "",
) -> Node:
    """Build a TPU node the way GKE would label it (see utils/consts.py)."""
    from ..utils import consts

    labels = {consts.LABEL_TPU_ACCELERATOR: accelerator}
    if slice_topology:
        labels[consts.LABEL_TPU_TOPOLOGY] = slice_topology
    if host_topology:
        labels[consts.LABEL_TPU_HOST_TOPOLOGY] = host_topology
    if host_offset:
        labels[consts.LABEL_TPU_HOST_OFFSET] = host_offset
    if slice_name:
        labels[consts.LABEL_TPU_SLICE] = slice_name
    res = {
        consts.RESOURCE_TPU_CORE: chips * consts.CORE_PER_CHIP,
        consts.RESOURCE_TPU_HBM: hbm_gib,
    }
    return Node(
        metadata=ObjectMeta(name=name, namespace="", uid=new_uid(), labels=labels),
        status=NodeStatus(capacity=dict(res), allocatable=dict(res)),
    )
