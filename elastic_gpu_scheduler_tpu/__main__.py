"""``python -m elastic_gpu_scheduler_tpu`` → the scheduler CLI."""

import sys

from .cli import main

sys.exit(main())
