"""Entry point / CLI.

Reference: cmd/main.go — flags ``-priority binpack|spread``, ``-mode`` comma
list, ``-kubeconf``; env ``PORT`` (default 39999) and ``THREADNESS`` (default
1) (main.go:26-30, 68-72, 103-110).  Additions: ``--priority ici-locality``
and ``--fake-nodes`` to run self-contained against an in-memory cluster (for
demos/benchmarks without an API server).
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading

from .controller.controller import Controller
from .k8s.client import FakeClientset, RestClientset
from .k8s.fake import FakeCluster
from .k8s.objects import make_tpu_node
from .scheduler.registry import build_resource_schedulers
from .scheduler.gang import GangCoordinator
from .scheduler.scheduler import SchedulerConfig
from .server.handlers import Bind, Predicate, Prioritize
from .server.routes import ExtenderServer


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def build_stack(
    clientset,
    cluster=None,
    priority: str = "binpack",
    modes: tuple[str, ...] = ("tpushare",),
    workers: int = 1,
    gang_timeout: float = 30.0,
    gang_batch_window: float = 0.0,
    gang_batch_min: int = 4,
    placement_index: bool = True,
    defrag_mode: str = "off",
    defrag_threshold: float = 0.5,
    defrag_max_moves: int = 8,
    defrag_priority_ceiling: int = 0,
    defrag_interval: float = 30.0,
    defrag_min_interval: float = 5.0,
    rebuild_on_start: bool = True,
):
    """Wire registry + handlers + controller (reference: main.go:56-96)."""
    # warm the native placement extension at startup so the first large-mesh
    # filter request never pays the g++ build under the allocator lock
    from .core.native import get_placement

    get_placement()
    # ONE registry resolves every rater spec (built-ins, profile-aware
    # wrapping, policy-plane expressions) — the journal CLI's --rater
    # goes through the same lookup (policy/registry.py)
    from .policy import POLICIES, default_gate_events, resolve_rater

    rater = resolve_rater(priority)
    config = SchedulerConfig(
        clientset=clientset, rater=rater, placement_index=placement_index,
        rebuild_on_start=rebuild_on_start,
    )
    registry = build_resource_schedulers(list(modes), config)
    gang = GangCoordinator(
        clientset, timeout=gang_timeout,
        batch_window_s=gang_batch_window, batch_min=gang_batch_min,
    )
    # defrag planner: always constructed (the /debug/defrag preview and
    # manual POST /defrag/run work in every mode); 'off' costs one
    # attribute check on the gang filter's infeasible path and nothing
    # anywhere near bind
    from .defrag import DefragPlanner

    gang.defrag = DefragPlanner(
        registry.values(), clientset,
        mode=defrag_mode,
        threshold=defrag_threshold,
        max_moves=defrag_max_moves,
        priority_ceiling=defrag_priority_ceiling,
        interval_s=defrag_interval,
        min_interval_s=defrag_min_interval,
    )
    # programmable policy plane: the process-global plane steers every
    # engine (score canaries split the bind path, filter policies prune
    # assume + the gang prefilter, defrag policies re-rank victims).
    # Zero-cost until a policy is loaded; the replay gate reads the live
    # journal, SLO frag regression reads the engine's frag snapshot.
    POLICIES.attach(registry.values())
    gang.defrag.policies = POLICIES
    POLICIES.gate_events_fn = default_gate_events
    first_engine = next(iter(registry.values()), None)
    if first_engine is not None:
        POLICIES.frag_provider = first_engine.frag_snapshot
    predicate = Predicate(registry, gang=gang)
    prioritize = Prioritize(registry)
    bind = Bind(registry, clientset, gang=gang)
    controller = None
    if cluster is not None:
        controller = Controller(cluster, registry, workers=workers)

    def status(summary: bool = False, top_k: int = 10,
               generations: bool = False):
        seen = []
        out = []
        for sched in registry.values():
            if id(sched) in seen:
                continue
            seen.append(id(sched))
            out.append(
                sched.status_summary(top_k=top_k, generations=generations)
                if summary else sched.status()
            )
        return {"schedulers": out, "gangs": gang.status()}

    return registry, predicate, prioritize, bind, controller, status, gang


def main(argv=None) -> int:
    p = argparse.ArgumentParser("tpu-elastic-scheduler")
    p.add_argument(
        "--priority",
        default="binpack",
        help="placement policy: binpack|spread|random|ici-locality, "
        "profile-aware[:BASE], or policy:FILE[:BASE] (a policy-plane "
        "expression file; BASE = fallback rater on fault).  Hot-loaded "
        "policies are managed at runtime via POST /policy/load",
    )
    p.add_argument(
        "--mode", default="tpushare", help="scheduler mode: tpushare (fractional + whole-chip) or tpuwhole (whole-chip exclusive admission for latency-SLO clusters); exactly one"
    )
    p.add_argument("--port", type=int, default=_env_int("PORT", 39999))
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument(
        "--kube-api", default="", help="API server URL (out-of-cluster REST mode)"
    )
    p.add_argument("--kube-token", default="")
    p.add_argument(
        "--fake-nodes",
        type=int,
        default=0,
        help="run self-contained with N fake 4-chip v5e TPU nodes",
    )
    p.add_argument(
        "--threadness", type=int, default=_env_int("THREADNESS", 1),
        help="controller worker threads",
    )
    p.add_argument("--gang-timeout", type=float, default=30.0)
    p.add_argument(
        "--gang-batch-window", type=float, default=0.0,
        help="batch admission sweep: a gang's first member parks up to "
        "this many seconds collecting other pending gangs, then ONE "
        "sweep plans the whole queue (shared clones, one reservation "
        "replay, multi-gang plan_gang_batch kernel calls).  0 (default) "
        "= plan each gang on arrival",
    )
    p.add_argument(
        "--gang-batch-min", type=int, default=4,
        help="end the batch window early once this many gangs are "
        "pending",
    )
    p.add_argument(
        "--placement-index", default="on", choices=["on", "off"],
        help="incremental free-capacity index: O(1) candidate rejection "
        "+ one placement probe per congruent node class on filter/score, "
        "index-fed gang-plan prefilter, dirty-node-only fragmentation "
        "refresh.  off = the full-rescan path everywhere (parity "
        "baseline; see OPERATIONS.md 'Cluster scale')",
    )
    p.add_argument("--tls-cert", default="", help="serve HTTPS with this cert")
    p.add_argument("--tls-key", default="")
    p.add_argument(
        "--leader-elect",
        action="store_true",
        help="run lease-based leader election (HA: standbys gate verbs and "
        "report /healthz 503 until they acquire the lease)",
    )
    p.add_argument("--leader-lease-duration", type=float, default=15.0)
    p.add_argument(
        "--follow", default="",
        help="warm-standby mode: continuously replay this leader's "
        "journal stream (http://leader:port) into live state via "
        "GET /journal/stream, so election (--leader-elect) swaps the "
        "replayed state in and resyncs as a DIFF against the annotation "
        "ledger instead of a cold rebuild.  Lag exported as "
        "tpu_ha_follow_lag_seqs/_seconds; posture at /debug/leader",
    )
    p.add_argument(
        "--fault-plan", default="",
        help="deterministic fault injection (chaos drills): a JSON plan "
        "list or @file — [{\"site\": ..., \"kind\": error|timeout|"
        "partition|torn-write|crash, \"p\"|\"nth\": ..., \"seed\": N}]. "
        "Also via TPU_FAULT_PLAN env or POST /faults/load at runtime; "
        "state at /debug/faults.  NEVER enable on a production leader "
        "except as a supervised game-day exercise",
    )
    p.add_argument(
        "--http-workers",
        type=int,
        default=_env_int("HTTP_WORKERS", 320),
        help="pre-spawned HTTP worker threads (0 = thread per connection); "
        "size for max expected gang concurrency — a gang bind parks one "
        "worker per member at the barrier",
    )
    p.add_argument(
        "--trace-sample", type=float, default=None,
        help="scheduling-trace sampling rate (1.0 = trace every pod, 0 = "
        "off; default from TPU_TRACE_SAMPLE, else 1.0).  /traces and "
        "/debug/schedule/<pod> serve the result",
    )
    p.add_argument(
        "--profile-sample", type=float, default=None,
        help="workload-profile sampling rate (1.0 = every sample, 0 = "
        "off; default from TPU_PROFILE_SAMPLE, else 1.0).  Enables the "
        "co-tenancy map + per-class profiles at /debug/profiles, the "
        "tpu_workload_*/tpu_interference_* metrics, and periodic "
        "`profile` journal records (when the journal is on)",
    )
    p.add_argument(
        "--relay-probe-interval", type=float,
        default=float(os.environ.get("TPU_RELAY_PROBE_INTERVAL", "0")),
        help="probe the TPU relay every this many seconds and publish "
        "tpu_relay_up on /metrics + /debug/relay (0 = off, default; the "
        "probe runs a bounded jax subprocess on its own thread, never "
        "on the scrape path)",
    )
    p.add_argument(
        "--journal-dir", default=os.environ.get("TPU_JOURNAL_DIR", ""),
        help="enable the scheduling flight recorder: append every "
        "allocator state mutation to crash-safe journal segments in this "
        "directory (default from TPU_JOURNAL_DIR; empty = off).  Replay "
        "offline with `python -m elastic_gpu_scheduler_tpu.journal`",
    )
    p.add_argument(
        "--journal-fsync", default="interval",
        choices=["always", "interval", "off"],
        help="journal durability: fsync per record batch (always), every "
        "~200ms (interval, default), or leave it to the OS (off)",
    )
    p.add_argument(
        "--journal-max-bytes", type=int, default=64 << 20,
        help="journal segment size before rotation (bytes, default 64MiB)",
    )
    p.add_argument(
        "--defrag", default="off", choices=["off", "observe", "auto"],
        help="mesh defragmentation: off (default; zero bind-path cost), "
        "observe (plans served at /debug/defrag, POST /defrag/run may "
        "execute), auto (gang filters retry after an unblocking round + "
        "a background tick compacts over-threshold nodes)",
    )
    p.add_argument(
        "--defrag-threshold", type=float, default=0.5,
        help="per-node fragmentation index (1 - largest_free_box/"
        "free_chips) above which auto mode compacts the node",
    )
    p.add_argument(
        "--defrag-max-moves", type=int, default=8,
        help="migration budget per defrag round",
    )
    p.add_argument(
        "--defrag-priority-ceiling", type=int, default=0,
        help="never migrate a pod (or any member of a gang) whose "
        "priority exceeds this",
    )
    p.add_argument(
        "--defrag-interval", type=float, default=30.0,
        help="auto-mode background tick period (seconds)",
    )
    p.add_argument(
        "--defrag-min-interval", type=float, default=5.0,
        help="minimum seconds between gang-filter unblock rounds (rate "
        "limit: a stream of infeasible gangs must not thrash the "
        "cluster with migrations)",
    )
    p.add_argument(
        "--fleet", default="off", choices=["off", "router", "auto"],
        help="elastic serving fleet: off (default), router (start the "
        "prefix-affinity front door over --fleet-replicas on "
        "--fleet-port), auto (router + the signal-driven autoscaler: "
        "scale decisions journaled as `fleet` records and executed as "
        "admissions/releases through this scheduler's own verbs)",
    )
    p.add_argument(
        "--fleet-port", type=int, default=8100,
        help="front-door router port (/v1/* fan-out, /debug/fleet, "
        "/metrics)",
    )
    p.add_argument(
        "--fleet-replicas", default="",
        help="seed replica list: comma-separated name@host:port entries "
        "(append !relay for replicas serving through the TPU probe "
        "relay — their health follows tpu_relay_up instead of burning "
        "HTTP timeouts when the relay drops)",
    )
    p.add_argument(
        "--fleet-page-size", type=int, default=16,
        help="prefix-affinity page size; must match the replicas' "
        "engine --page-size for affinity hits to be real cache hits "
        "(the router adopts a replica's advertised value when stats "
        "disagree)",
    )
    p.add_argument("--fleet-min-replicas", type=int, default=1)
    p.add_argument("--fleet-max-replicas", type=int, default=8)
    p.add_argument(
        "--fleet-queue-high", type=float, default=4.0,
        help="scale up when mean queued requests per replica reaches "
        "this (hysteresis + cooldown apply; see OPERATIONS.md)",
    )
    p.add_argument(
        "--fleet-queue-low", type=float, default=0.25,
        help="scale down only when queue/replica AND occupancy sit "
        "below the low watermarks",
    )
    p.add_argument("--fleet-cooldown-up", type=float, default=10.0)
    p.add_argument("--fleet-cooldown-down", type=float, default=60.0)
    p.add_argument(
        "--fleet-interval", type=float, default=5.0,
        help="autoscaler evaluation period (every evaluation is "
        "journaled as a `fleet` record when the journal is on)",
    )
    p.add_argument(
        "--fleet-health-interval", type=float, default=2.0,
        help="router health/stats poll period per replica",
    )
    p.add_argument(
        "--fleet-shed-margin", type=float, default=0.0,
        help="disaggregated data plane: > 0 lets the autoscaler "
        "REBALANCE in-flight sessions — on hold ticks a replica whose "
        "queue exceeds the idlest one's by this many requests sheds "
        "one live session over the KV-migration wire "
        "(/v1/migrate/out), and scale-down migrates the victim's "
        "sessions instead of waiting out their generation; every hop "
        "is a journaled `kv_migrate` record.  0 (default) = off",
    )
    p.add_argument(
        "--fleet-disagg-min-pages", type=int, default=4,
        help="prefill/decode split: a no-affinity prompt with at "
        "least this many full pages routes through a prefill-role "
        "replica (POST /v1/prefill) and the decode target adopts the "
        "pages (X-KV-Source pull); 0 disables the split",
    )
    p.add_argument(
        "--fleet-adopt-margin", type=float, default=0.0,
        help="prefix-index load shedding: > 0 routes AWAY from an "
        "overloaded prefix holder (queue delta past this margin) and "
        "adopts the pages onto the idlest replica instead; 0 "
        "(default) = affinity always wins, the historic behavior",
    )
    p.add_argument(
        "--fleet-wclass", default="serve",
        help="workload class the autoscaler reads generation "
        "throughput preferences for (profile observatory)",
    )
    p.add_argument(
        "--slo-config", default=os.environ.get("TPU_SLO_CONFIG", ""),
        help="fleet SLO plane: per-class objectives as inline JSON or "
        "@file — {\"classes\": {\"serve\": {\"ttft_p95_ms\": 200, "
        "\"e2e_p99_ms\": 2000, \"availability\": 0.99}}, "
        "\"window_short_s\": 60, \"window_long_s\": 300, "
        "\"burn_threshold\": 1.0}.  Enables request-journey recording "
        "at the fleet router, burn-rate breach journaling (`slo` "
        "records with exemplar trace ids), tpu_slo_* metrics, "
        "/debug/slo + /debug/trace/<id>, and the autoscaler's "
        "SLO-proactive scale-up input.  Also loadable at runtime via "
        "POST /slo/load",
    )
    p.add_argument(
        "--slo-interval", type=float, default=5.0,
        help="SLO evaluate tick period (burn-rate computation + breach "
        "journaling; the autoscaler tick also drives it when --fleet="
        "auto)",
    )
    p.add_argument("-v", "--verbose", action="count", default=0)
    args = p.parse_args(argv)

    if args.trace_sample is not None:
        from .tracing import TRACER

        TRACER.configure(args.trace_sample)

    if args.profile_sample is not None:
        # before build_stack, so the startup rebuild's bind replays
        # already populate the co-tenancy map
        from .profile import PROFILER

        PROFILER.configure(sample=args.profile_sample)

    relay_monitor = None
    if args.relay_probe_interval > 0:
        from .utils.tpuprobe import RELAY_MONITOR

        RELAY_MONITOR.interval_s = max(5.0, args.relay_probe_interval)
        relay_monitor = RELAY_MONITOR.start()

    if args.journal_dir:
        # before build_stack, so the startup rebuild's node_add/replay
        # records land in the journal too
        from .journal import JOURNAL

        JOURNAL.configure(
            args.journal_dir,
            fsync=args.journal_fsync,
            max_segment_bytes=args.journal_max_bytes,
        )

    if args.slo_config:
        # after the journal configures, so the objective load itself
        # lands as an `slo` annotation in the flight recorder
        from .slo import SLO, load_config_source

        try:
            SLO.load_config(load_config_source(args.slo_config))
        except (ValueError, TypeError, OSError) as e:
            print(f"error: --slo-config: {e}", file=sys.stderr)
            return 2

    if args.fault_plan:
        from .faultinject import FAULTS

        raw_plan = args.fault_plan
        if raw_plan.startswith("@"):
            with open(raw_plan[1:]) as f:
                raw_plan = f.read()
        try:
            FAULTS.load_json(raw_plan)
        except (ValueError, OSError) as e:
            print(f"error: --fault-plan: {e}", file=sys.stderr)
            return 2

    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )

    try:
        from .policy import resolve_rater

        resolve_rater(args.priority)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.leader_elect and args.leader_lease_duration < 1.0:
        print(
            "error: --leader-lease-duration must be >= 1s (sub-second leases "
            "flap leadership)",
            file=sys.stderr,
        )
        return 2
    if args.tls_key and not args.tls_cert:
        print("error: --tls-key given without --tls-cert", file=sys.stderr)
        return 2
    if args.tls_cert and not os.path.exists(args.tls_cert):
        print(f"error: --tls-cert {args.tls_cert}: no such file", file=sys.stderr)
        return 2
    if args.tls_key and not os.path.exists(args.tls_key):
        print(f"error: --tls-key {args.tls_key}: no such file", file=sys.stderr)
        return 2

    cluster = None
    if args.fake_nodes > 0:
        cluster = FakeCluster()
        for i in range(args.fake_nodes):
            cluster.add_node(
                make_tpu_node(
                    f"tpu-node-{i}", chips=4, hbm_gib=64, accelerator="v5e"
                )
            )
        clientset = FakeClientset(cluster)
    elif args.kube_api or os.environ.get("KUBERNETES_SERVICE_HOST"):
        from .k8s.client import RestClusterView

        clientset = RestClientset(base_url=args.kube_api, token=args.kube_token)
        # the controller consumes the same watch/list/get surface either way
        cluster = RestClusterView(clientset)
    else:
        print(
            "error: no cluster — use --fake-nodes N, --kube-api URL, or run "
            "in-cluster",
            file=sys.stderr,
        )
        return 2

    registry, predicate, prioritize, bind, controller, status, gang = build_stack(
        clientset,
        cluster=cluster,
        priority=args.priority,
        modes=tuple(m for m in args.mode.split(",") if m),
        workers=args.threadness,
        gang_timeout=args.gang_timeout,
        gang_batch_window=args.gang_batch_window,
        gang_batch_min=args.gang_batch_min,
        placement_index=args.placement_index != "off",
        defrag_mode=args.defrag,
        defrag_threshold=args.defrag_threshold,
        defrag_max_moves=args.defrag_max_moves,
        defrag_priority_ceiling=args.defrag_priority_ceiling,
        defrag_interval=args.defrag_interval,
        defrag_min_interval=args.defrag_min_interval,
        # a warm standby's state arrives via journal shipping and is
        # swapped in at election — a cold ledger rebuild here would only
        # be thrown away (and pay 10k get_node calls doing it)
        rebuild_on_start=not args.follow,
    )
    if controller is not None:
        controller.start()

    follower = None
    if args.follow:
        from .journal.ship import JournalFollower

        follower = JournalFollower(args.follow).start()

    elector = None
    if args.leader_elect:
        import socket as _socket

        from .scheduler.leader import LeaderElector

        def on_started_leading():
            if args.journal_dir:
                # a previous step-down flushed AND closed the journal;
                # re-acquiring reopens it (seq numbering resumes, the
                # writer adds a boot checkpoint) BEFORE takeover so the
                # takeover itself is journaled.  configure() clears the
                # checkpoint provider, so re-register it even WITHOUT a
                # follower — otherwise every later segment lacks a head
                # checkpoint and pruning eventually makes the journal
                # unreplayable (and unshippable to fresh followers)
                from .journal import JOURNAL

                if not JOURNAL.enabled:
                    JOURNAL.configure(
                        args.journal_dir,
                        fsync=args.journal_fsync,
                        max_segment_bytes=args.journal_max_bytes,
                    )
                    eng = next(iter(registry.values()), None)
                    if eng is not None:
                        eng.register_checkpoint_provider()
            if follower is not None:
                # warm takeover: adopt the follower's replayed state,
                # resync as a diff against the annotation ledger.  The
                # replayed ChipSets are adopted (not cloned) so exactly
                # ONE engine may take them; additional engines (multi-
                # mode deployments) cold-rebuild as before.
                from .scheduler.ha import warm_takeover

                engines = list({id(s): s for s in registry.values()}.values())
                if engines:
                    warm_takeover(engines[0], follower)
                for sched in engines[1:]:
                    sched._rebuild_state()

        elector = LeaderElector(
            clientset,
            identity=f"{_socket.gethostname()}-{os.getpid()}",
            lease_duration=args.leader_lease_duration,
            renew_period=args.leader_lease_duration / 3.0,
            on_started_leading=on_started_leading,
        )

    defrag = gang.defrag
    if elector is not None:
        # standbys must not migrate: the auto tick and the gang filter's
        # try_unblock consult the same leader predicate the HTTP layer
        # gates verbs with
        defrag.leader_check = elector.is_leader
    defrag.start()  # auto-mode background tick (no-op in off/observe)

    # elastic serving fleet (fleet/): router in router|auto, autoscaler
    # in auto.  The autoscaler is ADVISORY unless an executor is wired
    # (replica processes are deployment-controller territory; the
    # check-fleet tool demonstrates full execution with in-process
    # engines) — decisions are still journaled as `fleet` records and
    # served at /debug/fleet.
    fleet_state = None
    if args.fleet != "off":
        from .fleet import (
            Autoscaler,
            FleetRouter,
            FleetState,
            Replica,
            ReplicaSet,
            ScalingPolicy,
        )

        replica_set = ReplicaSet(interval_s=args.fleet_health_interval)
        for i, entry in enumerate(
            e.strip() for e in args.fleet_replicas.split(",") if e.strip()
        ):
            relay = entry.endswith("!relay")
            if relay:
                entry = entry[: -len("!relay")]
            name, _, addr = entry.rpartition("@")
            host_part, _, port_part = addr.rpartition(":")
            try:
                replica_set.add(
                    Replica(
                        name or f"replica-{i}", host_part or "127.0.0.1",
                        int(port_part), relay=relay,
                    )
                )
            except ValueError:
                print(
                    f"error: --fleet-replicas entry {entry!r} is not "
                    "name@host:port", file=sys.stderr,
                )
                return 2
        router = FleetRouter(
            replica_set, host=args.host, port=args.fleet_port,
            page_size=args.fleet_page_size,
            adopt_load_margin=args.fleet_adopt_margin,
            disagg_min_pages=args.fleet_disagg_min_pages,
        )
        from .slo import SLO
        from .slo.assembly import TraceAssembler

        # cross-process trace assembly: /debug/trace/<id> on both ports
        # pulls every replica's /traces through the live replica set, so
        # the pull list tracks scale-ups/downs; SLO breaches capture
        # their exemplar journeys eagerly (before replica rings evict)
        assembler = TraceAssembler(
            sources=lambda: [
                (r.name, (r.host, r.port)) for r in replica_set.all()
            ],
        )
        router.assembler = assembler
        # wired UNCONDITIONALLY: objectives may arrive at runtime via
        # POST /slo/load, and the hooks/ticker/provider must already be
        # in place when they do (evaluate() and scaling_input() no-op
        # while no objectives are loaded, so an SLO-less fleet pays one
        # attribute check per tick)
        SLO.breach_hooks.append(assembler.on_breach)
        # standalone evaluate tick: in auto mode the autoscaler's
        # slo_provider also drives evaluation, but breach detection
        # must not depend on an autoscaler being wired
        SLO.start_ticker(args.slo_interval)
        autoscaler = None
        if args.fleet == "auto":
            autoscaler = Autoscaler(
                replica_set, executor=None,
                policy=ScalingPolicy(
                    min_replicas=args.fleet_min_replicas,
                    max_replicas=args.fleet_max_replicas,
                    queue_high=args.fleet_queue_high,
                    queue_low=args.fleet_queue_low,
                    up_cooldown_s=args.fleet_cooldown_up,
                    down_cooldown_s=args.fleet_cooldown_down,
                ),
                interval_s=args.fleet_interval,
                wclass=args.fleet_wclass,
                # session rebalance rides the router's migration verb;
                # scale actions stay advisory without an executor, but
                # shedding only moves live sessions between replicas
                # that already exist — safe to enable CLI-side
                migrator=(
                    router.migrate_session
                    if args.fleet_shed_margin > 0 else None
                ),
                shed_queue_margin=args.fleet_shed_margin,
                # burn posture as a pure evaluate input: scale up on
                # budget burn before queue depth moves (journaled in
                # every `fleet` record, replayed by score_policy).
                # Always wired — scaling_input answers None until
                # objectives load, incl. a runtime POST /slo/load
                slo_provider=SLO.scaling_input,
            )
        fleet_state = FleetState(
            router=router, autoscaler=autoscaler, assembler=assembler
        )
        # both ports answer /debug/fleet with the SAME combined payload
        router.state_provider = fleet_state.debug_state

    from .policy import POLICIES
    from .server.handlers import Preemption

    server = ExtenderServer(
        predicate, prioritize, bind, status,
        preemption=Preemption(registry, clientset),
        host=args.host, port=args.port,
        tls_cert=args.tls_cert, tls_key=args.tls_key,
        workers=max(0, args.http_workers),
        leader_check=elector.is_leader if elector is not None else None,
        defrag=defrag,
        fleet=fleet_state,
        policy=POLICIES,
        elector=elector,
        follower=follower,
        assembler=(
            fleet_state.assembler if fleet_state is not None else None
        ),
    )

    if elector is not None:
        def on_stepping_down():
            # runs fenced (is_leader already False → new verbs 503) but
            # BEFORE the lease drops: drain in-flight verb handlers so
            # nothing commits after a successor could serve, then flush
            # + close the journal so the last sealed records reached
            # disk (and the shipping stream) while they were still ours
            server.wait_verbs_idle(
                timeout_s=max(1.0, args.leader_lease_duration / 3.0)
            )
            if args.journal_dir:
                from .journal import JOURNAL

                JOURNAL.flush(timeout=5.0)
                JOURNAL.close()

        elector.on_stepping_down = on_stepping_down
        # started only now: the hooks close over the fully-built server
        elector.start()

    stop = threading.Event()

    def on_signal(signum, frame):
        # second signal → hard exit (reference: signals/signal.go:16-30)
        if stop.is_set():
            os._exit(1)
        stop.set()
        server.stop()
        if elector is not None:
            elector.stop()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)

    port = server.start()
    print(f"tpu-elastic-scheduler serving on {args.host}:{port}")
    if fleet_state is not None:
        fleet_port = fleet_state.router.start()
        if fleet_state.autoscaler is not None:
            fleet_state.autoscaler.start()
        print(f"fleet router serving on {args.host}:{fleet_port}")
    try:
        while not stop.wait(0.5):
            pass
    finally:
        if follower is not None:
            follower.stop()
        if fleet_state is not None:
            from .slo import SLO

            SLO.stop_ticker()  # started whenever the fleet is on
            fleet_state.stop()
        defrag.stop()
        if relay_monitor is not None:
            relay_monitor.stop()
        if controller is not None:
            controller.stop()
        if args.journal_dir:
            # drain the writer's buffer before exit (atexit also covers
            # this, but a prompt close keeps the tail off the 100ms poll)
            from .journal import JOURNAL

            JOURNAL.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
