"""``python -m elastic_gpu_scheduler_tpu.serve`` — stand up the inference
HTTP server around the paged serving engine.

Model sources, in precedence order:
- ``--hf DIR``: a HuggingFace Llama/Mistral checkpoint directory
  (models/convert.py import path, GQA/sliding-window aware);
- ``--init``: random weights from the --d-model/--n-layers/... flags
  (smoke tests, benchmarking);
one of the two is required.  ``--int8`` quantizes whichever base loaded.

This is the workload-plane sibling of the extender CLI (cli.py): the
scheduler places and binds the pod, the launcher builds the mesh for
training jobs, and THIS entry serves a model over HTTP
(server/inference.py: /v1/completions incl. SSE streaming, /v1/stats,
/healthz).
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading

log = logging.getLogger("tpu-scheduler")


def build_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--host", default="0.0.0.0")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--hf", default="", help="HF checkpoint dir to import")
    src.add_argument("--init", action="store_true",
                     help="random init from the model flags")
    p.add_argument("--vocab-size", type=int, default=32000)
    p.add_argument("--d-model", type=int, default=512)
    p.add_argument("--n-layers", type=int, default=4)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--d-ff", type=int, default=1376)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--int8", action="store_true",
                   help="weight-only int8 quantization after load")
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--max-len", type=int, default=2048)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--n-pages", type=int, default=0,
                   help="KV pool pages (0 = slot-contiguous equivalent)")
    p.add_argument("--fused-steps", type=int, default=16)
    p.add_argument("--kv-int8", action="store_true")
    p.add_argument("--prefix-cache", action="store_true")
    p.add_argument("--tensor", type=int, default=1,
                   help="serve tensor-parallel over this many devices "
                        "(checkpoints bigger than one chip's HBM); needs "
                        ">= that many attached devices")
    p.add_argument("--spec-k", type=int, default=0,
                   help=">0 enables speculative decoding (this many draft "
                        "tokens per verify pass; prompt-lookup drafting "
                        "unless --draft-hf)")
    p.add_argument("--draft-hf", default="",
                   help="HF checkpoint dir for a DRAFT model "
                        "(draft-model speculation; requires --spec-k)")
    p.add_argument("--logprobs-k", type=int, default=5,
                   help="compiled top-k width for per-token logprobs "
                        "(0 disables; requests asking more are clamped)")
    p.add_argument("--prefill-chunk", type=int, default=0,
                   help=">0: long prompts ingest this many tokens per "
                        "engine iteration (chunked prefill) so decoding "
                        "requests keep streaming during big admissions")
    p.add_argument("--max-queue", type=int, default=0,
                   help=">0: bound the admission queue; excess requests "
                        "get 429 instead of unbounded tail latency")
    p.add_argument("--drain-timeout", type=float, default=30.0,
                   help="graceful-drain window on SIGTERM/SIGINT: stop "
                        "admitting (healthz 503), let in-flight requests "
                        "finish, then exit; a second signal hard-stops")
    p.add_argument("--serve-overlap", choices=["on", "off"], default="on",
                   help="double-buffered decode dispatch: the next fused "
                        "chunk is dispatched off device-resident state "
                        "before the previous one's tokens drain, so the "
                        "accelerator never waits on host bookkeeping.  "
                        "'off' = the exact sequential loop (correctness "
                        "mode; greedy/seeded outputs are bit-identical "
                        "either way)")
    p.add_argument("--paged-kernel", action="store_true",
                   help="decode attention reads the page pool in place "
                        "via the Pallas kernel (long-context HBM win); "
                        "composes with --kv-int8/--spec-k/--tensor and "
                        "sliding-window models")
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend in-process (overrides a "
                        "sticky JAX_PLATFORMS from site config; tests/dev)")
    p.add_argument("--trace-sample", type=float, default=None,
                   help="request-trace sampling rate (1.0 = every request, "
                        "0 = off; default from TPU_TRACE_SAMPLE, else 1.0); "
                        "GET /traces serves the result")
    p.add_argument("--profile-sample", type=float, default=None,
                   help="workload-profile sampling rate (1.0 = every "
                        "engine step, 0.25 = every 4th, 0 = off; default "
                        "from TPU_PROFILE_SAMPLE, else 1.0).  GET "
                        "/debug/profiles and the tpu_workload_* metrics "
                        "serve the result; cost per sampled step is one "
                        "ring-buffer append off the device path")
    p.add_argument("--fleet-role", choices=["both", "prefill", "decode"],
                   default="",
                   help="disaggregated-serving role (default from "
                        "TPU_FLEET_ROLE, else 'both'): 'prefill' "
                        "replicas batch chunked long-prompt prefill and "
                        "export the KV pages (/v1/prefill + "
                        "/v1/kv/export; the router keeps them out of "
                        "completion rotation), 'decode' replicas run "
                        "the token loop and adopt shipped pages, "
                        "'both' serves everything (the classic single-"
                        "role pod).  Requires --prefix-cache for the "
                        "page-shipping paths")
    p.add_argument("--replica-name", default="",
                   help="fleet identity this replica reports in /v1/stats "
                        "(default from POD_NAME; the front-door router "
                        "keys its replica set and prefix-affinity map "
                        "by it)")
    p.add_argument("--compile-cache-dir", default="",
                   help="persistent AOT compile-cache directory (default "
                        "from TPU_COMPILE_CACHE_DIR).  Lattice shapes "
                        "lowered at warm-up serialize here (CRC-checked "
                        "entries); a later pod start on the same dir "
                        "loads them back and performs ZERO new lowerings "
                        "— see OPERATIONS.md 'Compilation warm-start'")
    p.add_argument("--warmup", choices=["auto", "off", "lattice", "full"],
                   default="auto",
                   help="shape-lattice pre-lowering at pod start: the "
                        "engine's (batch, length)-bucket lattice compiles "
                        "BEFORE the pod reports Ready (/healthz 503 "
                        "{warming:true} meanwhile, so the fleet router "
                        "gates traffic on the warm cache).  'lattice' = "
                        "the default-traffic chunk variants, 'full' = all "
                        "32 variant combinations, 'auto' = lattice when a "
                        "compile cache is configured else off")
    p.add_argument("--workload-class", default="",
                   help="profile class this pod's measured behavior "
                        "aggregates under (default from "
                        "TPU_WORKLOAD_CLASS, else the "
                        "elasticgpu.io/workload-class annotation's "
                        "default class).  The scheduler keys interference "
                        "and throughput tables by it")
    p.add_argument("--slo-config", default="",
                   help="replica-side SLO plane: per-class objectives "
                        "as inline JSON or @file (default from "
                        "TPU_SLO_CONFIG).  Enables this pod's own "
                        "request-journey window (vantage=replica) at "
                        "/debug/slo and the queue-wait/TTFT telemetry "
                        "the fleet router folds into the client-"
                        "perceived journey records")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = build_args(argv)
    if args.draft_hf and args.spec_k <= 0:
        # fail BEFORE any weight I/O — a misconfigured flag pair must not
        # cost a multi-GB checkpoint read first
        raise SystemExit("--draft-hf requires --spec-k > 0")
    if args.trace_sample is not None:
        from .tracing import TRACER

        TRACER.configure(args.trace_sample)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    import os

    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from .models.serving import InferenceEngine
    from .models.transformer import TransformerConfig, init_params
    from .server.inference import serve_inference

    # build the mesh BEFORE loading any weights: with --tensor the whole
    # point is a checkpoint that does NOT fit one chip, so conversion and
    # quantization must materialize on HOST (default_device cpu) and the
    # engine then device_puts each leaf straight to its shard — no single
    # chip ever holds the full model
    mesh = None
    host_ctx = None
    if args.tensor > 1:
        from contextlib import ExitStack

        from .parallel.mesh import MeshSpec, make_mesh

        devs = jax.devices()
        if len(devs) < args.tensor:
            raise SystemExit(
                f"--tensor {args.tensor} needs that many devices, "
                f"have {len(devs)}"
            )
        mesh = make_mesh(MeshSpec(tensor=args.tensor), devs[: args.tensor])
        host_ctx = ExitStack()
        try:
            host_ctx.enter_context(
                jax.default_device(jax.local_devices(backend="cpu")[0])
            )
        except RuntimeError:
            host_ctx = None  # no CPU backend (already ON cpu): no-op

    def load_hf(path):
        from .models.convert import config_from_hf_llama, params_from_hf_llama

        import json as _json
        import pathlib

        hf_dir = pathlib.Path(path)
        hf_cfg = _json.loads((hf_dir / "config.json").read_text())
        cfg = config_from_hf_llama(hf_cfg)
        sd = {}
        # prefer safetensors when present (HF hub dirs often carry BOTH
        # formats — loading both would double-read every tensor); in the
        # .bin case load only weight shards, never e.g. training_args.bin
        st_files = sorted(hf_dir.glob("*.safetensors"))
        if st_files:
            from safetensors.torch import load_file

            for f in st_files:
                sd.update(load_file(f))
        else:
            import torch

            for f in sorted(hf_dir.glob("pytorch_model*.bin")):
                # weights_only: state dicts load fine with it and an
                # untrusted checkpoint dir can't run arbitrary code via
                # pickle (ADVICE r2)
                sd.update(
                    torch.load(f, map_location="cpu", weights_only=True)
                )
        if not sd:
            raise SystemExit(f"no weight files found under {hf_dir}")
        return params_from_hf_llama(sd, cfg), cfg

    if args.hf:
        params, cfg = load_hf(args.hf)
    else:
        cfg = TransformerConfig(
            vocab_size=args.vocab_size, d_model=args.d_model,
            n_layers=args.n_layers, n_heads=args.n_heads, d_ff=args.d_ff,
            dtype=args.dtype,
        )
        params = init_params(jax.random.key(0), cfg)
    if args.int8:
        from .models.quantize import quantize_params

        params = quantize_params(params)

    draft = None
    if args.draft_hf:
        draft = load_hf(args.draft_hf)

    if host_ctx is not None:
        host_ctx.close()  # params are host-resident; sharded placement next

    # workload profiling (profile/): identity before the engine starts
    # stepping, so the first samples already aggregate under the right
    # class/generation key.  Generation = the real chip kind on TPU, the
    # backend name elsewhere (a CPU dev box profiles under "cpu").
    import os as _os

    from .profile import PROFILER
    from .utils.consts import DEFAULT_WORKLOAD_CLASS

    if args.profile_sample is not None:
        PROFILER.configure(sample=args.profile_sample)
    devs0 = jax.devices()
    generation = (
        devs0[0].device_kind.lower().replace(" ", "-")
        if jax.default_backend() == "tpu" and devs0
        else jax.default_backend()
    )
    pod_key = "/".join(
        p for p in (
            _os.environ.get("POD_NAMESPACE", ""),
            _os.environ.get("POD_NAME", ""),
        ) if p
    )
    neighbors = tuple(
        c for c in _os.environ.get("TPU_COTENANT_CLASSES", "").split(",")
        if c
    )
    wclass = (
        args.workload_class
        or _os.environ.get("TPU_WORKLOAD_CLASS", "")
        or DEFAULT_WORKLOAD_CLASS
    )
    PROFILER.set_identity(
        pod=pod_key,
        wclass=wclass,
        generation=generation,
        chips=max(1, args.tensor),
        neighbors=neighbors,
    )

    # SLO plane (slo/): objectives from the flag (env TPU_SLO_CONFIG
    # already applied at import); this pod's replica-vantage journeys
    # aggregate under its workload class either way
    from .slo import SLO, load_config_source

    if args.slo_config:
        try:
            SLO.load_config(load_config_source(args.slo_config))
        except (ValueError, TypeError, OSError) as e:
            raise SystemExit(f"--slo-config: {e}")
    SLO.default_class = wclass

    # warm-start compilation plane (compilecache/): a persistent AOT
    # cache when a dir is configured; an in-memory single-flight cache
    # when only the warm-up is requested (warmth then lives for this
    # process).  'auto' warms exactly when a cache dir is set — the
    # combination the zero-lowerings-on-restart contract needs.
    cache_dir = args.compile_cache_dir or _os.environ.get(
        "TPU_COMPILE_CACHE_DIR", ""
    )
    warmup_mode = args.warmup
    if warmup_mode == "auto":
        warmup_mode = "lattice" if cache_dir else "off"
    compile_cache = None
    if cache_dir or warmup_mode != "off":
        from .compilecache import CompileCache

        compile_cache = CompileCache(cache_dir or None)

    engine = InferenceEngine(
        params, cfg,
        max_batch=args.max_batch, max_len=args.max_len,
        page_size=args.page_size, n_pages=args.n_pages,
        fused_steps=args.fused_steps, kv_int8=args.kv_int8,
        prefix_cache=args.prefix_cache, spec_k=args.spec_k, draft=draft,
        mesh=mesh, paged_kernel=args.paged_kernel,
        prefill_chunk=args.prefill_chunk,
        max_queue=args.max_queue, logprobs_k=args.logprobs_k,
        overlap=args.serve_overlap == "on",
        compile_cache=compile_cache,
    )
    # fleet identity (/v1/stats "replica"): the front-door router keys
    # its replica set by this
    engine.replica_name = (
        args.replica_name or _os.environ.get("POD_NAME", "")
    )
    # disaggregated-serving role (/v1/stats "role"): the router reads it
    # from stats polls — prefill-role replicas get zero completion
    # traffic, only /v1/prefill + /v1/kv/export work
    fleet_role = (
        args.fleet_role
        or _os.environ.get("TPU_FLEET_ROLE", "").strip().lower()
        or "both"
    )
    if fleet_role not in ("both", "prefill", "decode"):
        # argparse choices only guard the flag; the env path must fail
        # fast too — a typo'd role would silently disable the router's
        # prefill isolation (the replica would advertise an unknown
        # role and be treated as completion-taking)
        raise SystemExit(
            f"TPU_FLEET_ROLE={fleet_role!r} invalid "
            "(want both|prefill|decode)"
        )
    if fleet_role != "both" and not args.prefix_cache:
        # same fail-fast stance: a prefill replica without the prefix
        # cache starts healthy but is dead capacity (zero completion
        # traffic from the router, every /v1/prefill + /v1/kv/export a
        # 409), and a decode replica can't adopt shipped pages
        raise SystemExit(
            f"--fleet-role {fleet_role} requires --prefix-cache "
            "(KV pages are cached prefix pages)"
        )
    engine.fleet_role = fleet_role
    server, loop = serve_inference(engine, port=args.port, host=args.host)
    if warmup_mode != "off":
        # the HTTP server is already up: /healthz answers 503
        # {"warming": true} for the whole pre-lowering window, so the
        # router/Service gate traffic instead of routing into a compile
        # storm; requests that arrive anyway are served (they just pay
        # compiles the warm-up hasn't reached yet)
        from .compilecache import WarmupState, start_warmup_thread

        loop.warmup = WarmupState()
        loop.warmup.state = "warming"  # visible before the thread spins up
        start_warmup_thread(
            engine, loop.warmup,
            variants="full" if warmup_mode == "full" else "minimal",
        )
    log.info(
        "serving %s model (%d layers, d=%d) on %s:%d",
        "hf-imported" if args.hf else "random-init",
        cfg.n_layers, cfg.d_model, args.host, server.server_address[1],
    )
    stop = threading.Event()
    signals_seen = []

    def on_signal(signum, frame):
        signals_seen.append(signum)
        if len(signals_seen) > 1:
            log.info("second signal: hard stop")
            stop.set()
            return
        log.info(
            "signal %d: draining (in-flight requests finish; new ones "
            "get 503; second signal hard-stops)", signum,
        )

        def _drain():
            from .server.inference import drain

            ok = drain(loop, timeout=args.drain_timeout)
            log.info("drain %s", "complete" if ok else "timed out")
            stop.set()

        threading.Thread(target=_drain, name="drain", daemon=True).start()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    stop.wait()
    server.shutdown()
    loop.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
