"""Reconciliation controller: watch events → workqueue → sync.

Reference: pkg/controller/controller.go.  Same shape, Python-threaded:

- a pod watch (FakeCluster queue or API-server watch) filtered to TPU pods
  (FilteringResourceEventHandler analogue, controller.go:69-91);
- a deduplicating, rate-limited workqueue (controller.go:64) drained by N
  worker threads (THREADNESS analogue);
- ``sync_pod``: completed/deleted pod → ``forget_pod`` (frees chips);
  running pod with a node → ``add_pod`` (learns allocations made by other
  replicas or before a restart) (controller.go:154-185, 301-331);
- a periodic full resync as the safety net for missed events
  (controller.go:24-25: 30s informer resync).

Fixed vs reference (SURVEY §5): workers loop until stopped instead of
exiting after each item and relying on a 1s restart (controller.go:197-203).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Optional

from ..k8s.fake import FakeCluster, is_not_found
from ..k8s.objects import Pod
from ..scheduler.registry import get_resource_scheduler, is_tpu_pod
from ..scheduler.scheduler import ResourceScheduler
from ..core.annotations import assigned_node, is_assumed
from ..tracing import NOOP_SPAN, TRACER

log = logging.getLogger("tpu-scheduler")


class WorkQueue:
    """Deduplicating rate-limited queue keyed by pod key."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1.0):
        self._q: queue.Queue = queue.Queue()
        self._pending: set[str] = set()
        self._failures: dict[str, int] = {}
        self._lock = threading.Lock()
        self.base_delay = base_delay
        self.max_delay = max_delay

    def add(self, key: str) -> None:
        with self._lock:
            if key in self._pending:
                return
            self._pending.add(key)
        self._q.put(key)

    def add_rate_limited(self, key: str) -> None:
        with self._lock:
            n = self._failures.get(key, 0) + 1
            self._failures[key] = n
        delay = min(self.max_delay, self.base_delay * (2 ** min(n, 10)))
        t = threading.Timer(delay, self.add, args=(key,))
        t.daemon = True
        t.start()

    def forget(self, key: str) -> None:
        with self._lock:
            self._failures.pop(key, None)

    def get(self, timeout: float = 0.2) -> Optional[str]:
        try:
            key = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        with self._lock:
            self._pending.discard(key)
        return key


class Controller:
    def __init__(
        self,
        cluster: FakeCluster,
        registry: dict[str, ResourceScheduler],
        resync_period: float = 30.0,
        workers: int = 1,
    ):
        self.cluster = cluster
        self.registry = registry
        self.resync_period = resync_period
        self.workers = max(1, workers)
        self.wq = WorkQueue()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._watch_q: Optional[queue.Queue] = None
        # pods seen by the watch, so sync can distinguish deleted pods
        self._last_seen: dict[str, Pod] = {}
        self._seen_lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._watch_q = self.cluster.watch_pods()
        t = threading.Thread(target=self._watch_loop, name="ctl-watch", daemon=True)
        t.start()
        self._threads.append(t)
        t = threading.Thread(target=self._resync_loop, name="ctl-resync", daemon=True)
        t.start()
        self._threads.append(t)
        for i in range(self.workers):
            t = threading.Thread(
                target=self._worker_loop, name=f"ctl-worker-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)
        # initial resync so pre-existing pods are learned
        self._enqueue_all()

    def stop(self) -> None:
        self._stop.set()
        if self._watch_q is not None:
            self.cluster.stop_watch(self._watch_q)
        for t in self._threads:
            t.join(timeout=2)

    def wait_idle(self, timeout: float = 5.0) -> bool:
        """Test helper: wait until the queue drains."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self.wq._lock:
                empty = not self.wq._pending
            if empty and self.wq._q.empty():
                return True
            time.sleep(0.01)
        return False

    # -- event plumbing ------------------------------------------------------

    def _admit(self, pod: Pod) -> bool:
        """Only TPU pods enter the queue (reference: controller.go:69-91)."""
        return is_tpu_pod(pod)

    def _watch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                event, pod = self._watch_q.get(timeout=0.2)
            except queue.Empty:
                continue
            if not self._admit(pod):
                continue
            with self._seen_lock:
                # keep the DELETED pod's last state too — sync_pod consumes it
                # to release the allocation once get_pod returns NotFound
                self._last_seen[pod.key] = pod
            # Update events only matter on completion transition or
            # newly-assumed pods (reference: controller.go:242-266); enqueue
            # unconditionally — sync_pod is idempotent and cheap.
            self.wq.add(pod.key)

    def _resync_loop(self) -> None:
        while not self._stop.wait(self.resync_period):
            self._enqueue_all()
            self._prune_cordons()
            self._prune_vanished_nodes()

    def _prune_cordons(self) -> None:
        """Expire stale defrag cordons (the safety net for a planner
        that crashed mid-round holding nodes cordoned: every cordon
        carries a TTL, and this resync tick is what enforces it when
        nothing else touches the node)."""
        seen: list[int] = []
        for sched in self.registry.values():
            if id(sched) in seen:
                continue
            seen.append(id(sched))
            try:
                sched.prune_cordons()
            except Exception:
                pass

    def _prune_vanished_nodes(self) -> None:
        """Drop allocators for nodes the cluster no longer lists
        (decommissioned/renamed hardware).  Without this the registry —
        and every journal checkpoint snapshotting it — leaked each dead
        node forever, and replay's ``node_remove`` handler had no live
        emitter.  ``remove_node`` journals the removal and refuses while
        ledger pods still charge the node (their forgets must land
        first), so a node with a lost DELETE event drains naturally over
        successive resyncs."""
        # snapshot every registry BEFORE the node listing: an allocator
        # materialized for a node that joins the cluster AFTER
        # list_nodes() returns must never land in the prune set (it
        # would be removed as "vanished" while perfectly alive).  An
        # allocator in the pre-listing snapshot whose node is absent
        # from the post-snapshot listing really is gone.
        snapshots: list[tuple] = []
        seen: list[int] = []
        for sched in self.registry.values():
            if id(sched) in seen:
                continue
            seen.append(id(sched))
            remove = getattr(sched, "remove_node", None)
            if remove is None:
                continue
            try:
                with sched.lock:
                    snapshots.append((remove, list(sched.allocators)))
            except Exception:
                log.exception("vanished-node prune failed")
        try:
            live = {n.metadata.name for n in self.cluster.list_nodes()}
        except Exception as e:
            log.warning("resync node list failed: %s", e)
            return
        if not live:
            # an empty listing is far more likely an API failure than a
            # nodeless cluster; removing every idle allocator on a blip
            # would churn node_add/node_remove records
            return
        for remove, known in snapshots:
            for name in known:
                if name not in live:
                    try:
                        remove(name)
                    except Exception:
                        log.exception("vanished-node prune failed")

    def _enqueue_all(self) -> None:
        try:
            listed: set[str] = set()
            for pod in self.cluster.list_pods():
                if self._admit(pod):
                    listed.add(pod.key)
                    with self._seen_lock:
                        self._last_seen[pod.key] = pod
                    self.wq.add(pod.key)
            # pods we have seen but the list no longer returns were deleted
            # during a watch gap (REST reconnect); enqueue them so sync_pod
            # observes NotFound and releases their chips — without this, a
            # DELETED event lost across a reconnect leaks the allocation
            with self._seen_lock:
                vanished = [k for k in self._last_seen if k not in listed]
            for k in vanished:
                self.wq.add(k)
        except Exception as e:
            log.warning("resync list failed: %s", e)

    # -- sync ----------------------------------------------------------------

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            key = self.wq.get()
            if key is None:
                continue
            try:
                self.sync_pod(key)
                self.wq.forget(key)
            except Exception as e:
                log.warning("sync %s failed: %s; requeueing", key, e)
                self.wq.add_rate_limited(key)

    def sync_pod(self, key: str) -> None:
        """Reference: syncPod (controller.go:154-185).

        Traced only when the pod already has an open scheduling trace
        (a pod mid-placement): the periodic resync walks EVERY TPU pod,
        and minting a span per walked pod would bury real traces."""
        ctx = TRACER.pod_context(key)
        sp = (
            TRACER.span("controller.sync", parent=ctx, pod=key)
            if ctx is not None
            else NOOP_SPAN
        )
        with sp:
            ns, _, name = key.partition("/")
            try:
                pod = self.cluster.get_pod(ns, name)
            except Exception as e:
                if is_not_found(e):
                    with self._seen_lock:
                        pod = self._last_seen.pop(key, None)
                    if pod is not None:
                        sp.set_attr("action", "release_deleted")
                        self._release(pod)
                    # a deleted pod's scheduling story is over — close its
                    # trace instead of waiting for FIFO eviction
                    TRACER.finish_pod(key, status="deleted")
                    return
                raise
            if pod.is_completed():
                sp.set_attr("action", "release_completed")
                self._release(pod)
                TRACER.finish_pod(key, status="completed")
            elif pod.spec.node_name and is_assumed(pod):
                sp.set_attr("action", "assign")
                self._assign(pod)

    def _release(self, pod: Pod) -> None:
        """Reference: releasePod bridge (controller.go:301-307)."""
        sched = get_resource_scheduler(self.registry, pod)
        if sched is None:
            return
        if sched.released_pod(pod):
            return
        # source tags the flight-recorder record: a controller release
        # (pod completed/deleted) reads differently from a bind rollback
        # when auditing a journal offline
        sched.forget_pod(pod, source="controller_release")

    def _assign(self, pod: Pod) -> None:
        """Reference: assignPod bridge (controller.go:325-331)."""
        sched = get_resource_scheduler(self.registry, pod)
        if sched is None:
            return
        if sched.known_pod(pod):
            return
        sched.add_pod(pod, source="controller_assign")
