"""Deterministic stack VM for operator-loaded scheduling policies.

The programmable policy plane (ROADMAP item 4, gpu_ext direction) lets
operators hot-load placement logic into a RUNNING scheduler.  That is
only safe if a loaded policy can never take the bind path down with it,
so the execution model is deliberately tiny:

- straight-line stack bytecode compiled from a restricted expression
  language (``lang.py``) — no loops exist in the instruction set, so
  every program terminates by construction;
- a strict INSTRUCTION BUDGET (default 512, hard cap 4096) counted per
  executed instruction, plus a per-eval WALL DEADLINE checked every 64
  instructions — a pathological program (or a host stall under it)
  trips :class:`PolicyFault` instead of stretching a bind;
- typed read-only inputs: the caller passes a flat float vector laid
  out by the compiler's slot table; programs cannot reach anything the
  verb did not explicitly expose (no I/O, no state, no allocation of
  program-visible objects);
- total determinism: float arithmetic only, division/modulo by zero
  and non-finite results fault rather than propagate, so the same
  program on the same inputs yields bit-identical results across
  re-compiles and across replay (the what-if parity gate pins this).

Faults never escape to the verb: :class:`~.rater.PolicyRater` and the
verb hooks catch :class:`PolicyFault` and fall back to the incumbent
built-in, journaling a ``policy_fault`` annotation.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field

# -- instruction set ---------------------------------------------------------

(
    OP_CONST,   # push consts[arg]
    OP_LOAD,    # push inputs[arg]
    OP_ADD, OP_SUB, OP_MUL, OP_DIV, OP_MOD,
    OP_NEG, OP_NOT, OP_TRUTH,
    OP_LT, OP_LE, OP_GT, OP_GE, OP_EQ, OP_NE,
    OP_JMP,     # pc = arg
    OP_JMPF,    # pop; falsy → pc = arg
    OP_MIN, OP_MAX, OP_ABS, OP_FLOOR, OP_CEIL,
    OP_CLAMP,   # pop hi, lo, x → push min(max(x, lo), hi)
) = range(24)

OP_NAMES = {
    OP_CONST: "CONST", OP_LOAD: "LOAD", OP_ADD: "ADD", OP_SUB: "SUB",
    OP_MUL: "MUL", OP_DIV: "DIV", OP_MOD: "MOD", OP_NEG: "NEG",
    OP_NOT: "NOT", OP_TRUTH: "TRUTH", OP_LT: "LT", OP_LE: "LE",
    OP_GT: "GT", OP_GE: "GE", OP_EQ: "EQ", OP_NE: "NE", OP_JMP: "JMP",
    OP_JMPF: "JMPF", OP_MIN: "MIN", OP_MAX: "MAX", OP_ABS: "ABS",
    OP_FLOOR: "FLOOR", OP_CEIL: "CEIL", OP_CLAMP: "CLAMP",
}

DEFAULT_BUDGET = 512
MAX_BUDGET = 4096
DEFAULT_DEADLINE_S = 0.002  # 2ms: generous vs the ~µs a real eval takes,
# tight vs the bind path's own budget — a wedged host trips here, not there
_DEADLINE_STRIDE = 64  # instructions between perf_counter checks


class PolicyFault(Exception):
    """A policy program failed AT RUNTIME (budget, deadline, math, or a
    malformed stack).  Verb hooks catch this and fall back to the
    incumbent built-in — a fault is an annotation, never a failed bind."""

    def __init__(self, kind: str, detail: str = ""):
        super().__init__(f"{kind}: {detail}" if detail else kind)
        self.kind = kind
        self.detail = detail


@dataclass(frozen=True)
class Program:
    """Compiled policy bytecode.  Immutable; safe to share across
    threads (the VM keeps all mutable state on its own stack)."""

    code: tuple  # ((op, arg), ...)
    consts: tuple  # float literals
    slots: tuple  # input names in LOAD-slot order (first-use assigned)
    source: str
    budget: int = DEFAULT_BUDGET
    deadline_s: float = DEFAULT_DEADLINE_S
    fingerprint: str = field(default="", compare=False)
    # hot-path closure generated from the same AST (lang._build_py_fn),
    # present ONLY when the static instruction count fits the budget —
    # then neither budget nor deadline can trip at runtime (loop-free,
    # straight-line), so the closure and the interpreter are behavior-
    # identical (property-tested bit-for-bit).  Excluded from equality
    # and the fingerprint: the bytecode is the canonical form.
    py_fn: object = field(default=None, compare=False, repr=False)
    # the parsed AST the emitters consumed — kept so PolicyRater can
    # specialize a fused fills+expression rate function (lang.
    # build_filled_fn).  Same canonical-form stance as py_fn.
    ast: object = field(default=None, compare=False, repr=False)

    def disasm(self) -> list[str]:
        out = []
        for pc, (op, arg) in enumerate(self.code):
            name = OP_NAMES.get(op, f"OP{op}")
            if op == OP_CONST:
                out.append(f"{pc:3d} {name} {self.consts[arg]!r}")
            elif op == OP_LOAD:
                out.append(f"{pc:3d} {name} {self.slots[arg]}")
            elif op in (OP_JMP, OP_JMPF):
                out.append(f"{pc:3d} {name} ->{arg}")
            else:
                out.append(f"{pc:3d} {name}")
        return out


def evaluate(program: Program, inputs) -> float:
    """Hot-path entry: the generated closure when the program qualifies
    (static size ≤ budget — see ``Program.py_fn``), the interpreter
    otherwise.  Identical results and fault semantics either way."""
    fn = program.py_fn
    if fn is None:
        return run(program, inputs)
    try:
        result = fn(inputs)
    except PolicyFault:
        raise
    except OverflowError:
        raise PolicyFault("math", "overflow") from None
    except Exception as e:  # defensive: closure bugs must fault, not leak
        raise PolicyFault("fill", str(e)) from None
    if not math.isfinite(result):
        raise PolicyFault("math", "non-finite result")
    return result


def run(program: Program, inputs) -> float:
    """Evaluate ``program`` over the input vector (floats, laid out per
    ``program.slots``).  Raises :class:`PolicyFault` on budget trip,
    deadline trip, math fault (div/mod by zero, non-finite result) or a
    malformed program.  The hot loop allocates only Python floats and
    one stack list — steady-state allocation is flat (pinned by the
    property tests)."""
    code = program.code
    consts = program.consts
    budget = program.budget
    deadline_s = program.deadline_s
    stack: list = []
    push = stack.append
    pop = stack.pop
    pc = 0
    ncode = len(code)
    executed = 0
    t0 = time.perf_counter() if deadline_s else 0.0
    try:
        while pc < ncode:
            executed += 1
            if executed > budget:
                raise PolicyFault(
                    "budget", f"exceeded {budget} instructions"
                )
            if deadline_s and executed % _DEADLINE_STRIDE == 0:
                if time.perf_counter() - t0 > deadline_s:
                    raise PolicyFault(
                        "deadline", f"exceeded {deadline_s * 1e3:.1f}ms"
                    )
            op, arg = code[pc]
            pc += 1
            if op == OP_LOAD:
                push(inputs[arg])
            elif op == OP_CONST:
                push(consts[arg])
            elif op == OP_ADD:
                b = pop(); push(pop() + b)
            elif op == OP_SUB:
                b = pop(); push(pop() - b)
            elif op == OP_MUL:
                b = pop(); push(pop() * b)
            elif op == OP_DIV:
                b = pop()
                if b == 0.0:
                    raise PolicyFault("math", "division by zero")
                push(pop() / b)
            elif op == OP_MOD:
                b = pop()
                if b == 0.0:
                    raise PolicyFault("math", "modulo by zero")
                push(math.fmod(pop(), b))
            elif op == OP_NEG:
                push(-pop())
            elif op == OP_NOT:
                push(1.0 if pop() == 0.0 else 0.0)
            elif op == OP_TRUTH:
                push(0.0 if pop() == 0.0 else 1.0)
            elif op == OP_LT:
                b = pop(); push(1.0 if pop() < b else 0.0)
            elif op == OP_LE:
                b = pop(); push(1.0 if pop() <= b else 0.0)
            elif op == OP_GT:
                b = pop(); push(1.0 if pop() > b else 0.0)
            elif op == OP_GE:
                b = pop(); push(1.0 if pop() >= b else 0.0)
            elif op == OP_EQ:
                b = pop(); push(1.0 if pop() == b else 0.0)
            elif op == OP_NE:
                b = pop(); push(1.0 if pop() != b else 0.0)
            elif op == OP_JMP:
                pc = arg
            elif op == OP_JMPF:
                if pop() == 0.0:
                    pc = arg
            elif op == OP_MIN:
                b = pop(); a = pop(); push(a if a <= b else b)
            elif op == OP_MAX:
                b = pop(); a = pop(); push(a if a >= b else b)
            elif op == OP_ABS:
                push(abs(pop()))
            elif op == OP_FLOOR:
                push(float(math.floor(pop())))
            elif op == OP_CEIL:
                push(float(math.ceil(pop())))
            elif op == OP_CLAMP:
                hi = pop(); lo = pop(); x = pop()
                if x < lo:
                    x = lo
                if x > hi:
                    x = hi
                push(x)
            else:  # pragma: no cover - compiler never emits unknown ops
                raise PolicyFault("op", f"unknown opcode {op}")
    except IndexError:
        raise PolicyFault("stack", "stack underflow") from None
    except OverflowError:
        raise PolicyFault("math", "overflow") from None
    if len(stack) != 1:
        raise PolicyFault("stack", f"ended with {len(stack)} values")
    result = stack[0]
    if not math.isfinite(result):
        raise PolicyFault("math", "non-finite result")
    return result
