"""The policy expression language: lexer, parser, compiler.

One expression per policy — no statements, no loops, no assignment.
Grammar (C-ish precedence, short-circuit logical ops and ternary):

    expr    := or ('?' expr ':' expr)?
    or      := and (('or' | '||') and)*
    and     := not (('and' | '&&') not)*
    not     := ('not' | '!') not | cmp
    cmp     := sum (('<' '<=' '>' '>=' '==' '!=') sum)?
    sum     := term (('+' | '-') term)*
    term    := unary (('*' | '/' | '%') unary)*
    unary   := '-' unary | atom
    atom    := NUMBER | NAME | FUNC '(' expr (',' expr)* ')' | '(' expr ')'

Booleans are floats (true = 1.0, false = 0.0; anything non-zero is
truthy).  ``?:``, ``and`` and ``or`` SHORT-CIRCUIT — the untaken branch
is never executed, so ``x != 0 ? y / x : 0`` is total even at x == 0.
Functions: ``min``/``max`` (2+ args), ``abs``, ``floor``, ``ceil``,
``clamp(x, lo, hi)``.  Constants: ``true``, ``false``.

Every NAME must be one of the verb's declared inputs (``rater.py``
documents the per-verb tables); an unknown name is a COMPILE error, so
a typo can never become a silent 0.0 at runtime.  Left-associative
``+``/``*`` compile in source order, which is what makes a policy
spelling out the built-in binpack formula score BIT-IDENTICAL to it.

The compiler parses to a small AST and emits it TWICE:

- stack bytecode for the :mod:`.vm` interpreter — the auditable,
  budget-enforced canonical form (``Program.disasm``, fingerprints,
  the runtime instruction budget + wall deadline);
- when the program's STATIC instruction count fits its budget (so the
  budget could never trip at runtime — the code is loop-free and
  straight-line, so executed ≤ static), a restricted Python closure
  over the same input vector, used on the bind hot path.  The closure
  is generated from the AST (never from operator text), sees no
  builtins beyond the arithmetic helpers, and preserves fault
  semantics exactly (division by zero / non-finite results raise
  :class:`~.vm.PolicyFault`).  Property tests pin closure ≡ VM
  bit-identical on random programs and inputs.
"""

from __future__ import annotations

import hashlib
import math

from .vm import (
    DEFAULT_BUDGET,
    DEFAULT_DEADLINE_S,
    MAX_BUDGET,
    OP_ABS,
    OP_ADD,
    OP_CEIL,
    OP_CLAMP,
    OP_CONST,
    OP_DIV,
    OP_EQ,
    OP_FLOOR,
    OP_GE,
    OP_GT,
    OP_JMP,
    OP_JMPF,
    OP_LE,
    OP_LOAD,
    OP_LT,
    OP_MAX,
    OP_MIN,
    OP_MOD,
    OP_MUL,
    OP_NE,
    OP_NEG,
    OP_NOT,
    OP_SUB,
    OP_TRUTH,
    PolicyFault,
    Program,
)

MAX_SOURCE = 4096
MAX_TOKENS = 1024
MAX_DEPTH = 32

_FUNCS = {"abs": 1, "floor": 1, "ceil": 1, "min": 2, "max": 2, "clamp": 3}
_FUNC_MAX_ARGS = {"abs": 1, "floor": 1, "ceil": 1, "min": 16, "max": 16,
                  "clamp": 3}
_KEYWORDS = {"and", "or", "not", "true", "false"}
_CMP_OPS = ("<", "<=", ">", ">=", "==", "!=")
_PUNCT = (
    "<=", ">=", "==", "!=", "&&", "||",
    "+", "-", "*", "/", "%", "(", ")", ",", "?", ":", "<", ">", "!",
)


class CompileError(ValueError):
    """Source rejected at compile time (syntax, unknown input, size)."""

    def __init__(self, msg: str, pos: int = -1):
        super().__init__(f"{msg} (at offset {pos})" if pos >= 0 else msg)
        self.pos = pos


def _lex(src: str) -> list[tuple[str, object, int]]:
    """(kind, value, pos) stream; kind in num|name|punct."""
    toks: list[tuple[str, object, int]] = []
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c in " \t\r\n":
            i += 1
            continue
        if c == "#":  # comment to end of line
            while i < n and src[i] != "\n":
                i += 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and src[i + 1].isdigit()):
            j = i
            while j < n and (src[j].isdigit() or src[j] in ".eE" or (
                src[j] in "+-" and src[j - 1] in "eE"
            )):
                j += 1
            try:
                val = float(src[i:j])
            except ValueError:
                raise CompileError(f"bad number {src[i:j]!r}", i) from None
            toks.append(("num", val, i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (src[j].isalnum() or src[j] == "_"):
                j += 1
            toks.append(("name", src[i:j], i))
            i = j
            continue
        for p in _PUNCT:
            if src.startswith(p, i):
                toks.append(("punct", p, i))
                i += len(p)
                break
        else:
            raise CompileError(f"unexpected character {c!r}", i)
        if len(toks) > MAX_TOKENS:
            raise CompileError(f"expression exceeds {MAX_TOKENS} tokens")
    return toks


# -- parser (tokens → AST) ---------------------------------------------------
#
# AST nodes are plain tuples:
#   ("num", float) ("load", slot) ("neg", a) ("not", a)
#   ("bin", op_str, a, b)  op_str in + - * / % < <= > >= == !=
#   ("and", a, b) ("or", a, b) ("ternary", cond, a, b)
#   ("call", name, [args])


class _Parser:
    def __init__(self, toks, input_names):
        self.toks = toks
        self.pos = 0
        self.input_names = frozenset(input_names)
        self.slots: list[str] = []  # first-use order
        self.slot_idx: dict[str, int] = {}
        self.depth = 0

    def _peek(self):
        return self.toks[self.pos] if self.pos < len(self.toks) else None

    def _next(self):
        t = self._peek()
        if t is None:
            raise CompileError("unexpected end of expression")
        self.pos += 1
        return t

    def _accept(self, *punct):
        t = self._peek()
        if t is not None and t[0] == "punct" and t[1] in punct:
            self.pos += 1
            return t[1]
        return None

    def _accept_name(self, *names):
        t = self._peek()
        if t is not None and t[0] == "name" and t[1] in names:
            self.pos += 1
            return t[1]
        return None

    def _expect(self, punct):
        if self._accept(punct) is None:
            t = self._peek()
            raise CompileError(f"expected {punct!r}", t[2] if t else -1)

    def _enter(self):
        self.depth += 1
        if self.depth > MAX_DEPTH:
            raise CompileError(f"expression nests deeper than {MAX_DEPTH}")

    def expr(self):
        self._enter()
        node = self._or()
        if self._accept("?"):
            then = self.expr()
            self._expect(":")
            node = ("ternary", node, then, self.expr())
        self.depth -= 1
        return node

    def _or(self):
        node = self._and()
        while self._accept("||") or self._accept_name("or"):
            node = ("or", node, self._and())
        return node

    def _and(self):
        node = self._not()
        while self._accept("&&") or self._accept_name("and"):
            node = ("and", node, self._not())
        return node

    def _not(self):
        self._enter()
        if self._accept("!") or self._accept_name("not"):
            node = ("not", self._not())
        else:
            node = self._cmp()
        self.depth -= 1
        return node

    def _cmp(self):
        node = self._sum()
        t = self._peek()
        if t is not None and t[0] == "punct" and t[1] in _CMP_OPS:
            self.pos += 1
            node = ("bin", t[1], node, self._sum())
        return node

    def _sum(self):
        node = self._term()
        while True:
            op = self._accept("+", "-")
            if op is None:
                return node
            node = ("bin", op, node, self._term())

    def _term(self):
        node = self._unary()
        while True:
            op = self._accept("*", "/", "%")
            if op is None:
                return node
            node = ("bin", op, node, self._unary())

    def _unary(self):
        self._enter()
        if self._accept("-"):
            node = ("neg", self._unary())
        else:
            node = self._atom()
        self.depth -= 1
        return node

    def _atom(self):
        t = self._next()
        kind, val, pos = t
        if kind == "num":
            return ("num", float(val))
        if kind == "punct" and val == "(":
            node = self.expr()
            self._expect(")")
            return node
        if kind == "name":
            if val == "true":
                return ("num", 1.0)
            if val == "false":
                return ("num", 0.0)
            if val in _FUNCS:
                self._expect("(")
                args = [self.expr()]
                while self._accept(","):
                    args.append(self.expr())
                self._expect(")")
                lo, hi = _FUNCS[val], _FUNC_MAX_ARGS[val]
                if not lo <= len(args) <= hi:
                    raise CompileError(
                        f"{val}() takes {lo}..{hi} args, got {len(args)}",
                        pos,
                    )
                return ("call", val, args)
            if val in _KEYWORDS:
                raise CompileError(f"misplaced keyword {val!r}", pos)
            if val not in self.input_names:
                raise CompileError(
                    f"unknown input {val!r}; this verb exposes "
                    f"{sorted(self.input_names)}", pos,
                )
            idx = self.slot_idx.get(val)
            if idx is None:
                idx = len(self.slots)
                self.slots.append(val)
                self.slot_idx[val] = idx
            return ("load", idx)
        raise CompileError(f"unexpected token {val!r}", pos)


# -- bytecode emitter (AST → VM code) ----------------------------------------

_BIN_OPS = {
    "+": OP_ADD, "-": OP_SUB, "*": OP_MUL, "/": OP_DIV, "%": OP_MOD,
    "<": OP_LT, "<=": OP_LE, ">": OP_GT, ">=": OP_GE,
    "==": OP_EQ, "!=": OP_NE,
}
_CALL_OPS = {"abs": OP_ABS, "floor": OP_FLOOR, "ceil": OP_CEIL,
             "min": OP_MIN, "max": OP_MAX, "clamp": OP_CLAMP}


class _BytecodeEmitter:
    def __init__(self):
        self.code: list[list] = []
        self.consts: list[float] = []
        self.const_idx: dict[float, int] = {}

    def _emit(self, op, arg=0) -> int:
        self.code.append([op, arg])
        return len(self.code) - 1

    def _const(self, val: float):
        idx = self.const_idx.get(val)
        if idx is None:
            idx = len(self.consts)
            self.consts.append(float(val))
            self.const_idx[val] = idx
        self._emit(OP_CONST, idx)

    def emit(self, node) -> None:
        kind = node[0]
        if kind == "num":
            self._const(node[1])
        elif kind == "load":
            self._emit(OP_LOAD, node[1])
        elif kind == "neg":
            self.emit(node[1])
            self._emit(OP_NEG)
        elif kind == "not":
            self.emit(node[1])
            self._emit(OP_NOT)
        elif kind == "bin":
            self.emit(node[2])
            self.emit(node[3])
            self._emit(_BIN_OPS[node[1]])
        elif kind == "and":
            # a and b → truthy(a) ? truthy(b) : 0   (short-circuit)
            self.emit(node[1])
            jf = self._emit(OP_JMPF)
            self.emit(node[2])
            self._emit(OP_TRUTH)
            je = self._emit(OP_JMP)
            self.code[jf][1] = len(self.code)
            self._const(0.0)
            self.code[je][1] = len(self.code)
        elif kind == "or":
            # a or b → truthy(a) ? 1 : truthy(b)   (short-circuit)
            self.emit(node[1])
            jf = self._emit(OP_JMPF)
            self._const(1.0)
            je = self._emit(OP_JMP)
            self.code[jf][1] = len(self.code)
            self.emit(node[2])
            self._emit(OP_TRUTH)
            self.code[je][1] = len(self.code)
        elif kind == "ternary":
            self.emit(node[1])
            jf = self._emit(OP_JMPF)
            self.emit(node[2])
            je = self._emit(OP_JMP)
            self.code[jf][1] = len(self.code)
            self.emit(node[3])
            self.code[je][1] = len(self.code)
        elif kind == "call":
            fn, args = node[1], node[2]
            for a in args:
                self.emit(a)
            op = _CALL_OPS[fn]
            if fn in ("min", "max"):
                for _ in range(len(args) - 1):
                    self._emit(op)  # left fold
            else:
                self._emit(op)
        else:  # pragma: no cover - parser emits no other kinds
            raise CompileError(f"internal: unknown AST node {kind!r}")


# -- Python-closure emitter (AST → restricted source) ------------------------
#
# Fault-semantics helpers: the generated source calls ONLY these names
# (plus min/max/abs, which cannot fault on finite floats); the closure's
# globals carry nothing else — no builtins, no attribute access, no
# names the generator didn't put there.


def _pf_div(a: float, b: float) -> float:
    if b == 0.0:
        raise PolicyFault("math", "division by zero")
    return a / b


def _pf_mod(a: float, b: float) -> float:
    if b == 0.0:
        raise PolicyFault("math", "modulo by zero")
    return math.fmod(a, b)


def _pf_min(a: float, b: float) -> float:
    # EXACTLY the VM's OP_MIN (`a if a <= b else b`) — Python's min()
    # diverges on NaN intermediates (min(nan, 1) is nan, the VM says 1),
    # and the closure must stay bit-identical to the interpreter
    return a if a <= b else b


def _pf_max(a: float, b: float) -> float:
    return a if a >= b else b


def _pf_clamp(x: float, lo: float, hi: float) -> float:
    if x < lo:
        x = lo
    if x > hi:
        x = hi
    return x


def _pf_floor(x: float) -> float:
    return float(math.floor(x))


def _pf_ceil(x: float) -> float:
    return float(math.ceil(x))


_PY_GLOBALS = {
    "__builtins__": {},
    "_div": _pf_div,
    "_mod": _pf_mod,
    "_clamp": _pf_clamp,
    "_floor": _pf_floor,
    "_ceil": _pf_ceil,
    "_min": _pf_min,
    "_max": _pf_max,
    "abs": abs,
}


def _load_vec(i: int) -> str:
    return f"_i[{i}]"


def _py_src(node, load=_load_vec) -> str:
    kind = node[0]
    if kind == "num":
        return repr(node[1])
    if kind == "load":
        return load(node[1])
    if kind == "neg":
        return f"(-{_py_src(node[1], load)})"
    if kind == "not":
        return f"(1.0 if {_py_src(node[1], load)} == 0.0 else 0.0)"
    if kind == "bin":
        op = node[1]
        a, b = _py_src(node[2], load), _py_src(node[3], load)
        if op == "/":
            return f"_div({a}, {b})"
        if op == "%":
            return f"_mod({a}, {b})"
        if op in ("+", "-", "*"):
            return f"({a} {op} {b})"
        return f"(1.0 if {a} {op} {b} else 0.0)"
    if kind == "and":
        a, b = _py_src(node[1], load), _py_src(node[2], load)
        return f"((0.0 if {b} == 0.0 else 1.0) if {a} != 0.0 else 0.0)"
    if kind == "or":
        a, b = _py_src(node[1], load), _py_src(node[2], load)
        return f"(1.0 if {a} != 0.0 else (0.0 if {b} == 0.0 else 1.0))"
    if kind == "ternary":
        c = _py_src(node[1], load)
        a, b = _py_src(node[2], load), _py_src(node[3], load)
        return f"({a} if {c} != 0.0 else {b})"
    if kind == "call":
        fn = node[1]
        args = [_py_src(a, load) for a in node[2]]
        if fn in ("min", "max"):
            # left fold through the VM-exact pairwise helpers (Python's
            # own min/max disagree with OP_MIN/OP_MAX on NaN)
            out = args[0]
            for a in args[1:]:
                out = f"_{fn}({out}, {a})"
            return out
        if fn == "abs":
            return f"abs({args[0]})"
        return f"_{fn}({', '.join(args)})"  # _floor/_ceil/_clamp
    raise CompileError(f"internal: unknown AST node {kind!r}")


def _build_py_fn(ast, n_slots: int):
    """AST → closure over the input vector, with the SAME fault
    semantics as the VM (PolicyFault on div/mod-by-zero; the caller
    checks finiteness).  Returns None if generation fails for any
    reason — the interpreter is always the safe fallback."""
    try:
        src = f"lambda _i: ({_py_src(ast)})"
        return eval(compile(src, "<policy>", "eval"), dict(_PY_GLOBALS))
    except Exception:  # pragma: no cover - generator bug → interpret
        return None


def build_filled_fn(program: Program, fills):
    """Fuse a score program with its input fills into ONE generated
    function ``f(rater, chips, option) -> float`` — the bind-path form:
    each referenced input is computed once into a local, then the
    expression evaluates inline (no input vector, no second dispatch).
    Same restricted globals and fault semantics as ``py_fn``; only
    built for programs whose static size fits the budget (the same
    can-never-trip-at-runtime condition), and the caller still applies
    the finiteness check + PolicyFault fallback.  Returns None when
    ineligible — the interpreter path is always correct."""
    if program.ast is None or program.py_fn is None:
        return None
    try:
        lines = [
            f"    _v{i} = _f{i}(_r, _ch, _o)" for i in range(len(fills))
        ]
        body = _py_src(program.ast, load=lambda i: f"_v{i}")
        src = (
            "def _rate(_r, _ch, _o):\n"
            + ("\n".join(lines) + "\n" if lines else "")
            + f"    return ({body})\n"
        )
        g = dict(_PY_GLOBALS)
        for i, f in enumerate(fills):
            g[f"_f{i}"] = f
        exec(compile(src, "<policy-rate>", "exec"), g)
        return g["_rate"]
    except Exception:  # pragma: no cover - generator bug → slow path
        return None


def compile_expr(
    source: str,
    input_names,
    budget: int = DEFAULT_BUDGET,
    deadline_s: float = DEFAULT_DEADLINE_S,
) -> Program:
    """Compile one policy expression against a verb's input table.
    Raises :class:`CompileError`; never executes anything.

    The returned Program carries a hot-path Python closure (``py_fn``)
    ONLY when its static instruction count fits ``budget`` — a program
    that could trip the runtime budget always runs interpreted, so the
    budget fault stays a real, testable runtime behavior."""
    if not isinstance(source, str) or not source.strip():
        raise CompileError("empty expression")
    if len(source) > MAX_SOURCE:
        raise CompileError(f"source exceeds {MAX_SOURCE} chars")
    budget = max(1, min(int(budget), MAX_BUDGET))
    toks = _lex(source)
    parser = _Parser(toks, input_names)
    ast = parser.expr()
    if parser.pos != len(toks):
        t = parser.toks[parser.pos]
        raise CompileError(f"trailing input {t[1]!r}", t[2])
    em = _BytecodeEmitter()
    em.emit(ast)
    code = tuple((op, arg) for op, arg in em.code)
    consts = tuple(em.consts)
    slots = tuple(parser.slots)
    fp = hashlib.sha256(
        repr((code, consts, slots)).encode()
    ).hexdigest()[:16]
    py_fn = _build_py_fn(ast, len(slots)) if len(code) <= budget else None
    return Program(
        code=code, consts=consts, slots=slots, source=source,
        budget=budget, deadline_s=float(deadline_s), fingerprint=fp,
        py_fn=py_fn, ast=ast,
    )
