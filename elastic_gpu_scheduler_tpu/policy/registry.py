"""The policy registry / control plane: one process-global object
(``POLICIES``, the TRACER/JOURNAL/PROFILER pattern) that owns every
hot-loaded policy, the canary state machine, and the ONE rater-spec
parser both CLIs resolve through.

Verbs and their attachment points:

    score    the rater — scheduler bind/assume/score + gang planning
             (promoted policies replace the engine rater wholesale;
             canaries split the BIND path by deterministic pod hash)
    filter   per-node keep/reject after the built-in filter passes it
             (scheduler.assume + the gang prefilter)
    preempt  victim-group ranking in TPUUnitScheduler.preempt
    defrag   victim scoring in defrag's unblock/compact planners
    kv       serving KV-page preemption victim (server/inference.py)

Every decision an ACTIVE CANARY makes is journaled as a ``policy``
record; every runtime fault (any verb, any state) is journaled as a
``policy_fault`` annotation and falls back to the incumbent built-in.
The plane is zero-cost until a policy is loaded: each hook pays one
attribute check against an empty dict.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
import zlib
from typing import Optional

from ..core.rater import RATERS, get_rater
from ..journal import JOURNAL
from ..metrics import POLICY_EVALS, POLICY_EVENTS
from .lang import CompileError, compile_expr
from .promotion import SLOMonitor, replay_gate
from .rater import PolicyRater, VERB_INPUTS
from .vm import DEFAULT_BUDGET, PolicyFault, evaluate

VERBS = tuple(VERB_INPUTS)

_FAULT_JOURNAL_CAP = 64  # per loaded policy: faults are counted forever,
# journaled at most this many times (a hot broken policy must not flood
# the flight recorder)


class LoadedPolicy:
    """One compiled policy attached (staged/canary/active) to a verb."""

    def __init__(self, name: str, verb: str, program, source: str,
                 rater: Optional[PolicyRater] = None):
        self.name = name
        self.verb = verb
        self.program = program
        self.source = source
        self.rater = rater  # score verb only
        self.loaded_at = time.time()
        self.evals = 0
        self.faults = 0
        self.fault_kinds: dict[str, int] = {}
        self.journaled_faults = 0

    def snapshot(self) -> dict:
        out = {
            "name": self.name,
            "verb": self.verb,
            "source": self.source,
            "fingerprint": self.program.fingerprint,
            "budget": self.program.budget,
            "inputs": list(self.program.slots),
            "loaded_at": self.loaded_at,
            "evals": self.evals,
            "faults": self.faults,
            "fault_kinds": dict(self.fault_kinds),
        }
        if self.rater is not None:
            out["evals"] = self.rater.evals
            out["faults"] = self.rater.faults
            out["translation_invariant"] = self.rater.translation_invariant
            out["whole_chip_compact_first"] = (
                self.rater.whole_chip_compact_first
            )
        return out


def _gate_summary(gate: Optional[dict]) -> dict:
    """Compact, JSON-stable view of a replay-gate result (the full
    what-if dicts ride the load response; state keeps this)."""
    if gate is None:
        return {"pass": True, "reasons": ["gate skipped"]}
    out = {"pass": bool(gate["pass"]),
           "reasons": list(gate.get("reasons") or [])}
    if "tolerance" in gate:
        out["tolerance"] = gate["tolerance"]
    if gate.get("gate_faults"):
        out["gate_faults"] = gate["gate_faults"]
    for side in ("candidate", "incumbent"):
        d = gate.get(side)
        if d:
            out[side] = {
                k: d[k]
                for k in (
                    "rater", "binds", "placed", "unplaced",
                    "contiguous_frac", "final_frag_mean",
                    "mean_free_chip_frac", "mean_score",
                )
                if k in d
            }
    return out


def canary_bucket(pod_key: str) -> int:
    """Deterministic 0..9999 split bucket for a pod key — the SAME pod
    always lands on the same canary arm, across replicas and restarts."""
    return zlib.crc32(pod_key.encode()) % 10000


class PolicyPlane:
    """Registry + canary state machine + SLO watchdog for all verbs."""

    def __init__(self):
        self._lock = threading.Lock()
        # verb → LoadedPolicy (promoted / canarying); absent = built-in
        self.active: dict[str, LoadedPolicy] = {}
        self.canary: dict[str, LoadedPolicy] = {}
        self.canary_pct: dict[str, float] = {}
        self.gate_results: dict[str, dict] = {}
        # ONE SLO watchdog per canarying verb — loading a defrag policy
        # must not wipe a live score canary's accumulated regression
        # evidence (latency windows, frag baseline)
        self.slos: dict[str, SLOMonitor] = {}
        self.history: list[dict] = []  # load/gate/promote/rollback events
        # canary decision counters: verb → {candidate, incumbent, diverged}
        self.decisions: dict[str, dict] = {}
        self._slo_stride = 0
        self._orphan_faults_journaled = 0
        # serializes SLO evaluation: concurrent binds may stride into
        # check_slo together; the loser skips (the winner's verdict
        # covers it) instead of double-rolling-back
        self._slo_check_lock = threading.Lock()
        # engines this plane steers (weakrefs: tests build many stacks).
        # incumbent raters are remembered per engine so promote/rollback
        # can swap and restore.
        self._engines: "weakref.WeakSet" = weakref.WeakSet()
        self._incumbents: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary()
        )
        self.frag_provider = None  # callable → {node: (frag, largest)}
        self.gate_events_fn = None  # callable → journal event list | None

    # -- wiring ---------------------------------------------------------------

    def attach(self, engines) -> None:
        """Register scheduler engines; remembers each engine's CURRENT
        rater as the incumbent the plane falls back to."""
        for sched in engines:
            if sched in self._engines:
                continue
            self._engines.add(sched)
            self._incumbents[sched] = sched.rater
            sched.policies = self

    def incumbent_rater(self):
        """The rater a score candidate must beat — the PROMOTED policy
        when one is in force (the gate must not weaken to the original
        built-in after a promotion), the attach-time built-in
        otherwise."""
        act = self.active.get("score")
        if act is not None and act.rater is not None:
            return act.rater
        for sched in self._engines:
            inc = self._incumbents.get(sched)
            if inc is not None:
                return inc
        from ..core.rater import ICILocality

        return ICILocality()

    def reset(self) -> None:
        """Test hook: drop every policy and restore engine raters."""
        with self._lock:
            self.active.clear()
            self.canary.clear()
            self.canary_pct.clear()
            self.gate_results.clear()
            self.history.clear()
            self.decisions.clear()
            self.slos.clear()
        self._restore_engines()

    @property
    def slo(self) -> Optional[SLOMonitor]:
        """The score-verb canary's SLO monitor (the common case for
        tests and tools); per-verb monitors live in ``slos``."""
        return self.slos.get("score") or next(iter(self.slos.values()), None)

    # -- fast-path queries ----------------------------------------------------

    def wants(self, verb: str) -> bool:
        """One-dict-check gate the hooks pay when no policy is loaded."""
        return verb in self.active or verb in self.canary

    def decide(self, verb: str, key: str):
        """(policy | None, arm): which policy decides for this key.
        Promoted policies decide everything (arm ``active``); canaries
        take their pod-hash fraction (arm ``candidate``), the rest is
        the incumbent (arm ``incumbent``, still journaled for the
        divergence comparison).  The INCUMBENT of a canary is whatever
        was in force before it — the promoted active policy when one
        exists, the built-in otherwise — so staging a candidate never
        silently un-enforces a promoted policy on the incumbent arm."""
        pol = self.canary.get(verb)
        if pol is not None:
            frac = self.canary_pct.get(verb, 0.0)
            if canary_bucket(key) < frac * 100.0:
                return pol, "candidate"
            return self.active.get(verb), "incumbent"
        pol = self.active.get(verb)
        if pol is not None:
            return pol, "active"
        return None, "builtin"

    # -- load / gate / canary / promote / rollback ----------------------------

    def load(
        self,
        name: str,
        verb: str,
        expr: str,
        canary_pct: float = 10.0,
        tolerance: float = 0.02,
        budget: int = DEFAULT_BUDGET,
        translation_invariant: bool = False,
        whole_chip_compact_first: bool = False,
        gate_events: Optional[list] = None,
        skip_gate: bool = False,
    ) -> dict:
        """Stage a candidate: compile, replay-gate (score verb), then
        canary.  Returns {"state": blocked|canary, "gate": ...}.  A
        blocked candidate leaves the plane untouched."""
        if verb not in VERBS:
            raise ValueError(f"unknown verb {verb!r}; choose from {VERBS}")
        program = compile_expr(expr, VERB_INPUTS[verb], budget=budget)
        rater = None
        if verb == "score":
            rater = PolicyRater(
                program,
                fallback=self.incumbent_rater(),
                name=name,
                translation_invariant=translation_invariant,
                whole_chip_compact_first=whole_chip_compact_first,
                on_fault=self.note_fault,
            )
        pol = LoadedPolicy(name, verb, program, expr, rater=rater)
        POLICY_EVENTS.inc("load")
        self._journal(
            "load", verb=verb, name=name,
            fingerprint=program.fingerprint,
        )

        gate = None
        if verb == "score" and not skip_gate:
            events = gate_events
            if events is None and self.gate_events_fn is not None:
                events = self.gate_events_fn()
            if events is None:
                gate = {
                    "pass": False,
                    "reasons": [
                        "no recorded workload to gate against (enable "
                        "the journal or pass skip_gate explicitly)"
                    ],
                }
            else:
                # the gate is an OFFLINE evaluation: a candidate that
                # faults on every recorded bind must not write one
                # policy_fault per eval into the LIVE flight recorder
                # (nor count live-fault metrics) — count locally, report
                # in the gate summary, restore the live hook after
                gate_faults = [0]
                rater.on_fault = (
                    lambda _v, _n, _e: gate_faults.__setitem__(
                        0, gate_faults[0] + 1
                    )
                )
                try:
                    gate = replay_gate(
                        events, rater, self.incumbent_rater(),
                        tolerance=tolerance,
                    )
                finally:
                    rater.on_fault = self.note_fault
                if gate_faults[0]:
                    gate["gate_faults"] = gate_faults[0]
                    gate.setdefault("reasons", [])
                    if gate["pass"]:
                        gate["pass"] = False
                        gate["reasons"].append(
                            f"candidate faulted {gate_faults[0]} time(s) "
                            "during replay (fallback scores gated it "
                            "through; a faulting policy must not ship)"
                        )
            self._journal(
                "gate", verb=verb, name=name,
                passed=bool(gate["pass"]),
                reasons=gate.get("reasons") or None,
            )
            if not gate["pass"]:
                POLICY_EVENTS.inc("gate_block")
                self._note_history("gate_block", verb, name,
                                   reasons=gate["reasons"])
                return {"state": "blocked", "name": name, "verb": verb,
                        "gate": _gate_summary(gate)}
            POLICY_EVENTS.inc("gate_pass")

        # preempt/defrag/kv have no per-pod split surface (a defrag
        # round or page-pool eviction is not keyed by a pod hash), so a
        # staged policy there decides EVERY operation: report 100%
        # honestly instead of echoing a fraction that is not enforced
        pct = max(0.0, min(100.0, float(canary_pct)))
        if verb not in ("score", "filter"):
            pct = 100.0
        monitor = SLOMonitor()
        with self._lock:
            self.canary[verb] = pol
            self.canary_pct[verb] = pct
            self.gate_results[verb] = _gate_summary(gate) if gate else {
                "pass": True, "reasons": ["gate skipped"],
            }
            self.decisions[verb] = {
                "candidate": 0, "incumbent": 0, "diverged": 0,
            }
            self.slos[verb] = monitor
        if self.frag_provider is not None:
            monitor.set_frag_baseline(self._mean_frag())
        self._journal("canary", verb=verb, name=name, pct=pct)
        self._note_history("canary", verb, name, pct=pct)
        return {"state": "canary", "name": name, "verb": verb,
                "canary_pct": pct,
                "gate": _gate_summary(gate) if gate else None}

    def promote(self, verb: str) -> dict:
        with self._lock:
            pol = self.canary.pop(verb, None)
            if pol is None:
                raise ValueError(f"no canary staged for verb {verb!r}")
            self.active[verb] = pol
            self.canary_pct.pop(verb, None)
            self.slos.pop(verb, None)
        POLICY_EVENTS.inc("promote")
        self._journal("promote", verb=verb, name=pol.name)
        self._note_history("promote", verb, pol.name)
        if verb == "score":
            self._swap_engine_raters(pol.rater)
        return {"state": "active", "name": pol.name, "verb": verb}

    def rollback(self, verb: str, reason: str = "operator",
                 auto: bool = False) -> dict:
        with self._lock:
            pol = self.canary.pop(verb, None) or self.active.pop(verb, None)
            self.canary_pct.pop(verb, None)
            self.slos.pop(verb, None)
            if pol is None:
                raise ValueError(f"nothing loaded for verb {verb!r}")
        POLICY_EVENTS.inc("rollback")
        self._journal(
            "rollback", verb=verb, name=pol.name, reason=reason,
            auto=auto or None,
        )
        self._note_history("rollback", verb, pol.name, reason=reason,
                           auto=auto)
        if verb == "score":
            # a rolled-back CANARY must not dethrone a still-promoted
            # active policy; only when nothing is left does the engine
            # rater return to the incumbent built-in
            act = self.active.get("score")
            if act is not None:
                self._swap_engine_raters(act.rater)
            else:
                self._restore_engines()
        return {"state": "builtin", "rolled_back": pol.name, "verb": verb,
                "reason": reason}

    def _swap_engine_raters(self, rater) -> None:
        for sched in list(self._engines):
            with sched.lock:
                sched.rater = rater
                idx = getattr(sched, "index", None)
            if idx is not None:
                # the congruence-class memo caches SCORES from the old
                # rater keyed by node state — state won't change at the
                # swap instant, so flush it
                with idx._lock:
                    idx._memo.clear()

    def _restore_engines(self) -> None:
        for sched in list(self._engines):
            inc = self._incumbents.get(sched)
            if inc is None:
                continue
            with sched.lock:
                sched.rater = inc
                idx = getattr(sched, "index", None)
            if idx is not None:
                with idx._lock:
                    idx._memo.clear()

    # -- live-bind canary plumbing (score verb) -------------------------------

    def score_rater_for(self, pod_key: str, incumbent):
        """(rater, decision | None) for one bind.  A decision dict means
        a canary is live and this bind must be journaled + SLO-fed."""
        pol, arm = self.decide("score", pod_key)
        if arm == "candidate" and pol is not None and pol.rater is not None:
            return pol.rater, {"arm": arm, "policy": pol}
        if arm == "incumbent":
            # a rollback racing this bind may clear the canary between
            # decide() and here — then there is nothing to journal
            cur = self.canary.get("score")
            return incumbent, (
                {"arm": arm, "policy": cur} if cur is not None else None
            )
        if arm == "active" and pol is not None and pol.rater is not None:
            return pol.rater, None
        return incumbent, None

    def note_bind_decision(
        self, decision: dict, pod_key: str, node: str, opt,
        latency_s: float, na, incumbent,
    ) -> None:
        """Journal one canary bind decision with the cross-scored
        divergence (the OTHER arm's rating of the chosen placement),
        feed the SLO monitor, and periodically evaluate rollback."""
        pol = decision.get("policy")
        if pol is None:
            return
        arm = decision["arm"]
        chosen = opt.score
        other_rater = incumbent if arm == "candidate" else pol.rater
        try:
            with na.lock:
                other = other_rater.rate(na.chips, opt)
        except Exception:
            other = chosen
        divergence = abs(chosen - other)
        with self._lock:
            d = self.decisions.setdefault(
                "score", {"candidate": 0, "incumbent": 0, "diverged": 0}
            )
            d[arm] = d.get(arm, 0) + 1
            if divergence > 1e-9:
                d["diverged"] += 1
        POLICY_EVALS.inc("score", arm)
        self._journal(
            "canary_decide", verb="score", name=pol.name, pod=pod_key,
            node=node, arm=arm, score=round(chosen, 6),
            score_other=round(other, 6),
            divergence=round(divergence, 6),
        )
        slo = self.slos.get("score")
        if slo is not None:
            slo.note_latency(arm, latency_s)
            self._slo_stride += 1
            if self._slo_stride % 8 == 0:
                self.check_slo()

    def note_filter_decision(self, arm: str, kept: int, total: int) -> None:
        """Feed the filter canary's SLO monitor (per-arm kept/total
        candidate-node counts) and periodically evaluate rollback —
        a filter-only canary has no bind decisions to ride, so its
        watchdog strides HERE."""
        slo = self.slos.get("filter")
        if slo is None or arm not in ("candidate", "incumbent"):
            return
        slo.note_filter(arm, kept, total)
        self._slo_stride += 1
        if self._slo_stride % 8 == 0:
            self.check_slo()

    def check_slo(self) -> Optional[dict]:
        """Evaluate every canarying verb's SLO monitor; a regression
        auto-rolls back THAT verb's CANARY only (and reports why).
        No-op without a live canary.  Concurrency-safe: racing binds
        striding in together serialize on a try-lock (the loser skips —
        the winner's verdict covers it), and the rollback targets the
        canary atomically so a lost race can neither dethrone a
        promoted active policy nor raise out of a bind."""
        if not self._slo_check_lock.acquire(blocking=False):
            return None
        try:
            out = None
            for verb in list(self.canary):
                slo = self.slos.get(verb)
                if slo is None:
                    continue
                if self.frag_provider is not None:
                    slo.note_frag(self._mean_frag())
                reason = slo.regressed()
                if reason is None:
                    continue
                out = self._rollback_canary(verb, reason) or out
            return out
        finally:
            self._slo_check_lock.release()

    def _rollback_canary(self, verb: str, reason: str) -> Optional[dict]:
        """Auto-rollback of a CANARY only — never touches a promoted
        active policy, returns None (instead of raising) if an operator
        rollback raced it away."""
        with self._lock:
            pol = self.canary.pop(verb, None)
            self.canary_pct.pop(verb, None)
            self.slos.pop(verb, None)
            if pol is None:
                return None
        POLICY_EVENTS.inc("rollback")
        self._journal("rollback", verb=verb, name=pol.name, reason=reason,
                      auto=True)
        self._note_history("rollback", verb, pol.name, reason=reason,
                           auto=True)
        if verb == "score":
            act = self.active.get("score")
            if act is not None:
                self._swap_engine_raters(act.rater)
            else:
                self._restore_engines()
        return {"state": "builtin" if verb not in self.active else "active",
                "rolled_back": pol.name, "verb": verb, "reason": reason}

    def _mean_frag(self) -> Optional[float]:
        try:
            snap = self.frag_provider()
        except Exception:
            return None
        if not snap:
            return None
        return sum(v[0] for v in snap.values()) / len(snap)

    # -- non-score verb evaluation --------------------------------------------

    def _eval(self, verb: str, pol: LoadedPolicy, inputs: dict):
        """Evaluate a non-score policy over an input dict; returns the
        float or None on fault (callers fall back to the built-in)."""
        pol.evals += 1
        if verb in self.slos and pol.evals % 16 == 0:
            # preempt/defrag/kv canaries have no bind or filter traffic
            # to ride — their SLO watchdog (frag regression vs the
            # canary-start baseline) strides on their own evaluations
            self.check_slo()
        try:
            vals = [float(inputs[n]) for n in pol.program.slots]
            out = evaluate(pol.program, vals)
            POLICY_EVALS.inc(verb, "ok")
            return out
        except PolicyFault as e:
            self.note_fault(verb, pol.name, e, pol=pol)
            return None
        except Exception as e:
            self.note_fault(verb, pol.name, PolicyFault("fill", str(e)),
                            pol=pol)
            return None

    def eval_filter(self, pol: LoadedPolicy, inputs: dict) -> bool:
        """truthy = keep the node; fault = keep (incumbent behavior is
        'the built-in filter already passed it')."""
        out = self._eval("filter", pol, inputs)
        return True if out is None else out != 0.0

    def preempt_score(self, inputs: dict) -> float:
        """Victim-group rank (HIGHER = evict first); built-in equivalent
        is ``-priority`` (evict the lowest-priority group first)."""
        pol = self.canary.get("preempt") or self.active.get("preempt")
        if pol is None:
            return -float(inputs.get("priority", 0.0))
        out = self._eval("preempt", pol, inputs)
        if out is None:
            return -float(inputs.get("priority", 0.0))
        return out

    def preempt_scores(self, infos: list) -> Optional[list]:
        """Score EVERY victim group or none: returns the score list, or
        None when no policy is loaded or ANY group faults — the caller
        then orders the whole set by the built-in rule (mixing policy
        scores with built-in key values in one sort would place the
        faulted groups arbitrarily; same stance as defrag's
        ``_order_victims``).  A staged canary takes precedence over a
        promoted policy (it is the one under evaluation)."""
        pol = self.canary.get("preempt") or self.active.get("preempt")
        if pol is None:
            return None
        out = []
        for info in infos:
            s = self._eval("preempt", pol, info)
            if s is None:
                return None
            out.append(s)
        return out

    def defrag_score(self, inputs: dict) -> Optional[float]:
        """Victim preference for the defrag planners (HIGHER = move
        first); None on fault or no policy → caller's built-in order."""
        pol = self.canary.get("defrag") or self.active.get("defrag")
        if pol is None:
            return None
        return self._eval("defrag", pol, inputs)

    def select_kv_victim(self, slots: list[dict]) -> int:
        """Pick the serving KV-page preemption victim.  Built-in: the
        lowest-priority slot, most pages held as tiebreak (the historic
        hard-coded ``min(...)``).  With a loaded ``kv`` policy: the slot
        with the HIGHEST policy score (built-in on fault)."""
        pol = self.canary.get("kv") or self.active.get("kv")
        if pol is not None:
            best = None
            ok = True
            for info in slots:
                s = self._eval("kv", pol, info)
                if s is None:
                    ok = False
                    break
                if best is None or s > best[0]:
                    best = (s, int(info["slot"]))
            if ok and best is not None:
                return best[1]
        return int(min(
            slots, key=lambda i: (i["priority"], -i["pages"], i["slot"]),
        )["slot"])

    # -- fault + journal plumbing ---------------------------------------------

    def note_fault(self, verb: str, name: str, fault: PolicyFault,
                   pol: Optional[LoadedPolicy] = None) -> None:
        """Count + journal one policy runtime fault (budget trip,
        deadline, math).  The caller has already fallen back to the
        incumbent — this is the annotation trail, never control flow."""
        if pol is None:
            pol = self.canary.get(verb) or self.active.get(verb)
            if pol is not None and pol.name != name:
                pol = None
        POLICY_EVALS.inc(verb, "fault")
        POLICY_EVENTS.inc("fault")
        if pol is not None:
            pol.faults += 1
            pol.fault_kinds[fault.kind] = (
                pol.fault_kinds.get(fault.kind, 0) + 1
            )
            if pol.journaled_faults >= _FAULT_JOURNAL_CAP:
                return
            pol.journaled_faults += 1
        else:
            # unattributable fault (raters held outside the plane, e.g.
            # resolve_rater file policies): same flood cap, one shared
            # budget — counting stays exact via the metric above
            self._orphan_faults_journaled += 1
            if self._orphan_faults_journaled > _FAULT_JOURNAL_CAP:
                return
        if JOURNAL.enabled:
            JOURNAL.record(
                "policy_fault", verb=verb, name=name, kind=fault.kind,
                detail=fault.detail[:200] if fault.detail else None,
            )

    def _journal(self, action: str, **fields) -> None:
        if JOURNAL.enabled:
            JOURNAL.record("policy", action=action, **fields)

    def _note_history(self, event: str, verb: str, name: str, **extra):
        entry = {"t": time.time(), "event": event, "verb": verb,
                 "name": name, **extra}
        with self._lock:
            self.history.append(entry)
            del self.history[:-50]

    # -- introspection --------------------------------------------------------

    def debug_state(self) -> dict:
        with self._lock:
            out = {
                "verbs": list(VERBS),
                "active": {
                    v: p.snapshot() for v, p in self.active.items()
                },
                "canary": {
                    v: dict(p.snapshot(), canary_pct=self.canary_pct.get(v))
                    for v, p in self.canary.items()
                },
                "gate_results": dict(self.gate_results),
                "decisions": {
                    v: dict(d) for v, d in self.decisions.items()
                },
                "history": list(self.history[-20:]),
            }
        slos = dict(self.slos)
        if slos:
            out["slo"] = {v: m.state() for v, m in slos.items()}
        out["inputs"] = {v: list(n) for v, n in VERB_INPUTS.items()}
        return out

    def divergence_pct(self, verb: str = "score") -> float:
        with self._lock:
            d = self.decisions.get(verb) or {}
            total = d.get("candidate", 0) + d.get("incumbent", 0)
            if not total:
                return 0.0
            return 100.0 * d.get("diverged", 0) / total


POLICIES = PolicyPlane()


def default_gate_events():
    """Read the live journal for the replay gate (flushes first so the
    gate sees every bind up to now)."""
    if not JOURNAL.enabled:
        return None
    from ..journal import read_journal

    JOURNAL.flush()
    if not JOURNAL.dir:
        return None
    return read_journal(JOURNAL.dir)


def resolve_rater(spec: str):
    """THE rater-spec parser — the scheduler CLI's ``--priority`` and
    the journal CLI's ``--rater`` both resolve through here (built-ins
    + profile-aware wrapping + loaded/file-backed policies):

        binpack | spread | random | ici-locality   built-in geometry
        profile-aware[:BASE]                        measured-behavior
                                                    scaling over BASE
        policy:NAME[:BASE]                          a policy loaded in
                                                    this process, or an
                                                    expression FILE
                                                    (BASE = fallback)
    """
    spec = (spec or "").strip()
    if not spec:
        raise ValueError("empty rater spec")
    head, _, rest = spec.partition(":")
    if head == "profile-aware":
        from ..profile.rater import ProfileAwareRater

        return ProfileAwareRater(get_rater(rest) if rest else None)
    if head == "policy":
        src, _, base = rest.partition(":")
        if not src:
            raise ValueError(
                "policy rater spec needs a name or file: policy:NAME[:BASE]"
            )
        fallback = get_rater(base) if base else None
        loaded = POLICIES.active.get("score") or POLICIES.canary.get("score")
        if loaded is not None and loaded.name == src:
            return loaded.rater
        if os.path.exists(src):
            with open(src) as f:
                expr = f.read()
            try:
                program = compile_expr(expr, VERB_INPUTS["score"])
            except CompileError as e:
                raise ValueError(f"policy file {src!r}: {e}") from None
            return PolicyRater(
                program, fallback=fallback,
                name=os.path.basename(src),
                # file policies live OUTSIDE the plane's registry, but
                # their live faults must still journal + count (the
                # orphan-fault cap in note_fault bounds the flood)
                on_fault=POLICIES.note_fault,
            )
        raise ValueError(
            f"policy {src!r}: not a loaded policy name or expression file"
        )
    if spec in RATERS:  # the FULL spec: 'binpack:v2' must error, not
        return RATERS[spec]  # silently resolve to binpack
    raise ValueError(
        f"unknown rater {spec!r}; choose from {sorted(RATERS)}, "
        "profile-aware[:BASE], or policy:NAME|FILE[:BASE]"
    )
