"""Policy-backed raters and the per-verb typed input tables.

Each verb exposes a FIXED read-only input vocabulary; the compiler
rejects any name outside it, and the fill functions below are the only
code that can touch live scheduler state on a policy's behalf.  All
inputs are floats (booleans are 1.0/0.0).

``score`` (the rater verb — rate a placement option against the
post-assignment chip state, same convention as ``core.rater``):

    node_used     node-level core utilization BEFORE the option, [0,1]
    chip_used     mean pre-assignment utilization of touched chips
                  (fractional allocs), [0,1]
    preserve      fully-free chips remaining / total chips, [0,1]
    locality      whole-box ICI compactness bonus, [0,1]
    free_chips    fully-free chips after the option (count)
    total_chips   chips on the node
    option_chips  chips this option touches
    whole         1.0 if every TPU alloc is whole-chip
    contiguous    1.0 if every TPU alloc is contiguous
    tput          this class' measured tokens/s/chip on the node's
                  generation, normalized by its best generation
                  (profile observatory; 1.0 when unprofiled)
    interference  the class' worst measured co-location ratio when the
                  placement shares chips (1.0 exclusive/unprofiled)
    base          the incumbent built-in rater's score for this option
                  (computed only when referenced)

A policy spelling out the built-in binpack formula —
``35*node_used + 30*chip_used + 25*preserve + 10*locality`` — scores
BIT-IDENTICAL to :class:`~..core.rater.Binpack` (pinned by tests and
by the what-if parity gate).

``filter`` (per-candidate-node keep/reject after the built-in filter
passed it; result truthy = keep):

    free_chips, free_core, free_hbm, total_chips, frag, largest_box,
    demand_core, demand_hbm, demand_chips, tput, interference

``preempt`` (victim-group ranking; HIGHER = evict first):

    priority, chips, members, is_gang

``defrag`` (victim scoring; HIGHER = move first):

    chips, priority, whole, is_gang, node_free

``kv`` (serving KV-page preemption/migration victim; HIGHER = evict or
migrate first):

    priority, pages, tokens, slot, matched

``matched`` is the disaggregated data plane's input: tokens the slot
got from the prefix cache at admission (local hit or adopted pages) —
a slot riding a big cached prefix is the cheapest to evict or migrate,
because re-admission re-matches the pages instead of re-prefilling.
"""

from __future__ import annotations

from math import isfinite as _isfinite
from typing import Optional

from ..core.allocator import ChipSet, Option, Rater
from ..core.rater import (
    ICILocality,
    _chip_used_before,
    _locality_bonus,
    _node_used_before,
)
from ..profile.rater import ProfileAwareRater
from .vm import PolicyFault, Program, evaluate


def _option_chips(option: Option) -> float:
    n = 0
    for a in option.allocs:
        if a.needs_tpu:
            n += len(a.coords)
    return float(n)


def _all_whole(option: Option) -> float:
    for a in option.allocs:
        if a.needs_tpu and not a.whole:
            return 0.0
    return 1.0


def _all_contiguous(option: Option) -> float:
    for a in option.allocs:
        if a.needs_tpu and not a.contiguous:
            return 0.0
    return 1.0


# fill signature: (rater, chips, option) -> float.  ``rater`` carries the
# profile plumbing and the incumbent (for ``base``).
SCORE_FILLS = {
    "node_used": lambda r, ch, o: _node_used_before(ch, o),
    "chip_used": lambda r, ch, o: _chip_used_before(ch, o),
    "preserve": lambda r, ch, o: ch.free_count() / max(1, ch.num_chips),
    "locality": lambda r, ch, o: _locality_bonus(ch, o),
    "free_chips": lambda r, ch, o: float(ch.free_count()),
    "total_chips": lambda r, ch, o: float(ch.num_chips),
    "option_chips": lambda r, ch, o: _option_chips(o),
    "whole": lambda r, ch, o: _all_whole(o),
    "contiguous": lambda r, ch, o: _all_contiguous(o),
    "tput": lambda r, ch, o: r._prof._tput_factor(),
    "interference": lambda r, ch, o: r._prof._interference_factor(ch, o),
    "base": lambda r, ch, o: r.fallback.rate(ch, o),
}
SCORE_INPUTS = tuple(sorted(SCORE_FILLS))

FILTER_INPUTS = (
    "free_chips", "free_core", "free_hbm", "total_chips", "frag",
    "largest_box", "demand_core", "demand_hbm", "demand_chips",
    "tput", "interference",
)
PREEMPT_INPUTS = ("priority", "chips", "members", "is_gang")
DEFRAG_INPUTS = ("chips", "priority", "whole", "is_gang", "node_free")
KV_INPUTS = ("priority", "pages", "tokens", "slot", "matched")

VERB_INPUTS = {
    "score": SCORE_INPUTS,
    "filter": FILTER_INPUTS,
    "preempt": PREEMPT_INPUTS,
    "defrag": DEFRAG_INPUTS,
    "kv": KV_INPUTS,
}


class PolicyRater(Rater):
    """A compiled ``score`` policy wrapped in the Rater interface, with
    the incumbent built-in as its safe fallback: any
    :class:`PolicyFault` (budget trip, deadline, math fault) scores the
    option through ``fallback`` instead — never a failed bind — and is
    reported through ``on_fault`` (the plane journals it as a
    ``policy_fault`` annotation).

    Profile plumbing mirrors :class:`ProfileAwareRater` (it IS one,
    embedded): ``observe_profile``/``set_workload`` are duck-typed, so
    ``journal.replay.what_if`` drives a policy-backed rater over
    recorded profiles exactly like the PR 6 promotion harness.

    Planner-shortcut flags default to False (the safe stance for an
    unknown policy); a load request may declare ``translation_invariant``
    / ``whole_chip_compact_first`` when the expression qualifies (e.g.
    the binpack-parity policy).
    """

    def __init__(
        self,
        program: Program,
        fallback: Optional[Rater] = None,
        name: str = "policy",
        translation_invariant: bool = False,
        whole_chip_compact_first: bool = False,
        on_fault=None,
    ):
        self.program = program
        self.fallback = fallback or ICILocality()
        self.name = name
        self.translation_invariant = bool(translation_invariant)
        self.whole_chip_compact_first = bool(whole_chip_compact_first)
        self.on_fault = on_fault
        self._prof = ProfileAwareRater(self.fallback)
        # fills resolved ONCE, in slot order — rate() runs a tight loop
        self._fills = tuple(SCORE_FILLS[n] for n in program.slots)
        # fused fills+expression function (lang.build_filled_fn): the
        # bind-path form, eligible exactly when py_fn is (static size ≤
        # budget ⇒ budget/deadline can never trip).  None → interpret.
        from .lang import build_filled_fn

        self._rate_fn = build_filled_fn(program, self._fills)
        self.evals = 0
        self.faults = 0

    # -- what_if hooks (duck-typed; see profile/rater.py) --------------------

    def observe_profile(self, rec: dict) -> None:
        self._prof.observe_profile(rec)

    def set_workload(self, wclass, node=None, generation=None) -> None:
        self._prof.set_workload(wclass, node=node, generation=generation)

    # -- scoring -------------------------------------------------------------

    def rate(self, chips: ChipSet, option: Option) -> float:
        self.evals += 1
        try:
            fn = self._rate_fn
            if fn is not None:
                out = fn(self, chips, option)
                if not _isfinite(out):
                    raise PolicyFault("math", "non-finite result")
            else:
                vals = [fill(self, chips, option) for fill in self._fills]
                out = evaluate(self.program, vals)
        except PolicyFault as e:
            self.faults += 1
            if self.on_fault is not None:
                self.on_fault("score", self.name, e)
            return self.fallback.rate(chips, option)
        except Exception as e:  # a broken fill must never fail a bind
            self.faults += 1
            if self.on_fault is not None:
                self.on_fault("score", self.name, PolicyFault("fill", str(e)))
            return self.fallback.rate(chips, option)
        # bound into the Rater contract's [0, 100] (no-op for in-range
        # scores, so parity with a built-in formula is exact)
        if out < 0.0:
            return 0.0
        if out > 100.0:
            return 100.0
        return out


def behavior_factors(profiles: dict, interference: dict, wclass: str,
                     generation: str, neighbor_classes) -> tuple[float, float]:
    """(tput, interference) filter-verb inputs from observatory state:
    the class' normalized throughput on ``generation`` and its worst
    measured co-location ratio against the classes currently resident
    on the node.  1.0 / 1.0 when unprofiled."""
    tput = 1.0
    row = (profiles.get(wclass) or {}).get("tokens_per_sec_per_chip") or {}
    if row:
        best = max(row.values())
        if best > 0:
            here = row.get(generation)
            tput = 0.75 if here is None else max(
                0.0, min(1.0, here / best)
            )
    ifx = 1.0
    irow = interference.get(wclass) or {}
    for ncls in neighbor_classes:
        r = irow.get(ncls)
        if r is not None:
            ifx = min(ifx, max(0.0, float(r)))
    return tput, ifx
