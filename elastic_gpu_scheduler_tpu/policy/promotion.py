"""Replay-gated promotion and canary SLO monitoring.

Promotion is safe BY CONSTRUCTION, in three stages:

1. **Replay gate** — a staged ``score`` candidate must first beat (or
   tie within tolerance) the incumbent on a what-if replay of the
   RECORDED workload (``journal.replay.what_if``), judged on
   rater-NEUTRAL quality metrics — placements completed, contiguous
   fraction, final mean fragmentation — never on the raters' own
   scores (a policy that awards itself 100 for everything must not
   gate itself through).
2. **Canary** — the gated candidate decides a deterministic pod-hash
   fraction of live binds; every decision (both arms) is journaled as a
   ``policy`` record with the cross-scored divergence.
3. **Auto-rollback** — :class:`SLOMonitor` watches bind p99 (candidate
   arm vs incumbent arm), filter-reject rate, and the fleet's mean
   fragmentation delta since the canary started; a regression rolls
   the candidate back automatically and journals why.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

from ..journal.replay import what_if


def replay_gate(
    events: list,
    candidate,
    incumbent,
    tolerance: float = 0.02,
) -> dict:
    """Judge ``candidate`` against ``incumbent`` over the recorded
    workload.  Pass iff, within ``tolerance`` (an absolute slack on the
    [0,1] fractions), the candidate places at least as many binds, keeps
    the contiguous fraction, and does not worsen final fragmentation.

    Returns {"pass", "reasons", "candidate", "incumbent"}; an empty
    recording cannot validate anything and fails closed."""
    inc = what_if(events, incumbent)
    cand = what_if(events, candidate)
    reasons: list[str] = []
    if cand["binds"] == 0:
        reasons.append("no recorded binds to replay — gate cannot validate")
    if cand["placed"] < inc["placed"]:
        reasons.append(
            f"candidate placed {cand['placed']}/{cand['binds']} vs "
            f"incumbent {inc['placed']}"
        )
    if cand["contiguous_frac"] < inc["contiguous_frac"] - tolerance:
        reasons.append(
            f"contiguous fraction regressed: {cand['contiguous_frac']} vs "
            f"{inc['contiguous_frac']} (tolerance {tolerance})"
        )
    if cand["final_frag_mean"] > inc["final_frag_mean"] + tolerance:
        reasons.append(
            f"final mean fragmentation regressed: "
            f"{cand['final_frag_mean']} vs {inc['final_frag_mean']} "
            f"(tolerance {tolerance})"
        )
    if cand["mean_free_chip_frac"] < inc["mean_free_chip_frac"] - tolerance:
        reasons.append(
            f"whole-free-chip preservation regressed: "
            f"{cand['mean_free_chip_frac']} vs "
            f"{inc['mean_free_chip_frac']} (tolerance {tolerance})"
        )
    return {
        "pass": not reasons,
        "reasons": reasons,
        "tolerance": tolerance,
        "candidate": cand,
        "incumbent": inc,
    }


def _p99(samples) -> float:
    s = sorted(samples)
    if not s:
        return 0.0
    return s[min(len(s) - 1, max(0, int(0.99 * len(s) + 0.5) - 1))]


class SLOMonitor:
    """Canary-time SLO watchdog.  Cheap to feed (deque appends under a
    small lock); ``regressed()`` is evaluated periodically by the plane
    and by ``check_slo()`` callers.

    Regression conditions (any one trips):
    - candidate bind p99 > incumbent bind p99 × (1 + p99_pct/100), with
      at least ``min_samples`` per arm and an absolute floor so µs-level
      jitter on an idle box cannot trip it;
    - candidate filter-reject rate > incumbent rate + reject_delta
      (min_samples filter decisions per arm);
    - mean fragmentation index rose more than frag_delta since the
      canary started (measured through the plane's frag provider).
    """

    def __init__(
        self,
        p99_pct: float = 25.0,
        p99_floor_s: float = 0.001,
        reject_delta: float = 0.15,
        frag_delta: float = 0.15,
        min_samples: int = 20,
        window: int = 512,
    ):
        self.p99_pct = p99_pct
        self.p99_floor_s = p99_floor_s
        self.reject_delta = reject_delta
        self.frag_delta = frag_delta
        self.min_samples = min_samples
        self._lock = threading.Lock()
        self._lat = {
            "candidate": deque(maxlen=window),
            "incumbent": deque(maxlen=window),
        }
        # arm → [kept, total] filter candidate-node counts
        self._filter = {"candidate": [0, 0], "incumbent": [0, 0]}
        self.frag_baseline: Optional[float] = None
        self.frag_current: Optional[float] = None

    def note_latency(self, arm: str, seconds: float) -> None:
        with self._lock:
            self._lat[arm].append(seconds)

    def note_filter(self, arm: str, kept: int, total: int) -> None:
        with self._lock:
            row = self._filter[arm]
            row[0] += kept
            row[1] += total

    def set_frag_baseline(self, value: Optional[float]) -> None:
        with self._lock:
            self.frag_baseline = value
            self.frag_current = value

    def note_frag(self, value: Optional[float]) -> None:
        if value is None:
            return
        with self._lock:
            self.frag_current = value

    def regressed(self) -> Optional[str]:
        with self._lock:
            cand = list(self._lat["candidate"])
            inc = list(self._lat["incumbent"])
            cf = tuple(self._filter["candidate"])
            nf = tuple(self._filter["incumbent"])
            base, cur = self.frag_baseline, self.frag_current
        if len(cand) >= self.min_samples and len(inc) >= self.min_samples:
            cp, ip = _p99(cand), _p99(inc)
            if (
                cp > ip * (1.0 + self.p99_pct / 100.0)
                and cp - ip > self.p99_floor_s
            ):
                return (
                    f"bind p99 regression: candidate {cp * 1e3:.3f}ms vs "
                    f"incumbent {ip * 1e3:.3f}ms (budget +{self.p99_pct}%)"
                )
        if cf[1] >= self.min_samples and nf[1] >= self.min_samples:
            cr = 1.0 - cf[0] / cf[1]
            nr = 1.0 - nf[0] / nf[1]
            if cr > nr + self.reject_delta:
                return (
                    f"filter-reject regression: candidate rejects "
                    f"{cr:.2%} of candidate nodes vs incumbent {nr:.2%} "
                    f"(delta budget {self.reject_delta:.2})"
                )
        if base is not None and cur is not None:
            if cur - base > self.frag_delta:
                return (
                    f"fragmentation regression: mean index {cur:.3f} vs "
                    f"{base:.3f} at canary start (delta budget "
                    f"{self.frag_delta})"
                )
        return None

    def state(self) -> dict:
        with self._lock:
            return {
                "bind_p99_candidate_ms": round(
                    _p99(list(self._lat["candidate"])) * 1e3, 3
                ),
                "bind_p99_incumbent_ms": round(
                    _p99(list(self._lat["incumbent"])) * 1e3, 3
                ),
                "bind_samples": {
                    a: len(q) for a, q in self._lat.items()
                },
                "filter_kept": {
                    a: list(v) for a, v in self._filter.items()
                },
                "frag_baseline": self.frag_baseline,
                "frag_current": self.frag_current,
            }
