"""Programmable policy plane (ROADMAP item 4, gpu_ext direction).

Operators hot-load sandboxed scheduling policies — small deterministic
expressions compiled to stack bytecode (``lang``/``vm``) with strict
instruction budgets and per-eval wall deadlines — onto five verbs
(score / filter / preempt / defrag / kv) without a redeploy.  Promotion
is safe by construction: a candidate must beat the incumbent on journal
what-if replay of recorded workload, then canaries on a deterministic
pod-hash fraction of live binds with automatic SLO rollback
(``promotion``/``registry``).  Every decision and every runtime fault
is journaled; replay reconstructs which policy decided every bind.

See OPERATIONS.md "Programmable policy plane" for the language
reference, verb input tables, and the load→gate→canary→promote
workflow.
"""

from .lang import CompileError, compile_expr
from .rater import PolicyRater, VERB_INPUTS
from .registry import (
    POLICIES,
    PolicyPlane,
    canary_bucket,
    default_gate_events,
    resolve_rater,
)
from .vm import PolicyFault, Program, evaluate, run

__all__ = [
    "CompileError",
    "POLICIES",
    "PolicyFault",
    "PolicyPlane",
    "PolicyRater",
    "Program",
    "VERB_INPUTS",
    "canary_bucket",
    "compile_expr",
    "default_gate_events",
    "evaluate",
    "resolve_rater",
    "run",
]
