"""Warm takeover: swap a journal-shipping follower's replayed state into
a live scheduler engine, then resync as a DIFF against the annotation
ledger.

The cold path (``_rebuild_state`` at construction) lists every assumed
pod, then pays one ``get_node`` + one ``list_pods`` per materialized
node plus an option replay per pod — at a 10k-node fleet that is the
whole failover budget.  A caught-up follower already holds the complete
per-node ChipSet state, the pod ledger, and the node generations; warm
takeover:

1. **Adopts** the follower's ``ReplayEngine`` state — each replayed
   ChipSet becomes a live ``NodeAllocator`` (``from_state``: zero
   network), pod placements land in ``pod_maps``, the capacity index is
   rebuilt from the adopted entries.
2. **Diff-resyncs** against the annotation ledger with ONE ``list_pods``
   call: pods in the ledger the journal never shipped (bound in the
   leader's final unflushed window) are adopted through the normal
   ``add_pod`` path; replayed pods absent from the ledger (phase-2
   writes that never landed, deletions in flight) are forgotten.  Both
   directions journal through the standard commit points — a takeover
   leaves the same audit trail any reconciliation does.
3. **Journals** an ``ha_takeover`` annotation (replay counts it;
   ``what_if`` skips it) and requests a BOOT CHECKPOINT, so the new
   leader's journal is self-contained without re-journaling 10k
   node_add/bind re-assertions.

The ledger remains the arbiter: the diff is computed FROM it, so a
follower that lagged simply pays a bigger diff — correctness never
depends on the follower being caught up, only takeover SPEED does.
All clientset I/O happens off the engine lock (the lockdep rule);
the install itself is pure dict/index work under it.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from ..core.annotations import assigned_node, option_from_pod
from ..core.node import NodeAllocator
from ..journal import JOURNAL
from ..metrics import HA_TAKEOVER_SECONDS
from ..utils import consts
from ..utils.backoff import Backoff, retry_call

log = logging.getLogger("tpu-scheduler")

__all__ = ["warm_takeover"]


class _ShimMeta:
    __slots__ = ("namespace", "name", "uid", "annotations", "labels")


class _ShimPod:
    """The minimal Pod surface ``forget_pod`` consumes (key + uid) for
    replayed pods whose ledger entry vanished — there is no live Pod
    object to pass, the ledger is exactly what lost it."""

    __slots__ = ("key", "metadata")

    def __init__(self, pod_key: str, uid: str):
        ns, _, name = pod_key.partition("/")
        self.key = pod_key
        self.metadata = _ShimMeta()
        self.metadata.namespace = ns or "default"
        self.metadata.name = name
        self.metadata.uid = uid
        self.metadata.annotations = {}
        self.metadata.labels = {}


def warm_takeover(sched, source, clientset=None) -> dict:
    """Install a follower's replayed state into ``sched`` and diff-resync
    against the annotation ledger.  ``source`` is a
    ``journal.ship.JournalFollower`` (stopped first) or a bare
    ``ReplayResult``.  Returns a summary dict (also journaled as the
    ``ha_takeover`` record)."""
    t0 = time.perf_counter()
    follower = None
    if hasattr(source, "engine"):  # a JournalFollower
        follower = source
        follower.stop()  # settle: the poll thread must not mutate under us
        res = follower.engine.result
    else:
        res = source
    cs_client = clientset if clientset is not None else sched.clientset

    # -- ledger fetch, OFF the engine lock (network I/O) ---------------------
    ledger: dict[str, object] = {}
    try:
        pods = retry_call(
            lambda: cs_client.list_pods(
                label_selector={consts.ANNOTATION_ASSUMED: "true"}
            ),
            attempts=3,
            retry_on=(Exception,),
            backoff=Backoff(base_s=0.1, max_s=1.0, deadline_s=3.0),
        )
        for pod in pods:
            if pod.is_completed() or not assigned_node(pod):
                continue
            ledger[pod.key] = pod
    except Exception as e:
        # a takeover against a flapping apiserver still installs the
        # replayed state (serving resumes); the controller's periodic
        # resync converges the ledger diff later
        log.warning("warm takeover: ledger list failed (%s); installing "
                    "replayed state, resync deferred to the controller", e)
        ledger = None  # sentinel: skip the diff pass

    # -- build allocators off-lock (pure compute) ----------------------------
    adopted = {
        name: NodeAllocator.from_state(
            name, res.generations.get(name, "v5e"), cs
        )
        for name, cs in res.nodes.items()
    }

    # -- install under the engine lock (dict/index work only) ----------------
    nodes_installed = pods_installed = 0
    skipped_nodes: set[str] = set()
    with sched.lock:
        for name, na in adopted.items():
            if name in sched.allocators:
                # the standby engine materialized this node already
                # (e.g. a verb raced the election) — its live allocator
                # wins; the diff below still converges the pods
                skipped_nodes.add(name)
                continue
            sched.allocators[name] = na
            if sched.index is not None:
                na.on_change = sched.index.mark_dirty
                sched.index.note_node(name, na)
            nodes_installed += 1
        for pod_key, lp in res.pods.items():
            if pod_key in sched.pod_maps:
                continue
            if lp.node in skipped_nodes:
                # its charges live only in the NOT-adopted replayed
                # ChipSet; installing the ledger entry without charging
                # the live allocator would leave the chips looking free
                # (double-book).  The ledger diff below re-adopts the
                # pod through add_pod, which charges na.add properly.
                continue
            sched.pod_maps[pod_key] = (lp.node, lp.option)
            sched.released_pods.pop(pod_key, None)
            pods_installed += 1

    # -- diff resync vs the ledger (normal journaled verbs, off-lock) --------
    diff_added = diff_removed = 0
    if ledger is not None:
        with sched.lock:
            replayed_view = {
                pk: (node, opt) for pk, (node, opt) in sched.pod_maps.items()
            }
        for pod_key, pod in ledger.items():
            node = assigned_node(pod)
            entry = replayed_view.get(pod_key)
            if entry is not None and entry[0] == node:
                # same node: confirm the PLACEMENT too — a rebind that
                # rewrote the annotation in the lost window must win
                # (the ledger is the arbiter, the journal only a replica)
                na = sched.allocators.get(node)
                ledger_opt = (
                    option_from_pod(pod, na.chips.topo)
                    if na is not None else None
                )
                if ledger_opt is None or (
                    ledger_opt.allocs == entry[1].allocs
                ):
                    continue  # agree — the common case when caught up
            if entry is not None:
                # ledger moved the pod (migrate/rebind in the lost
                # window): release the replayed placement, adopt the
                # ledger's
                sched.forget_pod(pod, source="takeover")
                diff_removed += 1
            sched.add_pod(pod, source="takeover")
            diff_added += 1
        for pod_key in set(replayed_view) - set(ledger):
            lp = res.pods.get(pod_key)
            sched.forget_pod(
                _ShimPod(pod_key, lp.uid if lp else ""), source="takeover"
            )
            diff_removed += 1

    wall_ms = round((time.perf_counter() - t0) * 1000.0, 2)
    summary = {
        "nodes": nodes_installed,
        "nodes_skipped": len(skipped_nodes),
        "pods": pods_installed,
        "diff_added": diff_added,
        "diff_removed": diff_removed,
        "adopted_seq": res.last_seq,
        "ledger_pods": len(ledger) if ledger is not None else None,
        "wall_ms": wall_ms,
    }
    if JOURNAL.enabled:
        # a reconfigured journal (new leader, fresh dir) cleared its
        # checkpoint provider — the adopted engine is the snapshot source
        sched.register_checkpoint_provider()
        # the new leader's journal must replay WITHOUT the previous
        # leader's stream: snapshot the adopted state at the head.
        # Requested BEFORE the first record: the writer emits a pending
        # checkpoint at the top of its next non-empty batch, so
        # request-then-record guarantees the checkpoint precedes every
        # record of this incarnation (a mid-stream checkpoint would not
        # BOOT a replay, and every adopted node would look unknown)
        JOURNAL.request_checkpoint()
        JOURNAL.record("ha_takeover", **summary)
    HA_TAKEOVER_SECONDS.set(value=wall_ms / 1000.0)
    log.info(
        "warm takeover: adopted %d nodes / %d pods from seq %d, ledger "
        "diff +%d/-%d, %.1fms",
        nodes_installed, pods_installed, res.last_seq,
        diff_added, diff_removed, wall_ms,
    )
    return summary
