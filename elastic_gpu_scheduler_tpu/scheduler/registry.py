"""Scheduler registry + per-pod dispatch.

Reference: BuildResourceSchedulers / GetResourceScheduler
(pkg/scheduler/scheduler.go:292-334).  One engine instance is registered under
*both* the core and HBM resource names (scheduler.go:308-309); dispatch scans
the pod's container requests for a registered resource (scheduler.go:323-334).
The reference's pgpu/qgpu modes are commented-out TODOs; here the mode set is
just ``tpushare`` (fractional + whole-chip in one engine).
"""

from __future__ import annotations

from typing import Optional

from ..k8s.objects import Pod
from ..utils import consts
from .scheduler import ResourceScheduler, SchedulerConfig, TPUUnitScheduler

KNOWN_MODES = ("tpushare",)


def build_resource_schedulers(
    modes: list[str], config: SchedulerConfig
) -> dict[str, ResourceScheduler]:
    registry: dict[str, ResourceScheduler] = {}
    for mode in modes:
        if mode == "tpushare":
            engine = TPUUnitScheduler(config, name="tpushare")
            for res in (
                *consts.RESOURCE_TPU_CORE_ALIASES,
                *consts.RESOURCE_TPU_HBM_ALIASES,
            ):
                registry[res] = engine
        else:
            raise ValueError(f"unknown scheduler mode {mode!r}; known: {KNOWN_MODES}")
    return registry


def get_resource_scheduler(
    registry: dict[str, ResourceScheduler], pod: Pod
) -> Optional[ResourceScheduler]:
    for c in pod.spec.containers:
        for res_map in (c.resources.requests, c.resources.limits):
            for name in res_map or {}:
                if name in registry:
                    return registry[name]
    return None


def is_tpu_pod(pod: Pod) -> bool:
    """Does the pod request any recognized TPU resource?
    (reference: IsGPUPod, pkg/scheduler/pod.go:27-34)."""
    names = set(consts.RESOURCE_TPU_CORE_ALIASES) | set(
        consts.RESOURCE_TPU_HBM_ALIASES
    )
    for c in pod.spec.containers:
        for res_map in (c.resources.requests, c.resources.limits):
            for name, v in (res_map or {}).items():
                if name in names and int(str(v)) > 0:
                    return True
    return False
