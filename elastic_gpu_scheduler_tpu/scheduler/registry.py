"""Scheduler registry + per-pod dispatch.

Reference: BuildResourceSchedulers / GetResourceScheduler
(pkg/scheduler/scheduler.go:292-334).  One engine instance is registered under
*both* the core and HBM resource names (scheduler.go:308-309); dispatch scans
the pod's container requests for a registered resource (scheduler.go:323-334).

The reference's pgpu/qgpu modes are commented-out TODOs (scheduler.go:
296-316); here BOTH intended modes are live:

- ``tpushare`` — fractional + whole-chip in one engine (the qgpu/gpushare
  analogue);
- ``tpuwhole`` — whole-chip-only admission (the pgpu analogue): every
  container must request whole chips (core a positive multiple of 100,
  or chip_count), so every tenant gets exclusive TensorCores — the mode
  for latency-SLO clusters where cooperative fractional sharing
  (deviceplugin/plugin.py contract) is not acceptable.

The two modes claim the same resource names, so exactly one may be active.
"""

from __future__ import annotations

from typing import Optional

from ..core.request import TPURequest
from ..k8s.objects import Pod
from ..utils import consts
from .scheduler import ResourceScheduler, SchedulerConfig, TPUUnitScheduler

KNOWN_MODES = ("tpushare", "tpuwhole")


class TPUWholeScheduler(TPUUnitScheduler):
    """Whole-chip-only engine: rejects fractional shapes at admission
    (filter AND bind — a bind can arrive without a filter pass)."""

    def admits(self, request: TPURequest) -> Optional[str]:
        for name, u in zip(request.container_names, request.units):
            if not u.needs_tpu or u.wants_whole_chips:
                continue
            if u.core <= 0 or u.core % consts.CORE_PER_CHIP:
                return (
                    f"mode tpuwhole: container {name!r} requests a "
                    f"fractional share (core={u.core}, hbm={u.hbm}); "
                    "whole chips only (core a positive multiple of "
                    f"{consts.CORE_PER_CHIP})"
                )
        return None


def build_resource_schedulers(
    modes: list[str], config: SchedulerConfig
) -> dict[str, ResourceScheduler]:
    registry: dict[str, ResourceScheduler] = {}
    for mode in modes:
        if mode == "tpushare":
            engine: TPUUnitScheduler = TPUUnitScheduler(
                config, name="tpushare"
            )
        elif mode == "tpuwhole":
            engine = TPUWholeScheduler(config, name="tpuwhole")
        else:
            raise ValueError(
                f"unknown scheduler mode {mode!r}; known: {KNOWN_MODES}"
            )
        for res in (
            *consts.RESOURCE_TPU_CORE_ALIASES,
            *consts.RESOURCE_TPU_HBM_ALIASES,
        ):
            if res in registry:
                raise ValueError(
                    f"modes {registry[res].name!r} and {mode!r} both "
                    f"claim {res}; run exactly one of tpushare/tpuwhole"
                )
            registry[res] = engine
    return registry


def get_resource_scheduler(
    registry: dict[str, ResourceScheduler], pod: Pod
) -> Optional[ResourceScheduler]:
    for c in pod.spec.containers:
        for res_map in (c.resources.requests, c.resources.limits):
            for name in res_map or {}:
                if name in registry:
                    return registry[name]
    return None


def is_tpu_pod(pod: Pod) -> bool:
    """Does the pod request any recognized TPU resource?
    (reference: IsGPUPod, pkg/scheduler/pod.go:27-34)."""
    names = set(consts.RESOURCE_TPU_CORE_ALIASES) | set(
        consts.RESOURCE_TPU_HBM_ALIASES
    )
    for c in pod.spec.containers:
        for res_map in (c.resources.requests, c.resources.limits):
            for name, v in (res_map or {}).items():
                if name in names and int(str(v)) > 0:
                    return True
    return False
