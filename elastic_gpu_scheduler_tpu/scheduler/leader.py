"""Lease-based leader election for scheduler HA.

Net-new vs the reference (which runs a single replica, SURVEY §2 #17: the
deploy pins ``replicas: 1``).  Multiple scheduler replicas can now run
behind one Service: exactly one holds the ``coordination.k8s.io`` Lease and
serves verbs; standbys answer ``/healthz`` as not-ready so the Service's
readiness probe keeps them out of the endpoint set, and they take over
within ~``lease_duration`` of the leader dying.

The protocol is client-go's leaderelection recipe on the clientset's lease
surface (``get_lease``/``create_lease``/``update_lease``):

- acquire: create the lease if absent; if held and the holder's renewTime is
  older than ``lease_duration``, take it over with an optimistic-concurrency
  update (a 409 means somebody else won the race — back off and retry);
- renew: the leader bumps renewTime every ``renew_period``; a renewal
  conflict or error makes it STEP DOWN immediately (fail-stop: better a
  few seconds with no leader than two schedulers double-allocating chips);
- observe: standbys poll the lease at ``renew_period`` cadence.

The scheduling engine itself needs no changes for correctness: allocations
live in pod annotations (the durable ledger), so a new leader rebuilds the
complete state at startup/resync exactly like a restarted single replica.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from ..faultinject import FAULTS
from ..k8s.fake import is_conflict, is_not_found
from ..metrics import LEADER_STATE

log = logging.getLogger("tpu-scheduler")

LEASE_NAME = "tpu-elastic-scheduler"


def _now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


class LeaderElector:
    def __init__(
        self,
        clientset,
        identity: str,
        namespace: str = "kube-system",
        lease_name: str = LEASE_NAME,
        lease_duration: float = 15.0,
        renew_period: float = 5.0,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
        on_stepping_down: Optional[Callable[[], None]] = None,
    ):
        self.clientset = clientset
        self.identity = identity
        self.namespace = namespace
        self.lease_name = lease_name
        self.lease_duration = lease_duration
        self.renew_period = renew_period
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        # runs BETWEEN fencing and surrendering leadership: is_leader()
        # already answers False (new verbs 503) but the lease is still
        # ours, so this hook can drain in-flight verb handlers and
        # flush+close the journal while no standby can have taken over —
        # the step-down race the old fail-stop (rely on process exit)
        # left open.  Bounded work only: it runs on the elector thread.
        self.on_stepping_down = on_stepping_down
        self._leading = False
        # fencing flag: True while a step-down is draining.  Ordering on
        # the step-down path is store-fence-THEN-drain, so a verb that
        # read is_leader()==True before the fence is inside the drain
        # window, and one that reads after sees False.
        self.fenced = False
        self.transitions = 0  # local count of step-up/step-down cycles
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # our own last SUCCESSFUL renew (monotonic) — leadership expires by
        # this clock even if an in-flight renewal request is hung, bounding
        # split-brain to the lease duration (client-go's renewDeadline)
        self._last_renew_mono = 0.0
        # monotonic deadline tracking for OBSERVED renewals of other holders
        self._observed_holder = ""
        self._observed_rv = ""
        self._observed_renew_mono = 0.0

    # -- public --------------------------------------------------------------

    def is_leader(self) -> bool:
        """Leading AND not fenced AND renewed within the lease duration.
        The time check means a leader whose renewal request is stuck on a
        slow apiserver stops serving the moment its lease could have
        expired — before any standby is allowed to take over — so two
        replicas can never both answer True.  ``fenced`` covers the
        step-down window: verbs are rejected while in-flight handlers
        drain and the journal flushes, BEFORE the lease is surrendered."""
        return (
            self._leading
            and not self.fenced
            and time.monotonic() - self._last_renew_mono < self.lease_duration
        )

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="leader-elector", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=self.renew_period + 1)
        was_leading = self._leading
        # fence + drain FIRST: blanking the lease before stepping down
        # would let a standby acquire while our in-flight verbs are
        # still committing (a graceful-stop split-brain window)
        self._step_down()
        if was_leading:
            self._release()

    def _release(self) -> None:
        """Graceful handoff: blank the holder so standbys can acquire
        IMMEDIATELY instead of waiting out the observation window — a
        rolling restart costs one election round-trip, not lease_duration
        of 503s (client-go's releaseOnCancel)."""
        try:
            lease = self.clientset.get_lease(self.namespace, self.lease_name)
            spec = lease.get("spec") or {}
            if spec.get("holderIdentity") != self.identity:
                return
            spec["holderIdentity"] = ""
            spec["renewTime"] = ""
            self.clientset.update_lease(lease)
        except Exception as e:  # best-effort; expiry still covers it
            log.debug("lease release failed: %s", e)

    # -- protocol ------------------------------------------------------------

    def _lease_body(self, acquire_ts: str, transitions: int) -> dict:
        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"namespace": self.namespace, "name": self.lease_name},
            "spec": {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": int(self.lease_duration),
                "acquireTime": acquire_ts,
                "renewTime": _now_iso(),
                "leaseTransitions": transitions,
            },
        }

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                if self._leading:
                    self._renew()
                else:
                    self._try_acquire()
            except Exception as e:  # never kill the loop
                log.warning("leader election error: %s", e)
                self._step_down()
            self._stop.wait(self.renew_period)

    def _try_acquire(self) -> None:
        if FAULTS.enabled:
            FAULTS.maybe_fire("lease.acquire")
        try:
            lease = self.clientset.get_lease(self.namespace, self.lease_name)
        except Exception as e:
            if not is_not_found(e):
                raise
            try:
                sent_at = time.monotonic()
                self.clientset.create_lease(
                    self._lease_body(_now_iso(), 0)
                )
                self._become_leader("created lease", acquired_at=sent_at)
            except Exception as ce:
                # a real apiserver answers POST-of-existing with reason
                # AlreadyExists (still 409); either way it just means we
                # lost the creation race
                if is_conflict(ce) or getattr(ce, "code", None) == 409:
                    return  # stay standby
                raise
            return

        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity", "")
        if holder == self.identity:
            # our own stale lease (e.g. restart with same identity): renew it
            self._take_over(lease, transitions=False)
            return
        if not holder:
            # gracefully released — acquire immediately
            self._take_over(lease, transitions=True)
            return
        # track the holder's liveness by OBSERVING the lease's
        # resourceVersion on our monotonic clock: rv changes on EVERY
        # successful renew (sub-second resolution; wall-clock renewTime
        # strings truncate to seconds and cross-host clocks don't compare)
        rv = str(lease.get("metadata", {}).get("resourceVersion", ""))
        if holder != self._observed_holder or rv != self._observed_rv:
            self._observed_holder = holder
            self._observed_rv = rv
            self._observed_renew_mono = time.monotonic()
            return  # freshly observed → give the holder a full duration
        if time.monotonic() - self._observed_renew_mono >= self.lease_duration:
            self._take_over(lease, transitions=True)

    def _take_over(self, lease: dict, transitions: bool) -> None:
        spec = lease.get("spec") or {}
        body = self._lease_body(
            _now_iso(),
            int(spec.get("leaseTransitions", 0)) + (1 if transitions else 0),
        )
        body["metadata"]["resourceVersion"] = (
            lease.get("metadata", {}).get("resourceVersion", "")
        )
        sent_at = time.monotonic()
        try:
            self.clientset.update_lease(body)
        except Exception as e:
            if is_conflict(e):
                return  # someone else acted first
            raise
        self._become_leader(
            f"took over from '{spec.get('holderIdentity', '')}'",
            acquired_at=sent_at,
        )

    def _renew(self) -> None:
        try:
            if FAULTS.enabled:
                # inside the try: an injected failure IS a renewal
                # failure — fail-stop fences, drains, surrenders
                FAULTS.maybe_fire("lease.renew")
            lease = self.clientset.get_lease(self.namespace, self.lease_name)
            spec = lease.get("spec") or {}
            if spec.get("holderIdentity") != self.identity:
                log.warning("lease stolen by %s", spec.get("holderIdentity"))
                self._step_down()
                return
            body = self._lease_body(
                spec.get("acquireTime", _now_iso()),
                int(spec.get("leaseTransitions", 0)),
            )
            body["metadata"]["resourceVersion"] = (
                lease.get("metadata", {}).get("resourceVersion", "")
            )
            # stamp BEFORE the request goes out: standbys start their
            # takeover clock the moment the apiserver applies the update,
            # so our own expiry clock must not be credited with the
            # response latency (client-go does the same)
            sent_at = time.monotonic()
            self.clientset.update_lease(body)
            self._last_renew_mono = sent_at
        except Exception as e:
            # fail-stop: any renewal failure surrenders leadership
            log.warning("lease renewal failed (%s); stepping down", e)
            self._step_down()

    def _become_leader(self, how: str, acquired_at: float = 0.0) -> None:
        # acquired_at: monotonic time BEFORE the acquiring request was sent
        self._last_renew_mono = acquired_at or time.monotonic()
        if not self._leading:
            log.info("leader election: %s is leading (%s)", self.identity, how)
            self._leading = True
            self.fenced = False
            self.transitions += 1
            LEADER_STATE.set(value=1.0)
            if self.on_started_leading:
                self.on_started_leading()

    def _step_down(self) -> None:
        if not self._leading:
            return
        log.info("leader election: %s stepping down (fencing)", self.identity)
        # 1. fence: is_leader() answers False from here — new verbs get
        #    503+Retry-After while the lease is STILL OURS, so no standby
        #    can serve concurrently with our drain
        self.fenced = True
        LEADER_STATE.set(value=0.5)
        # 2. drain + flush: in-flight verb handlers finish (or are
        #    rejected), the journal's buffered tail reaches disk and the
        #    shipping stream — the records a follower needs to take over
        #    from exactly where we stopped
        if self.on_stepping_down:
            try:
                self.on_stepping_down()
            except Exception:
                log.exception("step-down drain hook failed")
        # 3. surrender
        log.info("leader election: %s stepped down", self.identity)
        self._leading = False
        self.fenced = False
        self.transitions += 1
        LEADER_STATE.set(value=0.0)
        if self.on_stopped_leading:
            self.on_stopped_leading()

    def debug_state(self) -> dict:
        """The /debug/leader payload (elector half)."""
        now = time.monotonic()
        return {
            "identity": self.identity,
            "leader": self.is_leader(),
            "leading_flag": self._leading,
            "fenced": self.fenced,
            "lease": f"{self.namespace}/{self.lease_name}",
            "lease_duration_s": self.lease_duration,
            "renew_period_s": self.renew_period,
            "last_renew_age_s": (
                round(now - self._last_renew_mono, 3)
                if self._last_renew_mono else None
            ),
            "observed_holder": self._observed_holder or None,
            "transitions": self.transitions,
        }
