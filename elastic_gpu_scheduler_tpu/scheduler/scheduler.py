"""The stateful scheduling engine (assume / score / bind / reconcile hooks).

TPU rebuild of the reference's GPUUnitScheduler/BaseScheduler
(reference: pkg/scheduler/scheduler.go:41-290):

- one engine instance serves both TPU resource names (core + HBM), registered
  under each (scheduler.go:308-309);
- ``assume`` fans candidate nodes out to a worker pool (scheduler.go:135-156;
  pool size configurable here, fixed 4 there);
- ``bind`` writes the annotation ledger with optimistic-conflict retry then
  POSTs the Binding subresource (scheduler.go:186-227).  Two deviations from
  the reference, both documented in SURVEY §5 as quirks-not-to-replicate:
  conflicts are detected structurally (HTTP 409) rather than by error-string
  match, and non-conflict update errors are *raised* (the reference swallows
  them and silently skips binding, scheduler.go:210-211);
- on construction the engine rebuilds all node state from ``assumed=true``
  pod annotations — the API server is the only durable store
  (scheduler.go:86-106);
- ``pod_maps``/``released_pods`` give at-most-once accounting across the
  controller's add/forget callbacks (scheduler.go:47-49, 261-281).
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Optional

from ..core.allocator import Option, option_demand
from ..core.index import _MISS, CapacityIndex, request_demand
from ..core.annotations import (
    annotations_for_option,
    assigned_node,
    is_assumed,
    option_from_pod,
    workload_class,
)
from ..core.node import NodeAllocator
from ..core.rater import Rater
from ..core.request import TPURequest, pod_gang_key, request_from_pod
from ..journal import JOURNAL, option_record
from ..k8s.client import Clientset
from ..k8s.fake import is_conflict, is_not_found
from ..k8s.objects import Binding, Pod
from ..metrics import CHIPS_ALLOCATED, FRAG_INDEX, FREE_SUBMESH, TimedLock
from ..profile import PROFILER
from ..tracing import AUDIT, TRACER
from ..utils import consts

log = logging.getLogger("tpu-scheduler")


@dataclass
class SchedulerConfig:
    """Reference: ElasticSchedulerConfig (scheduler.go:23-28)."""

    clientset: Clientset
    rater: Rater
    assume_workers: int = 4  # reference hardcodes 4 (scheduler.go:135)
    # incremental free-capacity index (core/index.py): O(1) candidate
    # rejection + one placement probe per congruence class on the
    # filter/score path, exact-by-construction via the allocator mutation
    # hook.  False = the full-rescan oracle path everywhere (the parity
    # baseline tools/check_cluster_scale.py measures against).
    placement_index: bool = True
    # False = skip the cold annotation-ledger rebuild at construction —
    # the HA follower path (--follow): a standby's state arrives via
    # journal shipping and is swapped in by scheduler/ha.warm_takeover
    # on election, so a cold rebuild here would only be thrown away.
    rebuild_on_start: bool = True
    # Per-engine journal instance.  None = the process-global JOURNAL
    # (every pre-federation caller).  A federation shard passes its own
    # Journal so many engines in one process each write their own
    # segment directory — the per-shard stream the cross-shard fed_gang
    # audit folds over.
    journal: Optional["object"] = None


class ResourceScheduler:
    """Verb interface the handlers dispatch to (reference: scheduler.go:30-39)."""

    name = "resource-scheduler"

    def assume(self, node_names: list[str], pod: Pod) -> tuple[list[str], dict[str, str]]:
        raise NotImplementedError

    def score(self, node_names: list[str], pod: Pod) -> list[int]:
        raise NotImplementedError

    def bind(self, node_name: str, pod: Pod) -> Pod:
        raise NotImplementedError

    def add_pod(self, pod: Pod, source: str = "add") -> None:
        raise NotImplementedError

    def forget_pod(self, pod: Pod, source: str = "forget") -> None:
        raise NotImplementedError

    def preempt(
        self, node_name: str, pod: Pod, victims: list[Pod]
    ) -> Optional[list[Pod]]:
        raise NotImplementedError

    def known_pod(self, pod: Pod) -> bool:
        raise NotImplementedError

    def released_pod(self, pod: Pod) -> bool:
        raise NotImplementedError

    def status(self) -> dict:
        raise NotImplementedError


class TPUUnitScheduler(ResourceScheduler):
    def __init__(self, config: SchedulerConfig, name: str = "tpushare"):
        self.name = name
        self.clientset = config.clientset
        self.rater = config.rater
        self.assume_workers = max(1, config.assume_workers)
        # Engine-scoped journal handle: the process-global JOURNAL unless
        # a federation shard injected its own.  Everything below (and the
        # gang coordinator, via sched.JOURNAL) writes through this handle
        # so per-shard engines journal to per-shard streams.
        self.JOURNAL = config.journal if config.journal is not None else JOURNAL
        # Sharded locking (wait-time-instrumented via metrics.LOCK_WAIT):
        # this lock guards ONLY the registry maps (allocators / pod_maps /
        # released_pods) — chip state lives behind each NodeAllocator's own
        # ranked lock.  Read verbs (assume/score/planning) take it once per
        # verb to snapshot allocators; the placement DFS and the cold
        # allocator build (network fetch + replay list) run OFF it.  Rank
        # discipline: gang coordinator (10) → this registry lock (20) →
        # per-node allocator locks (30).
        self.lock = TimedLock("scheduler", reentrant=True, rank=20)
        # cluster-scale capacity index: maintained by each NodeAllocator's
        # on_change hook (one GIL-atomic dict write per committed
        # mutation), consulted by assume/score/gang-planning/frag-refresh.
        # None = every verb walks the full-rescan oracle path.
        self.index: Optional[CapacityIndex] = (
            CapacityIndex() if config.placement_index else None
        )
        self.allocators: dict[str, NodeAllocator] = {}
        # pod key → (node, committed Option); the at-most-once ledger
        self.pod_maps: dict[str, tuple[str, Option]] = {}
        # pod key → uid; bounded (FIFO) so long-lived schedulers don't grow
        # without limit (the reference's releasedPodMap grows forever)
        self.released_pods: dict[str, str] = {}
        self.released_pods_max = 10000
        # defrag cordons: node → monotonic expiry.  A cordoned node fails
        # filter (new placements must not race a migration vacating it);
        # cordons carry a TTL and the reconciliation controller prunes
        # expired ones, so a crashed defrag round cannot strand a node.
        # Empty dict when defrag never runs — the filter pays one truthy
        # check.
        self.cordoned: dict[str, float] = {}
        # last gauge-refresh fragmentation snapshot (node → (index,
        # largest_free_box)): /scheduler/status and the defrag planner
        # read mesh health from here instead of re-scanning per request
        # (or needing a Prometheus scrape — frag_snapshot() refreshes
        # itself when stale)
        self._frag_cache: dict[str, tuple[float, int]] = {}
        self._frag_cache_at = 0.0  # monotonic of the last refresh
        # programmable policy plane (policy/PolicyPlane): None (or an
        # empty plane) costs one attribute/dict check per verb.  When a
        # score canary is live, bind splits raters by pod hash and
        # journals both arms; a loaded filter policy prunes assume()'s
        # feasible set; a preempt policy re-ranks reprieve order.
        # build_stack attaches the process-global POLICIES here.
        self.policies = None
        self._pool = ThreadPoolExecutor(
            max_workers=self.assume_workers, thread_name_prefix="assume"
        )
        # this engine snapshots full state into every rotated journal
        # segment, so pruned journals stay replayable; the fragmentation
        # gauges recompute from live chip state when /metrics is scraped
        # (LazyGauge) — never on the bind path.  weakref: tests build
        # many engines; a dead one must not be pinned or probed.
        ref = weakref.ref(self)
        self.register_checkpoint_provider()
        refresher = lambda: (  # noqa: E731 — tiny weakref trampoline
            lambda s: s._refresh_frag_gauges() if s is not None else None
        )(ref())
        FRAG_INDEX.refresher = refresher
        FREE_SUBMESH.refresher = refresher
        if config.rebuild_on_start:
            self._rebuild_state()

    # -- startup rebuild (reference: scheduler.go:86-106) --------------------

    def _rebuild_state(self) -> None:
        try:
            assumed = self.clientset.list_pods(
                label_selector={consts.ANNOTATION_ASSUMED: "true"}
            )
        except Exception as e:  # pragma: no cover - startup best effort
            log.warning("state rebuild: list assumed pods failed: %s", e)
            return
        for pod in assumed:
            if pod.is_completed():
                continue
            node = assigned_node(pod)
            if not node:
                continue
            try:
                self.add_pod(pod)
            except Exception as e:
                log.warning("state rebuild: add pod %s failed: %s", pod.key, e)

    def _get_allocator(self, node_name: str) -> Optional[NodeAllocator]:
        """Cache-or-fetch a node's allocator, replaying its assumed pods
        (reference: getNodeInfo, scheduler.go:62-84)."""
        with self.lock:
            na = self.allocators.get(node_name)
        if na is not None:
            return na
        return self._create_allocator(node_name)

    def get_allocators(
        self, node_names: list[str]
    ) -> dict[str, Optional[NodeAllocator]]:
        """Batch allocator fetch: ONE registry-lock acquisition for every
        cached node (the common case after warm-up), cold builds off-lock.
        assume/score/gang-planning call this instead of re-entering the
        global lock per candidate node."""
        out: dict[str, Optional[NodeAllocator]] = {}
        missing: list[str] = []
        with self.lock:
            for n in node_names:
                na = self.allocators.get(n)
                if na is not None:
                    out[n] = na
                else:
                    missing.append(n)
        for n in missing:
            out[n] = self._create_allocator(n)
        return out

    def _create_allocator(self, node_name: str) -> Optional[NodeAllocator]:
        """Cold path: fetch the node and its assumed-pod list OUTSIDE the
        registry lock (these are network calls — under the old coarse lock
        a cold fetch stalled every verb in the process), then insert and
        replay under it.  A concurrent creator may win the insert race; the
        loser defers to the winner's instance."""
        try:
            node = self.clientset.get_node(node_name)
        except Exception as e:
            log.debug("get node %s: %s", node_name, e)
            return None
        na = NodeAllocator(node)
        na.JOURNAL = self.JOURNAL  # resync records follow the engine's stream
        if na.chips.num_chips == 0:
            return None
        # replay pods already assumed onto this node
        try:
            pods = self.clientset.list_pods(
                label_selector={consts.ANNOTATION_ASSUMED: "true"},
                field_selector=lambda p: assigned_node(p) == node_name
                and not p.is_completed(),
            )
        except Exception:
            pods = []
        replayed: list[Pod] = []
        with self.lock:
            cur = self.allocators.get(node_name)
            if cur is not None:
                return cur  # lost the creation race; ours was never visible
            self.allocators[node_name] = na
            if self.index is not None:
                # only the WINNING instance enters the index; hooked before
                # the assumed-pod replay below so those na.add commits
                # dirty the entry like any later mutation
                na.on_change = self.index.mark_dirty
                self.index.note_node(node_name, na)
            if self.JOURNAL.enabled:
                # capacity inventory first, so every later bind/forget on
                # this node replays against a known chip set; generation
                # rides along so offline what-if replay can key
                # profile-aware scores by TPU type
                self.JOURNAL.record(
                    "node_add", node=node_name, generation=na.generation,
                    **na.chips.inventory(),
                )
            for pod in pods:
                if pod.key in self.pod_maps:
                    continue
                opt = option_from_pod(pod, na.chips.topo)
                if opt is None:
                    continue
                try:
                    na.add(opt)
                    self.pod_maps[pod.key] = (node_name, opt)
                    replayed.append(pod)
                    self._journal_event(
                        "bind", pod, node_name, opt=opt, source="replay"
                    )
                except ValueError as e:
                    log.warning("replay %s on %s: %s", pod.key, node_name, e)
        # Close the fetch-window race: a pod that completed or was deleted
        # while we were listing got its forget_pod as a no-op (no ledger
        # entry existed yet) and, if its delete event is already consumed,
        # nothing would ever free the capacity we just replayed.  Re-check
        # each replayed pod now that the entry exists — a deletion AFTER
        # this check finds the entry via the normal watch/resync path.
        for pod in replayed:
            stale = False
            try:
                cur_pod = self.clientset.get_pod(
                    pod.metadata.namespace, pod.metadata.name
                )
                stale = (
                    cur_pod.metadata.uid != pod.metadata.uid
                    or cur_pod.is_completed()
                )
            except Exception as e:
                stale = is_not_found(e)
            if stale:
                log.info(
                    "replay %s on %s: pod ended during allocator build; "
                    "releasing", pod.key, node_name,
                )
                self.forget_pod(pod)
        return na

    # -- verbs ---------------------------------------------------------------

    def admits(self, request: TPURequest) -> Optional[str]:
        """Mode-level admission policy hook: return a rejection reason or
        None.  The base engine (tpushare) admits every valid request;
        TPUWholeScheduler (tpuwhole) rejects fractional shapes."""
        return None

    def _index_partition(self, request: TPURequest, node_names: list[str]):
        """Split candidates through the capacity index: ``decided`` holds
        (feasible, score) verdicts the index answered — O(1) necessary-
        condition rejections plus congruence-class memo hits — ``groups``
        holds congruence classes awaiting ONE representative probe each,
        and ``rest`` falls through to the legacy per-node search (no
        entry, or a rater that is not translation-invariant).  Index
        verdicts are bit-identical to what the per-node trade would
        return (tests/test_cluster_index.py)."""
        idx = self.index
        idx.fold()
        demand = request_demand(request)
        invariant = getattr(self.rater, "translation_invariant", False)
        decided: dict[str, tuple] = {}
        groups: dict[tuple, list[str]] = {}
        rest: list[str] = []
        entries = idx.entries
        for n in node_names:
            e = entries.get(n)
            if e is None:
                rest.append(n)
                idx.misses += 1
                continue
            if not idx.can_fit(e, demand):
                # a NECESSARY condition failed: the DFS could only reach
                # the same verdict, so skip the node lock + search
                decided[n] = (False, None)
                idx.hits += 1
                continue
            if not invariant:
                rest.append(n)
                idx.misses += 1
                continue
            key = (request.units, request.container_names, e.plan_key)
            cached = idx.memo_get(key)
            if cached is not _MISS:
                decided[n] = cached
                idx.hits += 1
            else:
                groups.setdefault(key, []).append(n)
        return decided, groups, rest

    def _resolve_classes(
        self, request: TPURequest, groups: dict
    ) -> dict[str, tuple]:
        """One FRESH probe per congruence class (the first member pays
        it), memoized under the class's state key — every congruent
        candidate, in this verb and the next, reuses the verdict.  The
        probe bypasses the per-pod assume cache on purpose: the memo is
        keyed by node STATE and must never launder a stale pod-cached
        option into a class-wide answer."""
        idx = self.index
        out: dict[str, tuple] = {}
        for key, members in groups.items():
            rep = members[0]
            na = self._get_allocator(rep)
            if na is None:
                res = (False, None)
            else:
                opt = na.probe(request, self.rater)
                res = (opt is not None, None if opt is None else opt.score)
            idx.memo_put(key, res)
            idx.misses += 1  # the representative's probe
            idx.hits += len(members) - 1
            for m in members:
                out[m] = res
        return out

    def assume(
        self, node_names: list[str], pod: Pod
    ) -> tuple[list[str], dict[str, str]]:
        """Filter: which candidate nodes can host the pod
        (reference: scheduler.go:112-168)."""
        request = request_from_pod(pod)
        reason = self.admits(request)
        if reason is not None:
            return [], {n: reason for n in node_names}
        cordoned = self._cordoned_set() if self.cordoned else ()
        if cordoned:
            failed0 = {
                n: "cordoned for defragmentation"
                for n in node_names if n in cordoned
            }
            node_names = [n for n in node_names if n not in cordoned]
            if not node_names:
                return [], failed0
        else:
            failed0 = {}
        with TRACER.span(
            "sched.assume", pod=pod.key, nodes=len(node_names),
        ) as sp:
            decided: dict[str, tuple] = {}
            rest = node_names
            if self.index is not None and request.needs_tpu:
                decided, groups, rest = self._index_partition(
                    request, node_names
                )
                decided.update(self._resolve_classes(request, groups))
            by_name = self.get_allocators(rest)
            allocators = [(n, by_name[n]) for n in rest]

            ok: list[str] = []
            failed: dict[str, str] = dict(failed0)

            def try_node(item):
                name, na = item
                if na is None:
                    return name, "no TPU capacity visible"
                opt = na.assume(request, self.rater)
                if opt is None:
                    return name, "insufficient TPU resources"
                return name, None

            verdicts = dict(self._pool.map(try_node, allocators))
            for name in node_names:  # preserve candidate order
                if name in decided:
                    if decided[name][0]:
                        ok.append(name)
                    else:
                        failed[name] = "insufficient TPU resources"
                    continue
                err = verdicts.get(name)
                if err is None:
                    ok.append(name)
                else:
                    failed[name] = err
            plane = self.policies
            if plane is not None and ok and plane.wants("filter"):
                ok, failed = self._apply_filter_policy(
                    plane, request, pod, ok, failed
                )
            sp.set_attr("feasible", len(ok))
            sp.set_attr("index_decided", len(decided))
            return ok, failed

    def filter_policy_inputs(
        self, request: TPURequest, wclass: str, node_names: list[str],
    ) -> dict[str, dict]:
        """Per-node typed input vectors for the ``filter`` policy verb
        (policy/rater.py FILTER_INPUTS): capacity/fragmentation from the
        index entry when present (O(1), no node lock), allocator sums
        otherwise, plus the profile observatory's measured behavior for
        the pod's workload class (normalized throughput on the node's
        generation; worst interference ratio vs the classes currently
        resident there).  Shared by assume() and the gang prefilter."""
        from ..policy.rater import behavior_factors

        d_core, d_hbm, d_chips = request_demand(request)
        entries = {}
        if self.index is not None:
            self.index.fold()
            entries = self.index.entries
        # one profiles/matrix fold per verb, not per node
        prof_on = PROFILER.enabled
        profiles = PROFILER.profiles() if prof_on else {}
        matrix = PROFILER.interference_matrix() if prof_on else {}
        out: dict[str, dict] = {}
        by_name = self.get_allocators(
            [n for n in node_names if n not in entries]
        ) if any(n not in entries for n in node_names) else {}
        for n in node_names:
            e = entries.get(n)
            if e is not None:
                free_chips, free_core, free_hbm = (
                    e.free_chips, e.free_core, e.free_hbm,
                )
                frag, largest, gen = e.frag, e.largest, e.generation
                na = self.allocators.get(n)
                if na is not None:
                    total_chips = na.chips.num_chips
                else:  # entry without a cached allocator: topology bound
                    total_chips = 1
                    for d in e.topo_key[0]:
                        total_chips *= d
            else:
                na = by_name.get(n)
                if na is None:
                    continue
                with na.lock:
                    cs = na.chips
                    free_chips = cs.free_count()
                    free_core = cs.avail_core()
                    free_hbm = cs.avail_hbm()
                    total_chips = cs.num_chips
                    largest = cs.largest_free_box() if free_chips else 0
                frag = (
                    round(1.0 - largest / free_chips, 4)
                    if free_chips else 0.0
                )
                gen = na.generation
            tput, ifx = 1.0, 1.0
            if prof_on:
                tput, ifx = behavior_factors(
                    profiles, matrix, wclass, gen,
                    PROFILER.classes_on_node(n),
                )
            out[n] = {
                "free_chips": float(free_chips),
                "free_core": float(free_core),
                "free_hbm": float(free_hbm),
                "total_chips": float(total_chips),
                "frag": float(frag),
                "largest_box": float(largest),
                "demand_core": float(d_core),
                "demand_hbm": float(d_hbm),
                "demand_chips": float(d_chips),
                "tput": tput,
                "interference": ifx,
            }
        return out

    def _apply_filter_policy(
        self, plane, request: TPURequest, pod: Pod,
        ok: list[str], failed: dict[str, str],
    ) -> tuple[list[str], dict[str, str]]:
        """Run the loaded ``filter`` policy over the feasible set.  A
        canary filter splits by the same deterministic pod hash as the
        score canary; faults KEEP the node (the incumbent already
        passed it), and the SLO monitor watches the per-arm reject rate
        for auto-rollback."""
        pol, arm = plane.decide("filter", pod.key)
        if pol is None:
            if arm == "incumbent":
                # the incumbent arm keeps every built-in-feasible node;
                # its kept/total still feeds the reject-rate comparison
                plane.note_filter_decision(arm, len(ok), len(ok))
            return ok, failed
        inputs = self.filter_policy_inputs(
            request, workload_class(pod), ok
        )
        kept: list[str] = []
        for n in ok:
            info = inputs.get(n)
            if info is None or plane.eval_filter(pol, info):
                kept.append(n)
            else:
                failed[n] = f"rejected by policy {pol.name}"
        plane.note_filter_decision(arm, len(kept), len(ok))
        return kept, failed

    def score(self, node_names: list[str], pod: Pod) -> list[int]:
        """Priorities verb (reference: scheduler.go:170-184)."""
        from ..core.rater import to_extender_score

        request = request_from_pod(pod)
        with TRACER.span(
            "sched.score", pod=pod.key, nodes=len(node_names),
        ):
            decided: dict[str, tuple] = {}
            rest = node_names
            if self.index is not None and request.needs_tpu:
                # same index partition as assume(): a filter→score pair
                # pays the class probes once (the memo is state-keyed)
                decided, groups, rest = self._index_partition(
                    request, node_names
                )
                decided.update(self._resolve_classes(request, groups))
            # ONE registry-lock acquisition for all candidates, like
            # assume() — the old loop re-entered the global lock per node,
            # serializing priorities against every in-flight bind
            by_name = self.get_allocators(rest)
            scores = []
            for n in node_names:
                if n in decided:
                    feasible, s = decided[n]
                    scores.append(
                        to_extender_score(s)
                        if feasible
                        else consts.SCORE_MIN
                    )
                    continue
                na = by_name[n]
                if na is None:
                    scores.append(consts.SCORE_MIN)
                    continue
                s = na.score(request, self.rater)
                scores.append(
                    consts.SCORE_MIN if s is None else to_extender_score(s)
                )
            return scores

    def bind(self, node_name: str, pod: Pod) -> Pod:
        """Commit + persist + bind (reference: scheduler.go:186-227).

        Raises on failure; the committed allocation is rolled back if the
        annotation write or binding POST cannot be completed.
        """
        request = request_from_pod(pod)
        reason = self.admits(request)
        if reason is not None:  # bind can arrive without a filter pass
            raise RuntimeError(f"bind: {reason}")
        with TRACER.span(
            "sched.bind", pod=pod.key, node=node_name,
        ) as sp:
            na = self._get_allocator(node_name)
            if na is None:
                raise RuntimeError(
                    f"bind: node {node_name} has no TPU allocator"
                )
            # score-verb policy canary: a deterministic pod-hash fraction
            # of binds places under the CANDIDATE policy rater, the rest
            # under the incumbent; both arms journal a `policy` record
            # with the cross-scored divergence (note_bind_decision) and
            # feed the SLO monitor that auto-rolls a regressing canary
            # back.  One dict check when nothing is canarying.
            plane = self.policies
            rater = self.rater
            decision = None
            t_bind0 = time.perf_counter()
            if plane is not None and plane.wants("score"):
                rater, decision = plane.score_rater_for(pod.key, self.rater)
            # the placement search runs under the NODE's lock only — binds
            # to different nodes no longer serialize on the registry lock
            # (a pod mid-bind carries no assumed label yet, so no
            # controller callback can race a forget in this window)
            opt = na.allocate(request, rater)
            with self.lock:
                if self.allocators.get(node_name) is not na:
                    # the node was pruned (remove_node: it vanished from
                    # the cluster) between the off-lock fetch and this
                    # commit — committing would charge a zombie allocator
                    # and journal a bind AFTER the node_remove.  Free the
                    # orphan charge and refuse; kube-scheduler retries.
                    na.forget(opt)
                    raise RuntimeError(
                        f"bind: node {node_name} was removed mid-bind"
                    )
                self.pod_maps[pod.key] = (node_name, opt)
                self.released_pods.pop(pod.key, None)
                # journal at the COMMIT point, not after the API writes:
                # a concurrent forget (pod deleted mid-bind) must never
                # reach the journal before the bind it undoes
                self._update_node_gauge(node_name)
                self._journal_event(
                    "bind", pod, node_name, opt=opt, source="bind",
                    trace_id=sp.trace_id or None,
                )
            sp.event("allocated")
            if decision is not None:
                plane.note_bind_decision(
                    decision, pod_key=pod.key, node=node_name, opt=opt,
                    latency_s=time.perf_counter() - t_bind0, na=na,
                    incumbent=self.rater,
                )

            try:
                updated = self._write_annotations(pod, opt, node_name)
                sp.event("annotated")
                self.clientset.bind(
                    Binding(
                        pod_name=pod.metadata.name,
                        pod_namespace=pod.metadata.namespace,
                        pod_uid=pod.metadata.uid,
                        node=node_name,
                    )
                )
                sp.event("binding_posted")
                chips = [a.coords for a in opt.allocs if a.needs_tpu]
                sp.set_attr("chips", [str(c) for c in chips])
                AUDIT.record(
                    pod.key, "bind", trace_id=sp.trace_id, node=node_name,
                    chips=[str(c) for c in chips],
                )
                self._record_event(
                    pod, "Normal", "Scheduled",
                    f"bound to {node_name} (chips {chips})",
                )
                return updated
            except Exception as e:
                with self.lock:
                    entry = self.pod_maps.pop(pod.key, None)
                    if entry is not None:
                        # an absent entry means a racing forget_pod (pod
                        # deleted mid-bind) already freed the chips AND
                        # journaled the forget — freeing again here would
                        # credit back capacity charged to OTHER pods
                        # (Chip.give clamps, silently inflating avail)
                        na.forget(opt)
                        self._update_node_gauge(node_name)
                        self._journal_event(
                            "forget", pod, node_name, source="bind_rollback"
                        )
                self._record_event(
                    pod, "Warning", "FailedScheduling",
                    f"bind to {node_name}: {e}",
                )
                raise

    def preempt(
        self, node_name: str, pod: Pod, victims: list[Pod]
    ) -> Optional[list[Pod]]:
        """Preemption verb: which of ``victims`` must actually be evicted from
        ``node_name`` for ``pod`` to fit there?

        Returns the (possibly reduced) victim list, or ``None`` if the pod
        cannot fit even with every proposed victim gone — kube-scheduler then
        drops the node as a preemption candidate.  The reference never
        implements preemptVerb (README.md:47-89 lists only filter/priorities/
        bind); net-new here.

        Semantics:
        - Mode policy first: a preemptor admits() rejects could never bind
          after the evictions — return None so kube-scheduler drops the
          node instead of killing victims for nothing.
        - Simulated on a clone of the node's chip state; no live state is
          touched and nothing is evicted here — kube-scheduler performs the
          actual deletions, and the reconciliation controller frees the chips
          when the victims terminate.
        - Victims holding NO TPU allocation pass through untouched: they may
          be needed for resources (CPU/memory) this extender cannot see, so
          we only prune victims whose TPU chips we know are unnecessary.
        - Defensive re-check: a victim with priority >= the preemptor's is
          never treated as evictable TPU capacity — UNLESS it is a co-member
          of a gang that already has a legitimately-evictable victim:
          evicting any member kills the whole gang (the SPMD job cannot run
          short), so the co-member's chips come free as collateral either
          way and counting them is honest accounting, not an eligibility
          override (VERDICT r2 #5a).
        - Gang atomicity: victims of one gang free and reprieve AS A UNIT.
          Evicting one member while reprieving another would strand the
          reprieved member's chips on a dead job — exactly the silent-strand
          path this closes.  The server-side handler expands the proposal
          with same-node co-members first (handlers.py), so "evict one
          member" can never leave siblings behind on this node.
        - Reprieve pass mirrors kube-scheduler's own victim minimisation:
          restore highest-priority victims/gangs first, keep restored any
          whose chips the preemptor does not need.
        """
        request = request_from_pod(pod)
        if self.admits(request) is not None:
            # mode policy (tpuwhole): this preemptor could never bind even
            # with every victim gone — don't kill workloads for nothing
            return None
        na = self._get_allocator(node_name)
        if na is None:
            return None
        preemptor_prio = pod.spec.priority or 0
        with na.lock:
            scratch = na.chips.clone()

        # a gang is evictable capacity if ANY member is below the
        # preemptor's priority — eviction of that member kills the gang
        evictable_gangs = {
            g for g in (pod_gang_key(v) for v in victims
                        if (v.spec.priority or 0) < preemptor_prio)
            if g is not None
        }

        tpu_victims: list[tuple[Pod, Option]] = []
        passthrough: list[Pod] = []
        for v in victims:
            if (v.spec.priority or 0) >= preemptor_prio and (
                pod_gang_key(v) not in evictable_gangs
            ):
                # not evictable TPU capacity by this pod — but never SHRINK
                # kube-scheduler's proposal on an eligibility doubt (it
                # treats the returned set as authoritative); keep it listed,
                # claim no capacity from it
                passthrough.append(v)
                continue
            opt = None
            with self.lock:
                ledger = self.pod_maps.get(v.key)
            if ledger is not None and ledger[0] == node_name:
                opt = ledger[1]
            else:
                opt = option_from_pod(v, scratch.topo)
            if opt is None:
                passthrough.append(v)  # no TPU claim we can account for
            else:
                tpu_victims.append((v, opt))

        freed: list[tuple[Pod, Option]] = []
        for v, opt in tpu_victims:
            # validate BEFORE cancelling: Chip.give clamps at total, so a
            # skewed option (stale annotations, wrong node) would silently
            # inflate scratch capacity and confirm an eviction that frees
            # nothing.  Skew → keep the victim listed but claim no capacity.
            if scratch.can_cancel(opt):
                scratch.cancel(opt)
                freed.append((v, opt))
            else:
                passthrough.append(v)
        if scratch.trade(request, self.rater) is None:
            return None

        # reprieve whole gangs at once: restoring one member of a gang whose
        # sibling stays evicted would "free" chips onto a dead job.  A gang
        # with ANY member stuck in passthrough (unresolvable/skewed option —
        # it stays in the returned victim set and WILL be evicted) is doomed:
        # its freed members must never be reprieved into strands.
        doomed_gangs = {
            g for g in (pod_gang_key(v) for v in passthrough) if g is not None
        }
        groups: dict[str, list[tuple[Pod, Option]]] = {}
        for v, opt in freed:
            groups.setdefault(pod_gang_key(v) or f"solo/{v.key}", []).append(
                (v, opt)
            )
        # Reprieve order: built-in restores highest-priority victims
        # first (key = -priority, ascending).  A loaded ``preempt``
        # policy replaces the ranking with its own victim preference
        # (HIGHER = evict first → reprieve LAST).  All-or-nothing like
        # defrag's _order_victims: a policy that faults on ANY group
        # falls back to the built-in order for the WHOLE set — mixing
        # policy scores with -priority values in one sort would place
        # the faulted groups arbitrarily under neither rule.
        ordered_groups = sorted(
            groups.items(),
            key=lambda kv: -max((v.spec.priority or 0) for v, _ in kv[1]),
        )
        plane = self.policies
        if plane is not None and plane.wants("preempt"):
            scores = plane.preempt_scores([
                {
                    "priority": float(
                        max((v.spec.priority or 0) for v, _ in grp)
                    ),
                    "chips": float(sum(
                        len(a.coords)
                        for _v, o in grp
                        for a in o.allocs if a.needs_tpu
                    )),
                    "members": float(len(grp)),
                    "is_gang": 0.0 if gkey.startswith("solo/") else 1.0,
                }
                for gkey, grp in ordered_groups
            ])
            if scores is not None:
                ordered_groups = [
                    g for _s, g in sorted(
                        zip(scores, ordered_groups), key=lambda t: t[0]
                    )
                ]

        needed: list[Pod] = []
        for gkey, group in ordered_groups:
            if gkey in doomed_gangs:
                needed.extend(v for v, _ in group)
                continue
            restored = []
            ok = True
            for v, opt in group:
                if scratch.can_transact(opt):
                    scratch.transact(opt)
                    restored.append(opt)
                else:
                    ok = False
                    break
            if ok and scratch.trade(request, self.rater) is not None:
                continue  # whole gang reprieved: pod fits without evicting it
            for opt in reversed(restored):
                scratch.cancel(opt)
            needed.extend(v for v, _ in group)
        return needed + passthrough

    # -- defrag primitives (defrag/DefragPlanner drives these) ---------------

    def cordon(self, node_name: str, ttl_s: float = 120.0) -> None:
        """Mark a node unschedulable for new placements (filter rejects
        it) while a defrag round vacates/fills it.  TTL-bounded: a
        crashed round cannot strand the node — the controller's resync
        prunes expired cordons."""
        with self.lock:
            self.cordoned[node_name] = time.monotonic() + ttl_s

    def uncordon(self, node_name: str) -> None:
        with self.lock:
            self.cordoned.pop(node_name, None)

    def prune_cordons(self) -> dict[str, float]:
        """Drop expired cordons; returns the live ones (node →
        seconds remaining)."""
        now = time.monotonic()
        with self.lock:
            expired = [n for n, t in self.cordoned.items() if t <= now]
            for n in expired:
                del self.cordoned[n]
            return {
                n: round(t - now, 3) for n, t in self.cordoned.items()
            }

    def _cordoned_set(self) -> set:
        return set(self.prune_cordons())

    def frag_snapshot(self, max_age_s: float = 10.0) -> dict:
        """node → (fragmentation_index, largest_free_submesh_chips),
        reusing the last gauge refresh; refreshes itself when the
        snapshot is older than ``max_age_s`` (so /scheduler/status and
        the defrag planner see mesh health without a Prometheus
        scrape).  The contiguous-box scan still never rides the bind
        path — only status/scrape/planner callers pay it."""
        if time.monotonic() - self._frag_cache_at > max_age_s:
            self._refresh_frag_gauges()
        return dict(self._frag_cache)

    def migrate_pod(
        self,
        pod: Pod,
        from_node: str,
        to_node: str,
        old_opt: Option,
        new_opt: Option,
        source: str = "defrag",
    ) -> Pod:
        """Atomically re-home a live pod's allocation (the defrag
        planner's evict→rebind transaction).

        Order matters: the DESTINATION is charged first (validating
        transact — raises if the planned chips were taken), then the
        source is freed; the transient double-charge is the safe error
        direction (no other pod can ever be double-booked).  The journal
        ``migrate`` record is emitted at the commit point under the
        engine lock; replay verifies the move conserved the pod's chip
        demand.  The annotation-ledger rewrite runs OFF the engine lock
        (like bind); on failure the in-memory move is reversed with a
        compensating journaled migration so ledger and memory re-agree.
        """
        if option_demand(old_opt) != option_demand(new_opt):
            raise RuntimeError(
                f"migrate {pod.key}: plan does not conserve chip demand"
            )
        with TRACER.span(
            "sched.migrate", pod=pod.key, src=from_node, dst=to_node,
        ) as sp:
            # cold-build off the engine lock (see gang_allocate); the
            # plan-staleness check below revalidates under the lock
            na_to = self._get_allocator(to_node)
            with self.lock:
                entry = self.pod_maps.get(pod.key)
                if (
                    entry is None
                    or entry[0] != from_node
                    or entry[1].allocs != old_opt.allocs
                ):
                    raise RuntimeError(
                        f"migrate {pod.key}: plan stale (live placement "
                        "changed since planning)"
                    )
                na_from = self.allocators.get(from_node)
                if na_to is None or na_from is None:
                    raise RuntimeError(
                        f"migrate {pod.key}: allocator missing for "
                        f"{from_node if na_from is None else to_node}"
                    )
                if self.allocators.get(to_node) is not na_to:
                    # destination pruned (remove_node) since the off-lock
                    # fetch: charging it would journal onto a removed node
                    raise RuntimeError(
                        f"migrate {pod.key}: node {to_node} was removed "
                        "mid-commit"
                    )
                na_to.add(new_opt)  # validating transact: raises if taken
                na_from.forget(old_opt)
                self.pod_maps[pod.key] = (to_node, new_opt)
                self._update_node_gauge(from_node)
                self._update_node_gauge(to_node)
                self._journal_migrate(
                    pod, from_node, to_node, old_opt, new_opt, source,
                    trace_id=sp.trace_id or None,
                )
            try:
                updated = self._write_annotations(pod, new_opt, to_node)
            except Exception:
                # reverse in memory + journal the compensation, so the
                # durable ledger (still from_node/old) and memory agree
                ledger_skew = False
                with self.lock:
                    entry = self.pod_maps.get(pod.key)
                    if entry is not None and entry[0] == to_node:
                        try:
                            na_from.add(old_opt)
                        except ValueError:
                            # old chips stolen mid-rollback (possible only
                            # via a filterless bind racing the cordon):
                            # keep the new placement in memory and flag it
                            # LOUDLY — the ledger now disagrees until the
                            # next annotation write succeeds.  The k8s
                            # Event write is HTTP; it happens after the
                            # lock releases
                            ledger_skew = True
                        else:
                            na_to.forget(new_opt)
                            self.pod_maps[pod.key] = (from_node, old_opt)
                            self._update_node_gauge(from_node)
                            self._update_node_gauge(to_node)
                            self._journal_migrate(
                                pod, to_node, from_node, new_opt, old_opt,
                                source="migrate_rollback",
                            )
                if ledger_skew:
                    self._record_event(
                        pod, "Warning", "MigrationLedgerSkew",
                        f"migration {from_node}->{to_node} could "
                        "not roll back (source chips taken); "
                        "annotations are stale",
                    )
                raise
            AUDIT.record(
                pod.key, "migrate", trace_id=sp.trace_id,
                src=from_node, dst=to_node, source=source,
            )
            self._record_event(
                pod, "Normal", "Migrated",
                f"defrag: relocated from {from_node} to {to_node}",
            )
            return updated

    def _journal_migrate(
        self, pod, from_node, to_node, old_opt, new_opt, source,
        trace_id=None,
    ):
        self._profile_note("bind", pod, to_node, new_opt)
        if not self.JOURNAL.enabled:
            return None
        if trace_id is None:
            ctx = TRACER.pod_context(pod.key)
            trace_id = ctx.trace_id if ctx is not None else None
        return self.JOURNAL.record(
            "migrate",
            pod=pod.key,
            uid=pod.metadata.uid,
            node=to_node,
            source_node=from_node,
            option=option_record(new_opt),
            option_old=option_record(old_opt),
            gang=pod_gang_key(pod),
            source=source,
            trace_id=trace_id or None,
            wclass=workload_class(pod),
        )

    # -- gang split-phase primitives (scheduler/gang.py's commit protocol) ----
    #
    # The gang coordinator needs bind's three effects (allocate, annotate,
    # POST binding) as separately reversible steps so a mid-gang failure can
    # roll the WHOLE gang back to zero chips allocated / zero pods annotated
    # (SURVEY §7 hard part (b): assume-all-or-release).

    def gang_allocate(
        self, node_name: str, pod: Pod, source: str = "gang"
    ) -> Option:
        """In-memory allocation commit; reversed by ``gang_unallocate``.
        ``source`` labels the journal record (``gang`` for coordinator
        commits, ``resize`` for live gang-membership grows)."""
        request = request_from_pod(pod)
        # cold allocator materialization (k8s node fetch + assumed-pod
        # replay) stays OFF the engine lock — _get_allocator is race-safe
        # and idempotent, and a cold build under the lock would stall
        # every concurrent verb on one node's HTTP round-trip
        na = self._get_allocator(node_name)
        with self.lock:
            if na is None:
                raise RuntimeError(
                    f"gang allocate: node {node_name} has no TPU allocator"
                )
            if self.allocators.get(node_name) is not na:
                # pruned (remove_node) between the off-lock fetch and
                # this commit — charging the zombie instance would break
                # the journal's conservation invariant
                raise RuntimeError(
                    f"gang allocate: node {node_name} was removed "
                    "mid-commit"
                )
            opt = na.allocate(request, self.rater)
            self.pod_maps[pod.key] = (node_name, opt)
            self.released_pods.pop(pod.key, None)
            # journal at the phase-1 commit (the mutation), not at
            # post-commit bookkeeping: a racing mid-commit forget must
            # order AFTER this record, and a rolled-back gang balances
            # with gang_unallocate's forget records.  NO gauge refresh
            # here — phase 1 runs the whole gang under the engine lock,
            # and a per-member fragmentation scan inside that hold would
            # serialize every concurrent verb (gang_note_bound refreshes
            # per node after commit; the frag field may be one step stale)
            self._journal_event("bind", pod, node_name, opt=opt,
                                source=source)
            return opt

    def gang_apply_option(
        self, node_name: str, pod: Pod, opt: Option, source: str = "gang"
    ) -> None:
        """Apply a PRE-PLANNED option (validating transact — raises
        ValueError if the placement was taken since planning).  Lets a gang
        commit skip the per-member trade DFS."""
        # cold-build off the engine lock (see gang_allocate)
        na = self._get_allocator(node_name)
        with self.lock:
            if na is None:
                raise RuntimeError(
                    f"gang apply: node {node_name} has no TPU allocator"
                )
            if self.allocators.get(node_name) is not na:
                raise RuntimeError(
                    f"gang apply: node {node_name} was removed mid-commit"
                )
            na.add(opt)
            self.pod_maps[pod.key] = (node_name, opt)
            self.released_pods.pop(pod.key, None)
            self._journal_event("bind", pod, node_name, opt=opt,
                                source=source)

    def gang_unallocate(
        self, node_name: str, pod: Pod, opt: Option,
        source: str = "gang_rollback",
    ) -> None:
        with self.lock:
            entry = self.pod_maps.pop(pod.key, None)
            if entry is None:
                # already released (e.g. the controller forgot a deleted pod
                # mid-commit) — freeing again would double-free shared-chip
                # capacity held by OTHER pods
                return
            na = self.allocators.get(node_name)
            if na is not None:
                na.forget(opt)
            self._update_node_gauge(node_name)
            self._journal_event("forget", pod, node_name, source=source)

    def gang_annotate(
        self, pod: Pod, opt: Option, node_name: str, extra=None
    ) -> Pod:
        """``extra``: additional annotation keys the gang commit wants on
        the ledger (the DCN-boundary slice annotations for straddling
        gangs)."""
        return self._write_annotations(pod, opt, node_name, extra=extra)

    def gang_strip_annotations(self, pod: Pod) -> None:
        """Rollback of ``gang_annotate``: remove the ledger entry so neither
        restart rebuild nor the on-node agent sees an allocation.  Best-effort
        with one optimistic-conflict retry; a deleted pod needs no strip."""
        for attempt in range(2):
            try:
                cur = self.clientset.get_pod(
                    pod.metadata.namespace, pod.metadata.name
                )
            except Exception as e:
                if is_not_found(e):
                    return
                raise
            if cur.metadata.uid != pod.metadata.uid:
                return  # recreated; nothing of ours on it
            ann = cur.metadata.annotations
            removed = False
            for key in list(ann):
                if key.startswith(consts.ANNOTATION_CONTAINER_PREFIX) or key in (
                    consts.ANNOTATION_ASSUMED,
                    consts.ANNOTATION_NODE,
                    consts.ANNOTATION_TOPOLOGY,
                    consts.ANNOTATION_SLICE,
                    consts.ANNOTATION_GANG_SLICES,
                    consts.ANNOTATION_GANG_RANK,
                    consts.ANNOTATION_GANG_PEERS,
                    consts.ANNOTATION_TRACEPARENT,
                ):
                    ann.pop(key, None)
                    removed = True
            if cur.metadata.labels.pop(consts.ANNOTATION_ASSUMED, None) is not None:
                removed = True
            if not removed:
                return  # nothing of ours on it — skip the API write
            try:
                self.clientset.update_pod(cur)
                return
            except Exception as e:
                if is_conflict(e) and attempt == 0:
                    continue
                if is_not_found(e):
                    return
                raise

    def gang_post_binding(self, pod: Pod, node_name: str) -> None:
        self.clientset.bind(
            Binding(
                pod_name=pod.metadata.name,
                pod_namespace=pod.metadata.namespace,
                pod_uid=pod.metadata.uid,
                node=node_name,
            )
        )

    def gang_note_bound(self, pod: Pod, opt: Option, node_name: str) -> None:
        """Post-commit bookkeeping (gauge + event + audit), one member —
        the journal's bind record was already emitted at the phase-1
        allocation commit."""
        with self.lock:
            self._update_node_gauge(node_name)
        chips = [a.coords for a in opt.allocs if a.needs_tpu]
        ctx = TRACER.pod_context(pod.key)
        AUDIT.record(
            pod.key, "bind", trace_id=ctx.trace_id if ctx else "",
            node=node_name, chips=[str(c) for c in chips], gang=True,
        )
        self._record_event(
            pod, "Normal", "Scheduled",
            f"gang-bound to {node_name} (chips {chips})",
        )

    def _update_node_gauge(self, node_name: str) -> None:
        na = self.allocators.get(node_name)
        if na is not None:
            CHIPS_ALLOCATED.set(
                node_name,
                value=na.chips.total_core() - na.chips.avail_core(),
            )

    def _refresh_frag_gauges(self) -> None:
        """Scrape-time fragmentation refresh (LazyGauge.refresher): the
        contiguous-box scan runs on the scraper's request, never on the
        bind path.  Offline, the same numbers are derivable at ANY
        journal sequence number from the replayed chip state.

        With the capacity index on, only nodes DIRTIED since the last
        refresh are re-scanned (the index's second dirty-set consumer):
        a 10k-node fleet with a dozen binds between scrapes pays a dozen
        box scans, not ten thousand."""
        idx = self.index
        if idx is not None:
            # drain BEFORE folding: a mutation landing between the two
            # re-marks both sets, so it is re-read next cycle — draining
            # after the fold would latch the pre-mutation entry into the
            # gauges with nothing left to refresh it
            dirty = idx.take_frag_dirty()
            idx.fold()  # entries now fresh for every drained node
            if not dirty and self._frag_cache:
                self._frag_cache_at = time.monotonic()
                return
            cache = dict(self._frag_cache)
            entries = idx.entries
            for name in dirty:
                e = entries.get(name)
                if e is None:
                    cache.pop(name, None)
                else:
                    cache[name] = (e.frag, e.largest)
            # whole-series swap: a racing collect sees old or new, never
            # a cleared-but-unfilled intermediate
            FRAG_INDEX.replace({(n,): v[0] for n, v in cache.items()})
            FREE_SUBMESH.replace(
                {(n,): float(v[1]) for n, v in cache.items()}
            )
            self._frag_cache = cache
            self._frag_cache_at = time.monotonic()
            return
        with self.lock:
            allocators = dict(self.allocators)
        cache = {}
        for name, na in allocators.items():
            with na.lock:
                frag, largest, _free = na.chips.fragmentation()
            FRAG_INDEX.set(name, value=frag)
            FREE_SUBMESH.set(name, value=float(largest))
            cache[name] = (frag, largest)
        # snapshot reused by /scheduler/status and the defrag planner
        # (frag_snapshot) — whole-dict swap, GIL-atomic for readers
        self._frag_cache = cache
        self._frag_cache_at = time.monotonic()

    def register_checkpoint_provider(self) -> None:
        """Point the engine's journal's segment-head checkpoints at THIS
        engine.  Called at construction, and again after a journal
        reconfigure (``Journal.configure`` clears the provider — a new
        leader reopening its journal at warm takeover must re-register
        before its requested boot checkpoint can be written)."""
        ref = weakref.ref(self)
        self.JOURNAL.checkpoint_provider = lambda: (
            lambda s: s._journal_checkpoint() if s is not None else None
        )(ref())

    def _journal_checkpoint(self) -> Optional[dict]:
        """Full-state snapshot for the journal's segment-head checkpoint
        (runs on the journal writer thread: registry under self.lock,
        per-node inventory under each node's own lock)."""
        if not self.JOURNAL.enabled:
            return None
        with self.lock:
            # exact as_of: every engine mutation journals INSIDE this
            # lock, so the seq read here covers precisely the mutations
            # in the ledger copy below — no claimed-covered-but-absent
            # window (the journal's own fallback reads it pre-provider,
            # which is safe but coarser)
            as_of = self.JOURNAL.last_seq()
            allocators = dict(self.allocators)
            pods = [
                {"pod": k, "node": n, "option": option_record(o)}
                for k, (n, o) in self.pod_maps.items()
            ]
        nodes = {}
        for name, na in allocators.items():
            with na.lock:
                inv = na.chips.inventory()
            # generation rides along so a pruned-prefix replay can rebuild
            # the capacity index's buckets without the node_add records
            inv["generation"] = na.generation
            nodes[name] = inv
        return {"as_of_seq": as_of, "nodes": nodes, "pods": pods}

    def _journal_event(
        self,
        type_: str,
        pod: Pod,
        node_name: str,
        opt: Optional[Option] = None,
        source: Optional[str] = None,
        trace_id: Optional[str] = None,
    ):
        """Emit one flight-recorder record for a committed allocator
        mutation (no-op unless the journal is enabled).  Carries the
        pod's trace id (cross-link to /traces) and, for binds, the pod's
        workload class so offline what-if replay can drive profile-aware
        raters.  Also the profile observatory's co-tenancy choke point:
        every committed bind/forget passes through here."""
        self._profile_note(type_, pod, node_name, opt)
        if not self.JOURNAL.enabled:
            return None
        if trace_id is None:
            ctx = TRACER.pod_context(pod.key)
            trace_id = ctx.trace_id if ctx is not None else None
        # no fragmentation fields: the replayed chip state derives them
        # exactly at any seq (ReplayResult.summary), and attaching them
        # here would put the contiguous-box scan on the bind path
        return self.JOURNAL.record(
            type_,
            pod=pod.key,
            uid=pod.metadata.uid,
            node=node_name,
            option=option_record(opt) if opt is not None else None,
            gang=pod_gang_key(pod),
            source=source,
            trace_id=trace_id or None,
            wclass=workload_class(pod) if type_ == "bind" else None,
        )

    def _profile_note(self, type_: str, pod: Pod, node_name: str, opt):
        """Keep the profile observatory's co-tenancy map current (one
        attribute check when profiling is off; O(chips) dict ops when
        on — never a scan, safe under the engine lock)."""
        if not PROFILER.enabled:
            return
        if type_ == "forget":
            PROFILER.note_unbind(pod.key)
            return
        if type_ != "bind" or opt is None:
            return
        coords: list = []
        fractional = False
        for a in opt.allocs:
            if not a.needs_tpu:
                continue
            coords.extend(a.coords)
            if not a.whole:
                fractional = True
        na = self.allocators.get(node_name)
        PROFILER.note_bind(
            pod.key,
            node_name,
            workload_class(pod),
            getattr(na, "generation", "unknown") if na else "unknown",
            tuple(coords),
            fractional,
        )

    def _record_event(self, pod: Pod, etype: str, reason: str, message: str):
        """Record a k8s Event for a scheduling outcome.  The reference wires
        an event broadcaster but never records (controller.go:57-60); here
        outcomes are observable via `kubectl describe pod`."""
        try:
            self.clientset.create_event(
                {
                    "apiVersion": "v1",
                    "kind": "Event",
                    "type": etype,
                    "reason": reason,
                    "message": message,
                    "involvedObject": {
                        "kind": "Pod",
                        "namespace": pod.metadata.namespace,
                        "name": pod.metadata.name,
                        "uid": pod.metadata.uid,
                    },
                    "source": {"component": "tpu-elastic-scheduler"},
                }
            )
        except Exception:  # events are best-effort
            pass

    def _write_annotations(
        self, pod: Pod, opt: Option, node_name: str, extra=None
    ) -> Pod:
        """Annotation-ledger write with one optimistic-conflict retry
        (reference: scheduler.go:199-213).

        The write carries the pod's trace context (W3C traceparent
        annotation) alongside the allocation: the durable ledger is how
        the on-node side (device plugin Allocate, launcher) learns which
        scheduling trace it belongs to.  ``pod_traceparent`` resolves by
        pod key so gang commits writing from pool threads (no span on
        their stack) still propagate the member's own trace."""
        traceparent = (
            TRACER.pod_traceparent(pod.key) or TRACER.current_traceparent()
        )
        attempts = 2
        cur = pod
        for i in range(attempts):
            cur.metadata.annotations.update(annotations_for_option(opt, node_name))
            if traceparent:
                cur.metadata.annotations[consts.ANNOTATION_TRACEPARENT] = (
                    traceparent
                )
            if extra:
                cur.metadata.annotations.update(extra)
            cur.metadata.labels[consts.ANNOTATION_ASSUMED] = "true"
            try:
                return self.clientset.update_pod(cur)
            except Exception as e:
                if is_conflict(e) and i < attempts - 1:
                    fresh = self.clientset.get_pod(
                        pod.metadata.namespace, pod.metadata.name
                    )
                    if fresh.metadata.uid != pod.metadata.uid:
                        raise RuntimeError(
                            f"bind: pod {pod.key} was recreated (uid changed)"
                        ) from None
                    cur = fresh
                    continue
                raise
        raise RuntimeError("unreachable")

    # -- reconciliation hooks (reference: scheduler.go:229-281) --------------

    def add_pod(self, pod: Pod, source: str = "add") -> None:
        """Learn an allocation committed elsewhere (controller/startup)."""
        node_name = assigned_node(pod)
        if not node_name:
            return
        if pod.key in self.pod_maps:  # GIL-atomic fast path; re-checked
            return                    # under the lock below
        # cold-build off the engine lock (see gang_allocate)
        na = self._get_allocator(node_name)
        if na is None:
            return
        with self.lock:
            # _get_allocator may already have replayed this pod, or a
            # racing add_pod may have won
            if pod.key in self.pod_maps:
                return
            if self.allocators.get(node_name) is not na:
                # pruned (remove_node) since the off-lock fetch; if the
                # node truly exists the next resync re-learns the pod
                return
            opt = option_from_pod(pod, na.chips.topo)
            if opt is None:
                return
            try:
                na.add(opt)
            except ValueError as e:
                log.warning("add_pod %s: %s", pod.key, e)
                return
            self.pod_maps[pod.key] = (node_name, opt)
            self.released_pods.pop(pod.key, None)
            self._journal_event("bind", pod, node_name, opt=opt, source=source)

    def forget_pod(self, pod: Pod, source: str = "forget") -> None:
        """Free a completed/deleted pod's chips, at most once
        (reference: scheduler.go:247-267)."""
        with self.lock:
            entry = self.pod_maps.pop(pod.key, None)
            if entry is None:
                return
            if self.released_pods.get(pod.key) == pod.metadata.uid:
                return
            node_name, opt = entry
            na = self.allocators.get(node_name)
            if na is not None:
                na.forget(opt)
            self._update_node_gauge(node_name)
            self._journal_event("forget", pod, node_name, source=source)
            self.released_pods[pod.key] = pod.metadata.uid
            while len(self.released_pods) > self.released_pods_max:
                self.released_pods.pop(next(iter(self.released_pods)))

    def remove_node(self, node_name: str, source: str = "resync") -> bool:
        """Drop a node whose Node object vanished from the cluster (the
        reconciliation controller's resync calls this; before it existed
        the allocator registry leaked every decommissioned node forever,
        and journal/replay.py carried a ``node_remove`` handler nothing
        emitted).  Refuses while any ledger pod still charges the node —
        capacity leaves only through forget/migrate, so replay can hold
        its capacity-conservation invariant across the removal.  The
        ``node_remove`` record is emitted under the engine lock at the
        commit point, like every allocator mutation.

        The occupancy check is pod_maps-ONLY: in-flight verbs that
        prefetched this node's allocator off-lock (bind / gang commit /
        migrate / add_pod) are not visible here, so each of those commit
        points re-validates registry membership under the lock and backs
        out if the allocator was pruned in the window — a removal can
        cost a racing verb one clean retry, never a zombie charge."""
        with self.lock:
            na = self.allocators.get(node_name)
            if na is None:
                return False
            if any(n == node_name for n, _opt in self.pod_maps.values()):
                log.warning(
                    "remove_node %s: refused — ledger pods still charge "
                    "it (forget/migrate them first)", node_name,
                )
                return False
            del self.allocators[node_name]
            self.cordoned.pop(node_name, None)
            self._frag_cache.pop(node_name, None)
            if self.index is not None:
                self.index.drop_node(node_name)
            CHIPS_ALLOCATED.remove(node_name)
            FRAG_INDEX.remove(node_name)
            FREE_SUBMESH.remove(node_name)
            if self.JOURNAL.enabled:
                self.JOURNAL.record(
                    "node_remove", node=node_name, source=source
                )
        log.info("removed vanished node %s from the allocator registry",
                 node_name)
        return True

    def known_pod(self, pod: Pod) -> bool:
        with self.lock:
            return pod.key in self.pod_maps

    def released_pod(self, pod: Pod) -> bool:
        with self.lock:
            return self.released_pods.get(pod.key) == pod.metadata.uid

    def status(self) -> dict:
        """Per-node chip availability dump (reference: scheduler.go:283-290).

        Registry snapshot under the global lock, per-node dumps under each
        node's own lock — a debug scrape no longer freezes every verb for
        the duration of the full-state walk."""
        with self.lock:
            allocators = dict(self.allocators)
            pods = sorted(self.pod_maps)
        nodes = {n: na.status() for n, na in allocators.items()}
        # mesh health from the last gauge-refresh snapshot (self-refreshing
        # when stale) — operators and the defrag planner read fragmentation
        # here without a Prometheus scrape, and the contiguous-box scan
        # still never rides the bind path
        frag = self.frag_snapshot()
        for n, d in nodes.items():
            if n in frag:
                d["fragmentation_index"] = frag[n][0]
                d["largest_free_submesh_chips"] = frag[n][1]
        out = {
            "scheduler": self.name,
            "rater": self.rater.name,
            "nodes": nodes,
            "pods": pods,
        }
        cordons = self.prune_cordons()
        if cordons:
            out["cordoned"] = sorted(cordons)
        return out

    def status_summary(
        self, top_k: int = 10, generations: bool = False
    ) -> dict:
        """Fleet-scale status: aggregate counts + the top-K fragmented
        nodes instead of the full per-node chip dict.  At 10k nodes the
        classic dump serializes ~40k chip entries per poll; this answers
        the questions pollers actually ask (capacity left, per-generation
        spread, where defrag is owed) in O(nodes) small reads — from the
        capacity index when it is on, from per-node sums otherwise.
        ``GET /scheduler/status?summary=1[&top_k=N][&generations=1]`` —
        the per-node ``node_generations`` map (the one O(nodes) field;
        small strings, never chip dicts) ships only when asked for, so
        the default summary stays O(buckets + top_k)."""
        with self.lock:
            allocators = dict(self.allocators)
            n_pods = len(self.pod_maps)
        idx = self.index
        gens: dict[str, dict] = {}
        node_gens: dict[str, str] = {}
        totals = {
            "core_total": 0, "core_avail": 0,
            "hbm_total": 0, "hbm_avail": 0, "free_chips": 0,
        }

        def fold_node(name, gen, free_core, free_hbm, free_chips,
                      total_core, total_hbm):
            node_gens[name] = gen
            g = gens.setdefault(
                gen, {"nodes": 0, "free_chips": 0, "free_core": 0}
            )
            g["nodes"] += 1
            g["free_chips"] += free_chips
            g["free_core"] += free_core
            totals["core_total"] += total_core
            totals["core_avail"] += free_core
            totals["hbm_total"] += total_hbm
            totals["hbm_avail"] += free_hbm
            totals["free_chips"] += free_chips

        if idx is not None:
            idx.fold()
            entries = idx.entries
            for name in allocators:
                e = entries.get(name)
                if e is None:
                    continue
                fold_node(name, e.generation, e.free_core, e.free_hbm,
                          e.free_chips, e.total_core, e.total_hbm)
            top = idx.top_fragmented(top_k)
            index_stats = idx.stats()
            buckets = idx.bucket_stats()
        else:
            for name, na in allocators.items():
                with na.lock:
                    cs = na.chips
                    fold_node(
                        name, na.generation, cs.avail_core(),
                        cs.avail_hbm(), cs.free_count(),
                        cs.total_core(), cs.total_hbm(),
                    )
            frag = self.frag_snapshot()
            top = [
                {
                    "node": n,
                    "fragmentation_index": v[0],
                    "largest_free_submesh_chips": v[1],
                }
                for n, v in sorted(
                    frag.items(), key=lambda kv: (-kv[1][0], kv[0])
                )[:top_k]
            ]
            index_stats = None
            buckets = None
        out = {
            "scheduler": self.name,
            "rater": self.rater.name,
            "summary": True,
            "nodes": len(allocators),
            "pods": n_pods,
            "capacity": totals,
            "generations": gens,
            "top_fragmented": top,
        }
        if generations:
            out["node_generations"] = node_gens
        if index_stats is not None:
            out["index"] = index_stats
            out["buckets"] = buckets
        cordons = self.prune_cordons()
        if cordons:
            out["cordoned"] = len(cordons)
        return out
