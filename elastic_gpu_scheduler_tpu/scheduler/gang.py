"""Gang scheduling: all-or-nothing placement + bind for SPMD replica groups.

Net-new vs the reference (SURVEY §2 #19: the reference has no gang support;
this is the TPU build's counterpart of data/model-parallel job placement —
a 256-replica JAX job must land all replicas or none, BASELINE config 5).

Two cooperating mechanisms (SURVEY §7 hard part (b)):

1. **Plan at filter time.**  When the first gang member hits the filter verb,
   the coordinator *plans the whole gang*: it clones the current chip state of
   every candidate node (in ICI mesh order — slice, then host offset) and
   greedily places all N member shapes onto the clones.  If the gang cannot
   fully fit, every member is rejected — nothing is ever partially admitted.
   If it fits, the plan yields N node slots, and each arriving member's
   filter returns exactly its claimed slot.  Mesh-ordered planning makes the
   gang occupy contiguous hosts, so the slice's ICI links stay inside the
   job.  (Per-pod scattering — what the reference's per-pod verbs would do —
   lets N identical pods all chase the same "best" node and livelock; the
   plan is what makes 256-replica placement deterministic and fast.)

2. **Barrier at bind time.**  Each member's bind verb blocks until all N
   members' bind calls have arrived; only then does every member commit
   (allocate + annotation write + Binding POST).  A gang that doesn't fill
   within ``timeout`` seconds fails every waiter, releases the plan, and
   leaves nothing bound.  If a commit fails mid-gang, members not yet bound
   abort; already-bound members keep valid allocations (commit is
   crash-consistent best-effort — the same consistency the reference's
   single-pod bind path has, scheduler.go:199-227).

Pods opt in via annotations ``elasticgpu.io/gang-name`` and
``elasticgpu.io/gang-size``.  Gangs are assumed homogeneous (all members
request the same shape) — the SPMD case; heterogeneous members still bind,
but the plan is computed from the first member's shape.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..core.request import TPURequest, request_from_pod
from ..k8s.objects import Pod
from ..metrics import GANG_COMMIT, GANG_EVENTS
from ..utils import consts
from .scheduler import ResourceScheduler, TPUUnitScheduler

log = logging.getLogger("tpu-scheduler")


@dataclass
class _Plan:
    """Node slots for each gang member, in placement order."""

    slots: list[str]  # one node name per member, mesh-ordered
    claims: dict[str, str] = field(default_factory=dict)  # pod key → node
    created: float = 0.0
    # the member shape, so LATER plans can reserve this plan's capacity in
    # their clones (plans don't touch real allocators until bind)
    member_units: tuple = ()
    member_containers: tuple = ()
    bound: int = 0  # members already committed to the REAL allocators

    def claim(self, pod_key: str) -> Optional[str]:
        if pod_key in self.claims:
            return self.claims[pod_key]
        if len(self.claims) >= len(self.slots):
            return None
        node = self.slots[len(self.claims)]
        self.claims[pod_key] = node
        return node


@dataclass
class _Gang:
    name: str
    size: int
    created: float
    cond: threading.Condition
    members: dict[str, str] = field(default_factory=dict)  # pod key → node
    ready: bool = False
    failed: str = ""
    done: int = 0


class GangCoordinator:
    def __init__(self, clientset, timeout: float = 30.0):
        self.clientset = clientset
        self.timeout = timeout
        self._gangs: dict[str, _Gang] = {}
        self._plans: dict[str, _Plan] = {}
        self._lock = threading.Lock()
        # pod key → last commit duration (post-barrier); benchmark telemetry
        self.commit_secs: dict[str, float] = {}

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def gang_key(pod: Pod, req: TPURequest) -> str:
        return f"{pod.metadata.namespace}/{req.gang_name}"

    @staticmethod
    def is_gang_pod(req: TPURequest) -> bool:
        return bool(req.gang_name) and req.gang_size > 1

    def _node_mesh_order(self, names: list[str]) -> list[tuple[str, str]]:
        """Candidate nodes as (slice_id, name) in (slice, host-offset
        row-major) order so greedy planning fills the ICI mesh contiguously."""

        def key(name: str):
            try:
                node = self.clientset.get_node(name)
            except Exception:
                return ("~", 1 << 30, name)
            labels = node.metadata.labels or {}
            slice_id = labels.get(consts.LABEL_TPU_SLICE, "")
            offset = labels.get(consts.LABEL_TPU_HOST_OFFSET, "")
            try:
                from ..core.topology import parse_coord, parse_topology, Topology

                topo_spec = labels.get(consts.LABEL_TPU_TOPOLOGY, "")
                idx = (
                    Topology(parse_topology(topo_spec)).index(parse_coord(offset))
                    if topo_spec and offset
                    else 0
                )
            except Exception:
                idx = 0
            return (slice_id, idx, name)

        keyed = sorted(((key(n), n) for n in names))
        return [(k[0], n) for k, n in keyed]

    # -- filter-time planning ------------------------------------------------

    def filter(
        self, sched: TPUUnitScheduler, pod: Pod, node_names: list[str]
    ) -> tuple[list[str], dict[str, str]]:
        """Plan-once, steer-each-member filter for gang pods."""
        req = request_from_pod(pod)
        gkey = self.gang_key(pod, req)
        with self._lock:
            plan = self._plans.get(gkey)
            if plan is not None and time.monotonic() - plan.created > self.timeout:
                self._plans.pop(gkey, None)
                plan = None
            if plan is None:
                plan = self._plan(sched, req, node_names)
                if plan is None:
                    GANG_EVENTS.inc("plan_infeasible")
                    return [], {
                        n: f"gang {gkey}: {req.gang_size} members cannot fit"
                        for n in node_names
                    }
                plan.created = time.monotonic()
                plan.member_units = req.units
                plan.member_containers = req.container_names
                self._plans[gkey] = plan
                GANG_EVENTS.inc("planned")
            node = plan.claim(pod.key)
            if node is None:
                return [], {
                    n: f"gang {gkey}: all {req.gang_size} slots claimed"
                    for n in node_names
                }
            if node not in node_names:
                return [], {
                    n: f"gang {gkey}: planned node {node} not in candidates"
                    for n in node_names
                }
            return [node], {}

    def _plan(
        self, sched: TPUUnitScheduler, req: TPURequest, node_names: list[str]
    ) -> Optional[_Plan]:
        """Place all members onto cloned chip state.

        Slice-affine: each ICI slice is tried ALONE first (in mesh order), so
        a gang that fits inside one slice never straddles the DCN boundary;
        spanning slices is the last resort (collectives across slices fall
        off ICI onto DCN — the exact cost the placement model exists to
        avoid, SURVEY §5 'Distributed communication backend')."""
        ordered = self._node_mesh_order(node_names)
        slice_groups: dict[str, list[str]] = {}
        for slice_id, name in ordered:
            slice_groups.setdefault(slice_id, []).append(name)
        candidates: list[list[str]] = [g for g in slice_groups.values()]
        if len(candidates) > 1:
            candidates.append([n for _, n in ordered])  # spanning fallback
        demand = req.total_chips_equiv * req.gang_size * 100  # core units
        for group in candidates:
            # cheap prefilter: skip groups whose total free core can't hold
            # the gang (saves the clone+replay work on hopeless slices)
            free = 0
            for name in group:
                with sched.lock:
                    na = sched._get_allocator(name)
                if na is not None:
                    with na.lock:
                        free += na.chips.avail_core()
            if free < demand:
                continue
            slots = self._plan_on(sched, req, group)
            if slots is not None:
                return _Plan(slots=slots)
        return None

    def _reserve_other_plans(self, sched, clones: dict, get_clone) -> None:
        """Replay other ACTIVE plans' unbound placements into the clones so
        concurrent gangs don't double-count the same free chips (caller holds
        self._lock).  Without this, two gangs planned back-to-back both pass
        filter against the same capacity and one fails mid-commit."""
        now = time.monotonic()
        for other_key, other in self._plans.items():
            if now - other.created > self.timeout or not other.member_units:
                continue
            # members already bound are in the real allocator state the
            # clones start from — replaying them too would double-count
            for idx, node in enumerate(other.slots[other.bound :]):
                cs = get_clone(node)
                if cs is None:
                    continue
                member_req = TPURequest(
                    pod_uid=f"resv-{other_key}-{idx}",
                    pod_key=f"resv/{other_key}/{idx}",
                    units=other.member_units,
                    container_names=other.member_containers,
                )
                opt = cs.trade(member_req, sched.rater)
                if opt is not None:
                    cs.transact(opt)

    def _plan_on(
        self, sched: TPUUnitScheduler, req: TPURequest, ordered: list[str]
    ) -> Optional[list[str]]:
        """Greedy member placement over one candidate node group (cloned).

        Members are homogeneous (same shape), so a node that cannot fit
        member k cannot fit member k+1 either — the scan cursor only moves
        forward, making planning O(members + nodes) instead of O(m·n)
        (a v5p-2048 gang plans in one pass over 256 hosts)."""
        clones = {}

        def get_clone(name):
            cs = clones.get(name)
            if cs is None:
                with sched.lock:
                    na = sched._get_allocator(name)
                if na is None:
                    return None
                with na.lock:
                    cs = na.chips.clone()
                clones[name] = cs
            return cs

        self._reserve_other_plans(sched, clones, get_clone)
        slots: list[str] = []
        cursor = 0
        for member in range(req.gang_size):
            member_req = TPURequest(
                pod_uid=f"plan-{member}",
                pod_key=f"plan/{member}",
                units=req.units,
                container_names=req.container_names,
            )
            placed = False
            while cursor < len(ordered):
                name = ordered[cursor]
                cs = get_clone(name)
                if cs is None:
                    cursor += 1
                    continue
                opt = cs.trade(member_req, sched.rater)
                if opt is None:
                    cursor += 1  # full for this shape → full for all members
                    continue
                cs.transact(opt)
                slots.append(name)
                placed = True
                break
            if not placed:
                return None
        return slots

    # -- bind-time barrier ---------------------------------------------------

    def bind(self, sched: ResourceScheduler, node: str, pod: Pod) -> None:
        req = request_from_pod(pod)
        if not self.is_gang_pod(req):
            sched.bind(node, pod)
            return
        gkey = self.gang_key(pod, req)
        with self._lock:
            g = self._gangs.get(gkey)
            if g is None:
                g = _Gang(
                    name=gkey,
                    size=req.gang_size,
                    created=time.monotonic(),
                    cond=threading.Condition(),
                )
                self._gangs[gkey] = g
                GANG_EVENTS.inc("created")

        with g.cond:
            if g.failed:
                self._maybe_gc(gkey, g)
                raise RuntimeError(f"gang {gkey}: {g.failed}")
            g.members[pod.key] = node
            if len(g.members) >= g.size:
                # pre-commit feasibility re-check: a non-gang pod may have
                # taken planned capacity since filter time (per-pod filters
                # don't see plans).  Verify every member still fits BEFORE
                # anyone commits, so infeasibility fails the gang with
                # nothing bound.  (A bind landing between this check and the
                # commits is still possible — commit remains best-effort.)
                if not self._members_still_fit(sched, req, g):
                    g.failed = "planned capacity no longer available"
                    GANG_EVENTS.inc("stale_plan")
                    g.cond.notify_all()
                else:
                    g.ready = True
                    GANG_EVENTS.inc("barrier_tripped")
                    g.cond.notify_all()
            else:
                deadline = g.created + self.timeout
                while not g.ready and not g.failed:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        g.failed = (
                            f"timed out with {len(g.members)}/{g.size} members"
                        )
                        GANG_EVENTS.inc("timeout")
                        g.cond.notify_all()
                        break
                    g.cond.wait(timeout=remaining)
            if g.failed:
                g.members.pop(pod.key, None)
                self._maybe_gc(gkey, g)
                raise RuntimeError(f"gang {gkey}: {g.failed}")

        # barrier tripped: commit this member
        try:
            t0 = time.perf_counter()
            sched.bind(node, pod)
            commit_s = time.perf_counter() - t0
            GANG_COMMIT.observe(value=commit_s)
            with self._lock:
                self.commit_secs[pod.key] = commit_s
        except Exception as e:
            with g.cond:
                if not g.failed:
                    g.failed = f"member {pod.key} bind failed: {e}"
                    GANG_EVENTS.inc("commit_failed")
                    g.cond.notify_all()
            raise
        with self._lock:
            plan = self._plans.get(gkey)
            if plan is not None:
                plan.bound += 1
        with g.cond:
            g.done += 1
            if g.done >= g.size:
                GANG_EVENTS.inc("bound")
            self._maybe_gc(gkey, g)

    def _members_still_fit(
        self, sched: TPUUnitScheduler, req: TPURequest, g: _Gang
    ) -> bool:
        """Can every member's shape still be placed on its chosen node?
        (Clones the current REAL allocator state per distinct node.)"""
        clones: dict[str, object] = {}
        for i, (pod_key, node) in enumerate(sorted(g.members.items())):
            cs = clones.get(node)
            if cs is None:
                with sched.lock:
                    na = sched._get_allocator(node)
                if na is None:
                    return False
                with na.lock:
                    cs = na.chips.clone()
                clones[node] = cs
            member_req = TPURequest(
                pod_uid=f"chk-{i}",
                pod_key=f"chk/{i}",
                units=req.units,
                container_names=req.container_names,
            )
            opt = cs.trade(member_req, sched.rater)
            if opt is None:
                return False
            cs.transact(opt)
        return True

    # -- bookkeeping ---------------------------------------------------------

    def _maybe_gc(self, key: str, g: _Gang) -> None:
        """Drop finished/failed-and-drained gangs + their plans
        (caller holds g.cond)."""
        finished = g.done >= g.size or (g.failed and not g.members)
        if finished:
            with self._lock:
                if self._gangs.get(key) is g:
                    del self._gangs[key]
                if g.done >= g.size or g.failed:
                    self._plans.pop(key, None)

    def status(self) -> dict:
        with self._lock:
            return {
                "gangs": {
                    k: {
                        "size": g.size,
                        "arrived": len(g.members),
                        "done": g.done,
                        "ready": g.ready,
                        "failed": g.failed,
                        "age_s": round(time.monotonic() - g.created, 3),
                    }
                    for k, g in self._gangs.items()
                },
                "plans": {
                    k: {"slots": len(p.slots), "claimed": len(p.claims)}
                    for k, p in self._plans.items()
                },
            }
