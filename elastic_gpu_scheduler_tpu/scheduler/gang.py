"""Gang scheduling: all-or-nothing placement + bind for SPMD replica groups.

Net-new vs the reference (SURVEY §2 #19: the reference has no gang support;
this is the TPU build's counterpart of data/model-parallel job placement —
a 256-replica JAX job must land all replicas or none, BASELINE config 5).

Two cooperating mechanisms (SURVEY §7 hard part (b)):

1. **Plan at filter time.**  When the first gang member hits the filter verb,
   the coordinator *plans the whole gang*: it clones the current chip state of
   every candidate node (in ICI mesh order — slice, then host offset; clones
   are O(words) bitset snapshots, core/allocator.ChipSet) and greedily places
   all N member shapes onto the clones.  Homogeneous whole-chip gangs — the
   SPMD shape — go through the ``plan_gang`` kernel (native C++ when built,
   bit-identical Python fallback otherwise): per-node free bitsets in, every
   member's box out of one call, no per-member DFS.  Everything else runs the
   per-member trade search with results memoized by (shape, node-state) so
   congruent hosts replay one placement instead of re-searching
   (``_trade_cached``).  If the gang cannot fully fit, every member is
   rejected — nothing is ever partially admitted.
   If it fits, the plan yields N node slots, and each arriving member's
   filter returns exactly its claimed slot.  Mesh-ordered planning makes the
   gang occupy contiguous hosts, so the slice's ICI links stay inside the
   job.  (Per-pod scattering — what the reference's per-pod verbs would do —
   lets N identical pods all chase the same "best" node and livelock; the
   plan is what makes 256-replica placement deterministic and fast.)

2. **Barrier + single-committer all-or-nothing commit at bind time.**  Each
   member's bind verb blocks until all N members' bind calls have arrived.
   The LAST arriver then commits the whole gang in three reversible phases
   (SURVEY §7 hard part (b), the assume-all-or-release protocol the
   reference never had):

   - phase 1 — allocate every member in-memory under the scheduler lock
     (doubles as the feasibility re-check: failure → forget all, nothing
     escaped the process);
   - phase 2 — write the annotation ledger for ALL members (bounded
     executor; failure → strip written annotations + forget all);
   - phase 3 — POST all Binding subresources (failure → strip ALL members'
     annotations + forget all allocations, so zero chips stay allocated and
     zero pods stay annotated even though an already-accepted Binding cannot
     be un-POSTed — such pods are bound but unprovisioned, and a Warning
     event records it).

   A gang that doesn't fill within ``timeout`` seconds fails every waiter,
   releases the plan, and leaves nothing bound.  A bounded executor (not the
   N blocked HTTP threads) performs the API writes, so a 256-member commit
   doesn't thrash 256 Python threads against the GIL.

Pods opt in via annotations ``elasticgpu.io/gang-name`` and
``elasticgpu.io/gang-size``.  The first member's shape seeds the plan (the
SPMD/homogeneous case needs nothing else).  A member arriving with a
DIFFERENT shape triggers a full REPLAN (VERDICT r2 #5b): every
already-claimed member is re-placed on its already-returned slot with its
ACTUAL shape, the new member and the not-yet-seen members (assumed
first-shape until they arrive) are placed fresh — so every shape the
coordinator has SEEN is accounted exactly, and a heterogeneous gang that
cannot fit is rejected at filter with a named error instead of silently
mis-admitted and failed at the bind barrier.  Unseen members are the one
remaining guess; a wrong guess degrades to the phase-1 all-or-nothing
re-check at commit, never to over-commit.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Optional

from ..core.allocator import (
    ContainerAlloc,
    Option,
    iter_bits,
    plan_gang_batch_fallback,
    plan_gang_fallback,
)
from ..core.index import request_demand
from ..core.request import TPURequest, request_from_pod
from ..faultinject import FAULTS
from ..k8s.objects import Pod
from ..metrics import GANG_COMMIT, GANG_EVENTS, PLAN_CACHE, TimedLock
from ..tracing import AUDIT, NOOP_SPAN, TRACER
from ..utils import consts
from .scheduler import ResourceScheduler, TPUUnitScheduler

log = logging.getLogger("tpu-scheduler")

# sentinels for the whole-gang fast path / trade memo (None is a valid value)
_FAST_INELIGIBLE = object()
_MISS = object()


def _trap(fn, item):
    """Run fn(item), returning the exception instead of raising (so an
    executor map can collect per-member failures without cancelling peers)."""
    try:
        return fn(item)
    except Exception as e:
        return e


@dataclass
class _Plan:
    """Node slots for each gang member, in placement order."""

    slots: list[str]  # one node name per member, mesh-ordered
    # the Option computed for each slot during planning — commit applies it
    # directly (validating transact) instead of re-running the trade DFS per
    # member, turning a 256-member commit's phase 1 from 256 searches into
    # 256 O(chips-touched) applications
    options: list = field(default_factory=list)
    claims: dict[str, int] = field(default_factory=dict)  # pod key → slot idx
    created: float = 0.0
    last_claim: float = 0.0  # expiry is keyed off claim ACTIVITY, not age,
    # so a slow-arriving gang keeps its plan as long as members keep coming
    # the member shape, so LATER plans can reserve this plan's capacity in
    # their clones (plans don't touch real allocators until bind)
    member_units: tuple = ()
    member_containers: tuple = ()
    # per-slot ACTUAL shapes (VERDICT r2 #5b): seeded with the first
    # member's shape, overwritten per slot when a heterogeneous member
    # claims it via replan — reservation replay and the commit's
    # cached-option check use THESE, not the single seed shape
    slot_units: list = field(default_factory=list)
    slot_containers: list = field(default_factory=list)
    # node → slice id, captured from the SAME label reads planning ordered
    # candidates by — the commit's DCN-boundary annotations use this, so
    # no API call (and no swallowed API error) sits on the commit path
    node_slices: dict = field(default_factory=dict)
    # set while the single committer is writing this plan's allocations into
    # the REAL allocators — reservation replay must then skip it entirely
    committing: bool = False

    def claim(self, pod_key: str) -> Optional[str]:
        if pod_key in self.claims:
            return self.slots[self.claims[pod_key]]
        if len(self.claims) >= len(self.slots):
            return None
        idx = len(self.claims)
        self.claims[pod_key] = idx
        self.last_claim = time.monotonic()
        return self.slots[idx]


@dataclass
class _Gang:
    name: str
    size: int
    created: float
    cond: threading.Condition
    # pod key → (node, pod); pods are kept so the single committer can write
    # every member's annotations/binding itself
    members: dict[str, tuple[str, Pod]] = field(default_factory=dict)
    committed: bool = False
    failed: str = ""
    done: int = 0
    # phase telemetry (monotonic): barrier trip + commit completion, so the
    # wall can be decomposed into arrival / commit / response fan-out
    t_barrier: float = 0.0
    t_commit_end: float = 0.0


class GangCoordinator:
    def __init__(self, clientset, timeout: float = 30.0,
                 commit_workers: int = 16,
                 batch_window_s: float = 0.0, batch_min: int = 4):
        self.clientset = clientset
        self.timeout = timeout
        # batch admission sweep: >0 → the FIRST member of a gang parks up
        # to this long collecting other pending gangs' first members, then
        # ONE sweep plans the whole queue (shared clones, one reservation
        # replay, multi-spec plan_gang_batch kernel calls) instead of a
        # full per-gang rescan each.  0 (default) = plan-on-arrival,
        # exactly the pre-batch behavior.
        self.batch_window_s = batch_window_s
        self.batch_min = max(2, batch_min)
        self._batch_cond = threading.Condition()
        self._batch_pending: dict[str, tuple] = {}  # gkey → (req, names)
        self._batch_failed: dict[str, float] = {}  # gkey → monotonic stamp
        self._batch_sweeping = False
        self._gangs: dict[str, _Gang] = {}
        self._plans: dict[str, _Plan] = {}
        self._lock = TimedLock("gang", rank=10)  # wait-time →
        # metrics.LOCK_WAIT; rank: may be held while TAKING the
        # scheduler lock (filter->plan), never the reverse
        # bounded pool for the commit's API writes (annotations + bindings);
        # the N member HTTP threads just park on the barrier condition
        self._commit_pool = ThreadPoolExecutor(
            max_workers=max(1, commit_workers), thread_name_prefix="gang-commit"
        )
        # pod key → last commit duration (post-barrier); benchmark telemetry
        self.commit_secs: dict[str, float] = {}
        # Backstop warm of the native placement kernel for stacks built
        # WITHOUT cli.build_stack (tests, embedded executors): the cli
        # path already warms get_placement() synchronously before
        # constructing this coordinator (a deliberate
        # compile-before-serving readiness choice, cli.py), which makes
        # this thread a memoized no-op there.  For direct constructions
        # the first plan_gang call used to pay the g++ fork (~120s cold)
        # while HOLDING the gang lock — the static lockdep pass
        # (analysis/) flagged the path.  Daemon thread so construction
        # never stalls; a plan arriving mid-warm parks on the build's
        # own unranked lock exactly as it did pre-warm.
        from ..core.native import get_placement

        threading.Thread(
            target=get_placement, name="native-warm", daemon=True
        ).start()
        # optional DefragPlanner (defrag/): when set and in auto mode, an
        # infeasible gang plan triggers one defrag round and ONE filter
        # retry (the admission-retry path).  None = a single attribute
        # check on the infeasible path, nothing anywhere else.
        self.defrag = None

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def gang_key(pod: Pod, req: TPURequest) -> str:
        return f"{pod.metadata.namespace}/{req.gang_name}"

    @staticmethod
    def is_gang_pod(req: TPURequest) -> bool:
        return bool(req.gang_name) and req.gang_size > 1

    def _node_mesh_order(self, names: list[str]) -> list[tuple[str, str]]:
        """Candidate nodes as (slice_id, name) in (slice, host-offset
        row-major) order so greedy planning fills the ICI mesh contiguously."""

        def key(name: str):
            try:
                node = self.clientset.get_node(name)
            except Exception:
                return ("~", 1 << 30, name)
            labels = node.metadata.labels or {}
            slice_id = labels.get(consts.LABEL_TPU_SLICE, "")
            offset = labels.get(consts.LABEL_TPU_HOST_OFFSET, "")
            try:
                from ..core.topology import parse_coord, parse_topology, Topology

                topo_spec = labels.get(consts.LABEL_TPU_TOPOLOGY, "")
                idx = (
                    Topology(parse_topology(topo_spec)).index(parse_coord(offset))
                    if topo_spec and offset
                    else 0
                )
            except Exception:
                idx = 0
            return (slice_id, idx, name)

        keyed = sorted(((key(n), n) for n in names))
        return [(k[0], n) for k, n in keyed]

    # -- filter-time planning ------------------------------------------------

    def filter(
        self, sched: TPUUnitScheduler, pod: Pod, node_names: list[str]
    ) -> tuple[list[str], dict[str, str]]:
        """Plan-once, steer-each-member filter for gang pods — with one
        defrag-and-retry when the plan is infeasible and the planner runs
        in auto mode (fragmentation blocking a gang is exactly the signal
        the defrag subsystem exists for).  With the batch window on
        (--gang-batch-window), a gang with no plan yet first rides the
        batch-admission gate so a deep pending queue plans in one sweep."""
        if self.batch_window_s > 0:
            req0 = request_from_pod(pod)
            if self.is_gang_pod(req0) and sched.admits(req0) is None:
                self._batch_gate(sched, pod, req0, node_names)
        ok, failed = self._filter_once(sched, pod, node_names)
        defrag = self.defrag
        if (
            not ok
            and defrag is not None
            and failed
            # two retryable rejections: the plan is infeasible
            # (fragmentation blocks the gang — exactly what a round
            # fixes), or every candidate is cordoned (a round is IN
            # FLIGHT for a sibling member — try_unblock then parks on
            # the planner lock until it finishes and re-checks)
            and any(
                "cannot fit" in m or "cordoned" in m
                for m in failed.values()
            )
        ):
            req = request_from_pod(pod)
            # try_unblock is a no-op outside auto mode and rate-limited
            # inside it; the gang lock is NOT held here, so the planner
            # may freely take engine/node locks for the round
            if defrag.try_unblock(sched, req):
                GANG_EVENTS.inc("defrag_retry")
                # a sweep's cached infeasible verdict predates the round;
                # the refilter must replan, not re-reject from the marker
                self._batch_failed.pop(self.gang_key(pod, req), None)
                ok, failed = self._filter_once(sched, pod, node_names)
        return ok, failed

    def _batch_marker_ttl(self) -> float:
        return min(self.timeout, max(1.0, self.batch_window_s * 8))

    def _batch_gate(self, sched, pod: Pod, req: TPURequest, node_names) -> None:
        """Batch-admission gate: the first member of an unplanned gang
        parks up to ``batch_window_s`` collecting other pending gangs'
        first members, then ONE sweep (``plan_batch``) plans the whole
        queue; later members (and gangs arriving mid-sweep) ride the same
        sweep instead of re-scanning the cluster per gang.  Purely an
        optimization gate: whatever happens here, ``_filter_once`` still
        claims from an installed plan or plans solo, so correctness never
        depends on the gate's timing."""
        gkey = self.gang_key(pod, req)
        with self._lock:
            if self._plans.get(gkey) is not None:
                # a plan exists (possibly mid-commit): _filter_once claims
                # from it; joining the sweep would replan over it
                return
        cond = self._batch_cond
        deadline = time.monotonic() + max(self.batch_window_s * 8, 0.5)
        with cond:
            if (
                time.monotonic() - self._batch_failed.get(gkey, -1e9)
                < self._batch_marker_ttl()
            ):
                return  # fresh sweep verdict: _filter_plan rejects from it
            if gkey not in self._batch_pending:
                self._batch_pending[gkey] = (req, list(node_names))
                cond.notify_all()
            while True:
                if gkey not in self._batch_pending:
                    return  # swept: plan or failure marker is installed
                if not self._batch_sweeping:
                    break  # nobody sweeping → this thread takes the role
                if time.monotonic() >= deadline:
                    # don't wedge the verb on a stuck sweep; solo planning
                    # in _filter_once takes over
                    self._batch_pending.pop(gkey, None)
                    return
                cond.wait(max(0.01, deadline - time.monotonic()))
            self._batch_sweeping = True
            try:
                window_end = time.monotonic() + self.batch_window_s
                while (
                    len(self._batch_pending) < self.batch_min
                    and time.monotonic() < window_end
                ):
                    cond.wait(max(0.005, window_end - time.monotonic()))
                pending = [
                    (k, r, names)
                    for k, (r, names) in self._batch_pending.items()
                ]
                self._batch_pending.clear()
                # plan OUTSIDE the gate condition (plan_batch takes the
                # gang lock and node locks); joiners park on the condition
                # until the sweep posts results
                cond.release()
                try:
                    results = self.plan_batch(sched, pending)
                finally:
                    cond.acquire()
                stamp = time.monotonic()
                for k, planned in results.items():
                    if planned is None:
                        self._batch_failed[k] = stamp
            finally:
                self._batch_sweeping = False
                cond.notify_all()

    def _filter_once(
        self, sched: TPUUnitScheduler, pod: Pod, node_names: list[str]
    ) -> tuple[list[str], dict[str, str]]:
        req = request_from_pod(pod)
        reason = sched.admits(req)
        if reason is not None:  # mode policy (tpuwhole) covers gangs too
            return [], {n: reason for n in node_names}
        failed0: dict[str, str] = {}
        if getattr(sched, "cordoned", None):
            # defrag round in flight: its nodes are off-limits to new
            # plans (the gang path bypasses sched.assume's own check)
            cordoned = sched._cordoned_set()
            if cordoned:
                failed0 = {
                    n: "cordoned for defragmentation"
                    for n in node_names if n in cordoned
                }
                node_names = [n for n in node_names if n not in cordoned]
                if not node_names:
                    return [], failed0
        ok, failed = self._filter_plan(sched, pod, req, node_names)
        if failed0:  # cordoned nodes keep their verdict in the response
            failed = {**failed0, **failed}
        return ok, failed

    def _filter_plan(
        self,
        sched: TPUUnitScheduler,
        pod: Pod,
        req,
        node_names: list[str],
    ) -> tuple[list[str], dict[str, str]]:
        gkey = self.gang_key(pod, req)
        with self._lock:
            plan = self._plans.get(gkey)
            if plan is not None and not plan.committing:
                # expiry keyed off last claim ACTIVITY (ADVICE r1: expiring a
                # plan mid-arrival by age forgets members' existing claims and
                # turns a slow gang into a guaranteed commit failure)
                last_activity = max(plan.created, plan.last_claim)
                if time.monotonic() - last_activity > self.timeout:
                    self._plans.pop(gkey, None)
                    plan = None
            if plan is None and self.batch_window_s > 0:
                # a batch sweep already judged this gang infeasible against
                # current capacity: answer from the marker instead of
                # re-scanning per member (the TTL and any defrag unblock
                # round clear it)
                stamp = self._batch_failed.get(gkey)
                if stamp is not None:
                    if (
                        time.monotonic() - stamp < self._batch_marker_ttl()
                    ):
                        GANG_EVENTS.inc("batch_reject_cached")
                        return [], {
                            n: (
                                f"gang {gkey}: {req.gang_size} members "
                                "cannot fit"
                            )
                            for n in node_names
                        }
                    self._batch_failed.pop(gkey, None)
            if plan is None:
                plan = self._plan(sched, req, node_names)
                if plan is None:
                    GANG_EVENTS.inc("plan_infeasible")
                    AUDIT.record(
                        pod.key, "gang", gang=gkey, event="plan_infeasible",
                        detail=(
                            f"{req.gang_size} members cannot fit on "
                            f"{len(node_names)} candidate node(s)"
                        ),
                    )
                    return [], {
                        n: f"gang {gkey}: {req.gang_size} members cannot fit"
                        for n in node_names
                    }
                plan.created = time.monotonic()
                plan.member_units = req.units
                plan.member_containers = req.container_names
                plan.slot_units = [req.units] * len(plan.slots)
                plan.slot_containers = [req.container_names] * len(plan.slots)
                self._plans[gkey] = plan
                GANG_EVENTS.inc("planned")
            existing_idx = plan.claims.get(pod.key)
            if existing_idx is None and len(plan.claims) >= len(plan.slots):
                return [], {
                    n: f"gang {gkey}: all {req.gang_size} slots claimed"
                    for n in node_names
                }
            shape_changed = existing_idx is not None and (
                req.units != plan.slot_units[existing_idx]
                or req.container_names != plan.slot_containers[existing_idx]
            )
            if (
                existing_idx is None
                and (req.units, req.container_names)
                != (plan.member_units, plan.member_containers)
            ) or shape_changed:
                # heterogeneous member (VERDICT r2 #5b): its slot was planned
                # for a different shape — replan the whole gang with every
                # SEEN shape pinned before handing out a slot.  Covers both
                # a new member with a non-seed shape and a RE-FILTERED
                # member whose pod was recreated with a new shape (its
                # cached option would otherwise bind the old shape).
                if not self._replan_hetero(
                    sched, plan, req, node_names, gkey,
                    pinned_idx=existing_idx,
                ):
                    GANG_EVENTS.inc("plan_hetero_infeasible")
                    AUDIT.record(
                        pod.key, "gang", gang=gkey,
                        event="hetero_replan_infeasible",
                        detail=f"shape {req.units} does not fit alongside "
                               "the claimed members",
                    )
                    return [], {
                        n: (
                            f"gang {gkey}: heterogeneous member "
                            f"{pod.key} (shape {req.units}) does not fit "
                            "alongside the claimed members"
                        )
                        for n in node_names
                    }
                GANG_EVENTS.inc("replanned_hetero")
            node = plan.claim(pod.key)
            if node is None:
                return [], {
                    n: f"gang {gkey}: all {req.gang_size} slots claimed"
                    for n in node_names
                }
            AUDIT.record(
                pod.key, "gang", gang=gkey, event="slot_claimed",
                detail=(
                    f"slot {plan.claims[pod.key]}/{len(plan.slots)} "
                    f"→ {node}"
                ),
            )
            if existing_idx is None:
                # record the actual claimed shape exactly once; an existing
                # claim's shape is only ever rewritten via the replan above
                idx = plan.claims[pod.key]
                plan.slot_units[idx] = req.units
                plan.slot_containers[idx] = req.container_names
            if node not in node_names:
                return [], {
                    n: f"gang {gkey}: planned node {node} not in candidates"
                    for n in node_names
                }
            return [node], {}

    def _plan(
        self, sched: TPUUnitScheduler, req: TPURequest, node_names: list[str]
    ) -> Optional[_Plan]:
        with TRACER.span(
            "gang.plan", size=req.gang_size, candidates=len(node_names),
        ) as sp:
            plan = self._plan_inner(sched, req, node_names)
            sp.set_attr("feasible", plan is not None)
            if plan is not None:
                sp.set_attr("hosts", len(set(plan.slots)))
            return plan

    def _plan_inner(
        self, sched: TPUUnitScheduler, req: TPURequest, node_names: list[str]
    ) -> Optional[_Plan]:
        """Place all members onto cloned chip state.

        Slice-affine: each ICI slice is tried ALONE first (in mesh order), so
        a gang that fits inside one slice never straddles the DCN boundary;
        spanning slices is the last resort (collectives across slices fall
        off ICI onto DCN — the exact cost the placement model exists to
        avoid, SURVEY §5 'Distributed communication backend')."""
        ordered = self._node_mesh_order(node_names)
        # ONE registry fetch + ONE pass of per-node locks for the whole plan
        # (the old prefilter re-took sched.lock then na.lock per node per
        # candidate group — 2×nodes×groups acquisitions of the hottest lock)
        allocators = sched.get_allocators([n for _, n in ordered])
        free_core = self._free_core_view(sched, ordered, allocators)
        idx = getattr(sched, "index", None)
        if idx is not None:
            # index prune: drop nodes that cannot host even ONE member
            # (necessary conditions on committed state; reservations only
            # shrink capacity, so no viable candidate is ever dropped) —
            # at fleet scale this is what keeps the clone count
            # proportional to plausible hosts, not to the cluster
            ordered = self._prune_ordered(idx, req, ordered)
        # policy-plane filter verb (promoted policies only: every member
        # of a gang must see the SAME candidate set, so the per-pod
        # canary split never applies here).  Faults keep the node; a
        # policy that empties the set makes the gang infeasible — the
        # same verdict an operator's "never place here" rule implies.
        plane = getattr(sched, "policies", None)
        if plane is not None and ordered and "filter" in plane.active:
            pol = plane.active["filter"]
            inputs = sched.filter_policy_inputs(
                req, self._req_wclass(req), [n for _, n in ordered]
            )
            ordered = [
                (s, n) for s, n in ordered
                if n not in inputs or plane.eval_filter(pol, inputs[n])
            ]
        candidates = self._candidate_groups(ordered)
        # memoized trade results, shared across candidate groups — keyed by
        # full node state, so clones from different groups can only hit when
        # the states genuinely match
        memo: dict = {}
        clones, get_clone = self._clone_ctx(sched, allocators)
        self._reserve_other_plans(sched, clones, get_clone, memo=memo)
        planned = self._plan_groups(
            sched, req, candidates, free_core, get_clone, memo
        )
        if planned is not None:
            slots, options = planned
            return _Plan(
                slots=slots,
                options=options,
                node_slices={n: s for s, n in ordered},
            )
        return None

    @staticmethod
    def _req_wclass(req: TPURequest) -> str:
        """Workload class for the policy filter's behavior inputs — the
        request wire type carries no annotations, so gangs profile under
        the default class unless the request grew one."""
        return getattr(req, "wclass", None) or consts.DEFAULT_WORKLOAD_CLASS

    @staticmethod
    def _candidate_groups(ordered: list[tuple[str, str]]) -> list[list[str]]:
        """Slice-affine candidate groups: each ICI slice alone (mesh
        order), then the spanning fallback."""
        slice_groups: dict[str, list[str]] = {}
        for slice_id, name in ordered:
            slice_groups.setdefault(slice_id, []).append(name)
        candidates: list[list[str]] = [g for g in slice_groups.values()]
        if len(candidates) > 1:
            candidates.append([n for _, n in ordered])  # spanning fallback
        return candidates

    @staticmethod
    def _free_core_view(sched, ordered, allocators) -> dict:
        """name → free core units for the group prefilter: read from the
        capacity index when it is on (one fold, zero node locks — the
        fleet-scale path), else one pass of per-node locks.  Values are
        identical either way: the index is exact as of the last committed
        mutation."""
        idx = getattr(sched, "index", None)
        if idx is not None:
            idx.fold()
            return idx.free_core_map([n for _, n in ordered])
        free_core: dict[str, int] = {}
        for name, na in allocators.items():
            if na is not None:
                with na.lock:
                    free_core[name] = na.chips.avail_core()
        return free_core

    @staticmethod
    def _prune_ordered(idx, req: TPURequest, ordered):
        """Keep only nodes satisfying the per-MEMBER necessary capacity
        conditions (plus nodes the index doesn't know, which the planner
        resolves the slow way).  A pruned node could never host a member,
        so the kernel/trade cursor would skip it anyway — placements are
        bit-identical, only the clones are fewer."""
        core, hbm, whole = request_demand(req)
        entries = idx.entries
        out = []
        for s, n in ordered:
            e = entries.get(n)
            if e is None or (
                e.free_core >= core
                and e.free_hbm >= hbm
                and e.free_chips >= whole
            ):
                out.append((s, n))
        return out

    def _plan_groups(
        self, sched, req: TPURequest, candidates, free_core, get_clone, memo
    ):
        """Try each candidate group in order on SHARED clones; a failed
        group attempt rolls its partial consumption back (the per-group
        fresh-clone behavior this replaces discarded the whole context
        instead).  Returns (slots, options) or None."""
        demand = req.total_chips_equiv * req.gang_size * 100  # core units
        for group in candidates:
            # cheap prefilter: skip groups whose total free core can't hold
            # the gang (saves the clone+replay work on hopeless slices)
            if sum(free_core.get(n, 0) for n in group) < demand:
                continue
            planned = self._plan_on_clones(sched, req, group, get_clone, memo)
            if planned is not None:
                return planned
        return None

    def _trade_cached(self, cs, req: TPURequest, rater, memo: Optional[dict]):
        """``cs.trade`` with per-plan memoization: results are keyed by
        (request shape, full node state incl. relative geometry), so the
        placement found for one gang member replays onto every congruent
        node state — identical hosts of an SPMD slice hit after one DFS per
        distinct fill level instead of re-searching per member.  Only valid
        for translation-invariant raters (the template stores slot indices,
        not absolute coords); others go straight to trade."""
        if memo is None or not getattr(rater, "translation_invariant", False):
            return cs.trade(req, rater)
        key = (req.units, req.container_names, cs.plan_key())
        hit = memo.get(key, _MISS)
        if hit is not _MISS:
            PLAN_CACHE.inc("hit")
            return (
                None if hit is None else cs.option_from_template(hit, req.hash())
            )
        opt = cs.trade(req, rater)
        memo[key] = None if opt is None else cs.option_template(opt)
        PLAN_CACHE.inc("miss")
        return opt

    def _reserve_other_plans(
        self, sched, clones: dict, get_clone, skip_key: Optional[str] = None,
        memo: Optional[dict] = None,
    ) -> None:
        """Replay other ACTIVE plans' placements into the clones so
        concurrent gangs don't double-count the same free chips (caller holds
        self._lock).  Without this, two gangs planned back-to-back both pass
        filter against the same capacity and one fails mid-commit.

        A plan being COMMITTED is skipped wholesale: its allocations are
        landing in the real allocator state the clones start from (commit is
        all-or-nothing, so there is never a partially-bound slot list to
        replay — ADVICE r1's bound-counter skew cannot occur).  ``skip_key``
        excludes the plan being REPLANNED (its old placements must not
        shadow the capacity the replan is re-deriving)."""
        now = time.monotonic()
        for other_key, other in self._plans.items():
            if other_key == skip_key:
                continue
            if other.committing or not other.member_units:
                continue
            if now - max(other.created, other.last_claim) > self.timeout:
                continue
            for idx, node in enumerate(other.slots):
                cs = get_clone(node)
                if cs is None:
                    continue
                # apply the plan's own stored option when it still fits —
                # O(chips-touched) instead of re-running the trade DFS per
                # reserved member (a 1024-member prior plan made the NEXT
                # gang's planning ~2x slower via re-search)
                if idx < len(other.options):
                    opt = other.options[idx]
                    if cs.can_transact(opt):
                        cs.transact(opt)
                        continue
                member_req = TPURequest(
                    pod_uid=f"resv-{other_key}-{idx}",
                    pod_key=f"resv/{other_key}/{idx}",
                    units=(
                        other.slot_units[idx]
                        if idx < len(other.slot_units)
                        else other.member_units
                    ),
                    container_names=(
                        other.slot_containers[idx]
                        if idx < len(other.slot_containers)
                        else other.member_containers
                    ),
                )
                opt = self._trade_cached(cs, member_req, sched.rater, memo)
                if opt is not None:
                    cs.transact(opt)

    @staticmethod
    def _clone_ctx(sched: TPUUnitScheduler, allocators: Optional[dict] = None):
        """(clones, get_clone): lazily clone per-node chip state for
        plan simulation — plans never touch real allocators until bind.

        ``allocators`` is the batch prefetched by the caller (one sched.lock
        acquisition for the whole plan); nodes outside it — e.g. another
        plan's slots during reservation replay — fall back to a one-off
        batch fetch.  Cloning itself takes only the node's own lock, and is
        O(words) with the packed ChipSet representation."""
        clones: dict = {}

        def get_clone(name):
            cs = clones.get(name)
            if cs is None:
                if allocators is not None and name in allocators:
                    na = allocators[name]
                else:
                    na = sched.get_allocators([name]).get(name)
                if na is None:
                    return None
                with na.lock:
                    cs = na.chips.clone()
                clones[name] = cs
            return cs

        return clones, get_clone

    def _replan_hetero(
        self,
        sched: TPUUnitScheduler,
        plan: _Plan,
        req: TPURequest,
        node_names: list[str],
        gkey: str,
        pinned_idx: Optional[int] = None,
    ) -> bool:
        """Re-place the WHOLE gang when a member's shape differs from the
        plan's (caller holds self._lock).  Claimed members stay PINNED to
        their already-returned slots (their filters answered; bind will
        arrive with those nodes) with their ACTUAL shapes; a new member
        claims the next index with ITS shape; members not yet seen keep the
        seed shape.  ``pinned_idx`` set = the arriving member ALREADY holds
        that claim (pod recreated with a new shape): its slot stays pinned
        but its shape and option are re-derived, so the commit cache can
        never apply the old shape's option.  Mutates ``plan`` in place on
        success; on failure the plan is untouched and the caller rejects at
        filter with a named error.  Full scan per member (no forward-only
        cursor — a node full for one shape may fit another); heterogeneous
        gangs are expected to be small."""
        allocators = sched.get_allocators(
            list(dict.fromkeys(list(node_names) + list(plan.slots)))
        )
        clones, get_clone = self._clone_ctx(sched, allocators)
        memo: dict = {}
        self._reserve_other_plans(
            sched, clones, get_clone, skip_key=gkey, memo=memo
        )
        n_claimed = len(plan.claims)
        new_slots = list(plan.slots)
        new_options = list(plan.options)
        new_units = list(plan.slot_units)
        new_containers = list(plan.slot_containers)
        if pinned_idx is not None:
            new_units[pinned_idx] = req.units
            new_containers[pinned_idx] = req.container_names

        # 1) pin claimed members to their slots with their actual shapes
        for key, idx in sorted(plan.claims.items(), key=lambda kv: kv[1]):
            cs = get_clone(plan.slots[idx])
            if cs is None:
                return False
            member_req = TPURequest(
                pod_uid=f"pin-{idx}", pod_key=f"pin/{idx}",
                units=new_units[idx],
                container_names=new_containers[idx],
            )
            opt = self._trade_cached(cs, member_req, sched.rater, memo)
            if opt is None:
                return False
            cs.transact(opt)
            new_options[idx] = opt

        # 2) the arriving member (next claim index, unless it already holds
        #    a pinned claim), then the unseen tail at the seed shape
        ordered = [n for _, n in self._node_mesh_order(node_names)]
        shapes = []
        if pinned_idx is None:
            shapes.append((req.units, req.container_names))
        shapes += [(plan.member_units, plan.member_containers)] * (
            len(plan.slots) - n_claimed - len(shapes)
        )
        for offset, (units, containers) in enumerate(shapes):
            idx = n_claimed + offset
            member_req = TPURequest(
                pod_uid=f"replan-{idx}", pod_key=f"replan/{idx}",
                units=units, container_names=containers,
            )
            placed = False
            for name in ordered:
                cs = get_clone(name)
                if cs is None:
                    continue
                opt = self._trade_cached(cs, member_req, sched.rater, memo)
                if opt is not None:
                    cs.transact(opt)
                    new_slots[idx] = name
                    new_options[idx] = opt
                    new_units[idx] = units
                    new_containers[idx] = containers
                    placed = True
                    break
            if not placed:
                return False

        plan.slots = new_slots
        plan.options = new_options
        plan.slot_units = new_units
        plan.slot_containers = new_containers
        return True

    @staticmethod
    def _whole_gang_shape(req: TPURequest, rater) -> Optional[int]:
        """chip_count when this request is the homogeneous single
        whole-chip-unit SPMD shape the plan_gang kernel handles (and the
        rater guarantees compact-first selection matches its argmax), else
        None."""
        if not getattr(rater, "whole_chip_compact_first", False):
            return None
        tpu = [u for u in req.units if u.needs_tpu]
        if len(tpu) != 1 or not tpu[0].wants_whole_chips:
            return None
        return tpu[0].chip_count

    def _plan_whole_fast(
        self,
        sched: TPUUnitScheduler,
        req: TPURequest,
        ordered: list[str],
        get_clone,
        count: int,
    ):
        """Whole-gang placement through the plan_gang kernel (native C++
        when built, bit-identical Python fallback otherwise): per-node free
        bitsets go in, every member's box comes out of ONE kernel call per
        topology run — no per-member DFS, no per-candidate Python rating.

        Returns (slots, options), None (gang cannot fit — same verdict the
        per-member search would reach, it walks the same candidate streams
        with the same forward-only cursor), or _FAST_INELIGIBLE (state the
        kernel's selection shortcut doesn't cover: fall back to trade)."""
        from ..core.native import get_placement

        nodes: list[tuple[str, object]] = []
        for name in ordered:
            cs = get_clone(name)
            if cs is None:
                continue
            if len(set(cs._core_total)) > 1 or len(set(cs._hbm_total)) > 1:
                # heterogeneous chip totals: candidate boxes no longer
                # consume identical capacity, so non-locality rate terms
                # stop being candidate-invariant — exact trade required
                return _FAST_INELIGIBLE
            nodes.append((name, cs))
        if not nodes:
            return None
        native = get_placement()
        use_native = native is not None and hasattr(native, "plan_gang")
        # nodes of different slices carry different Topologies (the
        # spanning-fallback group mixes slices); run the kernel once per
        # consecutive same-topology run, preserving the forward-only cursor
        placements: list[tuple[int, tuple[int, ...], bool]] = []
        remaining = req.gang_size
        pos = 0
        while pos < len(nodes) and remaining > 0:
            topo = nodes[pos][1].topo
            end = pos
            while end < len(nodes) and nodes[end][1].topo == topo:
                end += 1
            free_lists = [
                tuple(cs._mesh_idx[i] for i in iter_bits(cs._free_bits))
                for _, cs in nodes[pos:end]
            ]
            if use_native:
                placed = native.plan_gang(
                    topo.dims, topo.wrap, free_lists, count, remaining, 64
                )
            else:
                placed = plan_gang_fallback(
                    topo, free_lists, count, remaining, 64
                )
            # one count per kernel INVOCATION (the metric's documented
            # meaning) — a spanning group runs it once per topology chunk,
            # and an infeasible gang still shows the kernel was tried
            PLAN_CACHE.inc("native_kernel" if use_native else "python_kernel")
            placements.extend(
                (pos + node_i, idxs, contig) for node_i, idxs, contig in placed
            )
            remaining -= len(placed)
            pos = end
        if remaining > 0:
            return None
        return self._materialize_members(sched, req, nodes, placements)

    @staticmethod
    def _materialize_members(sched, req: TPURequest, nodes, placements):
        """Kernel placements → (slots, options), applying each member's
        box to its node clone and rating it — shared by the single-gang
        fast path and the batch sweep so the two can never drift."""
        slots: list[str] = []
        options: list = []
        for member, (node_pos, idxs, contiguous) in enumerate(placements):
            name, cs = nodes[node_pos]
            coords = tuple(cs.topo.coord_of(i) for i in idxs)
            allocs = tuple(
                ContainerAlloc(
                    container=cname, coords=coords, whole=True,
                    contiguous=bool(contiguous),
                )
                if unit.needs_tpu
                else ContainerAlloc(container=cname, coords=(), whole=False)
                for cname, unit in zip(req.container_names, req.units)
            )
            member_req = TPURequest(
                pod_uid=f"plan-{member}",
                pod_key=f"plan/{member}",
                units=req.units,
                container_names=req.container_names,
            )
            opt = Option(member_req.hash(), allocs)
            # direct apply, not transact: the kernel owns the free masks it
            # just placed against, so re-validating 1024 members is pure
            # overhead (_apply still raises if a chip is somehow taken)
            for a in allocs:
                if a.needs_tpu:
                    cs._apply(a)
            # rate AFTER apply, like trade does — cheap now (bitset counts)
            opt.score = sched.rater.rate(cs, opt)
            slots.append(name)
            options.append(opt)
        return slots, options

    def _plan_on_clones(
        self,
        sched: TPUUnitScheduler,
        req: TPURequest,
        ordered: list[str],
        get_clone,
        memo: Optional[dict] = None,
    ):
        """Greedy member placement over one candidate node group, on the
        caller's (shared) clone context.

        Members are homogeneous (same shape), so a node that cannot fit
        member k cannot fit member k+1 either — the scan cursor only moves
        forward, making planning O(members + nodes) instead of O(m·n)
        (a v5p-2048 gang plans in one pass over 256 hosts).

        Whole-chip SPMD gangs take the plan_gang kernel fast path; anything
        else (fractional shapes, multi-container pods, custom raters) runs
        the per-member trade DFS with memoized results.  A failed attempt
        leaves the clones exactly as it found them (the fast path is
        all-or-nothing by construction; the trade path rolls back), so one
        clone context serves every group and every gang of a batch sweep."""
        count = self._whole_gang_shape(req, sched.rater)
        if count is not None:
            fast = self._plan_whole_fast(sched, req, ordered, get_clone, count)
            if fast is not _FAST_INELIGIBLE:
                return fast
        slots: list[str] = []
        options: list = []
        undo: list[tuple] = []  # (clone, option) applied so far
        cursor = 0
        for member in range(req.gang_size):
            member_req = TPURequest(
                pod_uid=f"plan-{member}",
                pod_key=f"plan/{member}",
                units=req.units,
                container_names=req.container_names,
            )
            placed = False
            while cursor < len(ordered):
                name = ordered[cursor]
                cs = get_clone(name)
                if cs is None:
                    cursor += 1
                    continue
                opt = self._trade_cached(cs, member_req, sched.rater, memo)
                if opt is None:
                    cursor += 1  # full for this shape → full for all members
                    continue
                cs.transact(opt)
                undo.append((cs, opt))
                slots.append(name)
                options.append(opt)
                placed = True
                break
            if not placed:
                for cs, opt in reversed(undo):
                    cs.cancel(opt)
                return None
        return slots, options

    # -- batch admission sweep (fleet-scale pending-queue planning) ----------

    def _plan_whole_batch(self, sched, specs, ordered, get_clone):
        """Plan a SEGMENT of consecutive whole-chip-eligible gangs through
        ONE plan_gang_batch kernel call (native when built, bit-identical
        Python fallback): per-node free bitsets go in once, every placed
        gang's boxes come out, carried state between specs inside the
        kernel — no per-gang free-list rebuild, no per-gang Python↔C++
        crossing.

        ``specs`` is ``[(gkey, req, count), ...]`` in arrival order.
        Returns ``(results, clean, ineligible)``: ``results`` maps gkey →
        (slots, options) for the contiguous SUCCESS PREFIX (the kernel
        stops at the first spec that cannot fully place and consumes
        nothing for it — exactly what sequential per-gang planning would
        leave behind); ``clean`` is False when a failure cut the batch
        short; ``ineligible`` True means this group's node states aren't
        covered by the kernel shortcut (heterogeneous chip totals, or
        nodes of mixed topologies whose spill semantics need the per-gang
        path) and NOTHING was attempted."""
        from ..core.native import get_placement

        nodes: list[tuple[str, object]] = []
        for name in ordered:
            cs = get_clone(name)
            if cs is None:
                continue
            if len(set(cs._core_total)) > 1 or len(set(cs._hbm_total)) > 1:
                return {}, True, True
            nodes.append((name, cs))
        if not nodes:
            return {}, False, False
        topo0 = nodes[0][1].topo
        if any(cs.topo != topo0 for _, cs in nodes):
            # multi-topology group: a gang may have to SPILL across
            # topology runs, which is per-gang cursor state the batch
            # kernel doesn't model — the per-gang fast path handles it
            return {}, True, True
        free_lists = [
            tuple(cs._mesh_idx[i] for i in iter_bits(cs._free_bits))
            for _, cs in nodes
        ]
        kspecs = [(count, req.gang_size) for _, req, count in specs]
        native = get_placement()
        use_native = native is not None and hasattr(native, "plan_gang_batch")
        if use_native:
            out = native.plan_gang_batch(
                topo0.dims, topo0.wrap, free_lists, kspecs, 64
            )
        else:
            out = plan_gang_batch_fallback(topo0, free_lists, kspecs, 64)
        PLAN_CACHE.inc(
            "native_batch_kernel" if use_native else "python_batch_kernel"
        )
        results: dict = {}
        clean = True
        for (gkey, req, _count), placed in zip(specs, out):
            if placed and len(placed) >= req.gang_size:
                results[gkey] = self._materialize_members(
                    sched, req, nodes, placed
                )
            else:
                clean = False
                break
        return results, clean, False

    def _batch_group_pass(self, sched, group, gangs, get_clone, memo):
        """One candidate-group pass over pending gangs, order preserved:
        consecutive whole-chip-eligible gangs flow through the batch
        kernel; others (fractional shapes, custom raters) run the trade
        path on the same shared clones.  Stops at the FIRST placement
        failure (returns clean=False): everything after it must re-plan
        strictly sequentially, or later gangs would see consumption in an
        order the per-gang oracle never produces."""
        placed: dict = {}
        i = 0
        while i < len(gangs):
            gkey, req = gangs[i]
            count = self._whole_gang_shape(req, sched.rater)
            if count is not None:
                j = i
                specs = []
                while j < len(gangs):
                    k2, r2 = gangs[j]
                    c2 = self._whole_gang_shape(r2, sched.rater)
                    if c2 is None:
                        break
                    specs.append((k2, r2, c2))
                    j += 1
                results, clean, ineligible = self._plan_whole_batch(
                    sched, specs, group, get_clone
                )
                if ineligible:
                    # per-gang fast path (handles hetero totals via trade
                    # and multi-run spill) on the same clones, in order
                    for k2, r2, _c2 in specs:
                        got = self._plan_on_clones(
                            sched, r2, group, get_clone, memo
                        )
                        if got is None:
                            return placed, False
                        placed[k2] = got
                    i = j
                    continue
                placed.update(results)
                if not clean:
                    return placed, False
                i = j
            else:
                got = self._plan_on_clones(sched, req, group, get_clone, memo)
                if got is None:
                    return placed, False
                placed[gkey] = got
                i += 1
        return placed, True

    def _plan_chunk(
        self, sched, chunk, node_names, allocators, get_clone, memo
    ):
        """Plan a run of pending gangs sharing one candidate list,
        bit-identical to planning each gang alone in arrival order.

        Lockstep phase: the SLICE groups only (they are disjoint node
        sets, so as long as every attempt succeeds, group-major order and
        gang-major order produce identical placements — consumption in
        one slice cannot affect another).  The spanning group overlaps
        every slice, so the moment any gang is left unplaced by the slice
        phase (placement failure, or prefiltered off every slice) the
        sequential oracle's ordering starts to matter: that gang would
        have consumed (possibly spanning) capacity BEFORE any
        later-arrived gang placed.  To stay exact, every placed gang
        ordered AFTER the first unplaced one is rolled back off the
        clones and the tail re-plans strictly sequentially (same shared
        clones, same group iteration the single-gang planner uses)."""
        ordered = self._node_mesh_order(list(node_names))
        free_core = self._free_core_view(sched, ordered, allocators)
        idx = getattr(sched, "index", None)
        node_slices = {n: s for s, n in ordered}
        placed: dict = {}
        remaining = list(chunk)  # [(gkey, req)] in arrival order
        bail = False
        groups_by_req: dict = {}

        def groups_for(req):
            key = id(req)
            got = groups_by_req.get(key)
            if got is None:
                ords = (
                    self._prune_ordered(idx, req, ordered)
                    if idx is not None
                    else ordered
                )
                got = self._candidate_groups(ords)
                groups_by_req[key] = got
            return got

        def slice_groups_for(req):
            groups = groups_for(req)
            # drop the overlapping spanning fallback from the lockstep
            # (it exists only when there are ≥2 slice groups)
            return groups[:-1] if len(groups) > 1 else groups

        n_groups = max(
            (len(slice_groups_for(r)) for _, r in remaining), default=0
        )
        for gi in range(n_groups):
            if bail or not remaining:
                break
            # gangs whose gi-th slice group exists and passes the prefilter
            attempt = []
            for gkey, req in remaining:
                groups = slice_groups_for(req)
                if gi >= len(groups):
                    continue
                group = groups[gi]
                demand = req.total_chips_equiv * req.gang_size * 100
                if sum(free_core.get(n, 0) for n in group) < demand:
                    continue
                attempt.append((gkey, req, group))
            if not attempt:
                continue
            # group lists are identical across same-shape gangs of a
            # chunk; segment by concrete group so the kernel sees one
            seg_start = 0
            while seg_start < len(attempt) and not bail:
                seg_group = attempt[seg_start][2]
                seg = []
                k = seg_start
                while k < len(attempt) and attempt[k][2] == seg_group:
                    seg.append((attempt[k][0], attempt[k][1]))
                    k += 1
                got, clean = self._batch_group_pass(
                    sched, seg_group, seg, get_clone, memo
                )
                placed.update(got)
                if not clean:
                    bail = True
                seg_start = k
            remaining = [g for g in remaining if g[0] not in placed]
        # order repair: sequential semantics say the first unplaced gang
        # consumes (maybe spanning every slice) before any later gang
        # places — so later gangs' lockstep placements are unwound and
        # re-derived in strict order
        order = [gkey for gkey, _ in chunk]
        first_unplaced = next(
            (i for i, k in enumerate(order) if k not in placed), None
        )
        if first_unplaced is not None:
            for k in order[first_unplaced + 1:]:
                got = placed.pop(k, None)
                if got is not None:
                    slots, options = got
                    for slot, opt in zip(slots, options):
                        cs = get_clone(slot)
                        if cs is not None:
                            cs.cancel(opt)
            for gkey, req in chunk[first_unplaced:]:
                got = self._plan_groups(
                    sched, req, groups_for(req), free_core, get_clone, memo
                )
                placed[gkey] = got  # may be None → infeasible
        return placed, node_slices

    def plan_batch(
        self, sched: TPUUnitScheduler,
        pending: list[tuple[str, TPURequest, list]],
    ) -> dict:
        """Batch admission sweep: plan every pending gang in ONE ranked
        pass — one clone context, one reservation replay, one (or few)
        multi-spec kernel invocations per congruent host class — instead
        of a full per-gang rescan each.  ``pending`` is
        ``[(gkey, request, candidate_node_names), ...]`` in arrival
        order; returns gkey → _Plan (installed in ``self._plans``, ready
        for members' filters to claim) or None (infeasible).  Results are
        bit-identical to planning each gang alone in the same order
        (tests/test_cluster_index.py asserts it)."""
        with self._lock:
            results: dict = {}
            todo: list[tuple] = []
            for gkey, req, node_names in pending:
                plan = self._plans.get(gkey)
                if plan is not None:
                    # existing plan — INCLUDING one mid-commit: members
                    # must claim from it (exactly _filter_plan's rule);
                    # replanning over a committing plan would split the
                    # gang between two placements
                    results[gkey] = plan
                    continue
                todo.append((gkey, req, tuple(node_names)))
            if not todo:
                return results
            union: list[str] = list(
                dict.fromkeys(n for _, _, names in todo for n in names)
            )
            allocators = sched.get_allocators(union)
            clones, get_clone = self._clone_ctx(sched, allocators)
            memo: dict = {}
            self._reserve_other_plans(sched, clones, get_clone, memo=memo)
            GANG_EVENTS.inc("batch_sweep")
            i = 0
            while i < len(todo):
                cand = todo[i][2]
                j = i
                while j < len(todo) and todo[j][2] == cand:
                    j += 1
                chunk = [(k, r) for k, r, _ in todo[i:j]]
                placed, node_slices = self._plan_chunk(
                    sched, chunk, cand, allocators, get_clone, memo
                )
                for gkey, req in chunk:
                    got = placed.get(gkey)
                    if got is None:
                        results[gkey] = None
                        GANG_EVENTS.inc("batch_infeasible")
                        continue
                    slots, options = got
                    plan = _Plan(
                        slots=slots,
                        options=options,
                        node_slices=dict(node_slices),
                    )
                    plan.created = time.monotonic()
                    plan.member_units = req.units
                    plan.member_containers = req.container_names
                    plan.slot_units = [req.units] * len(slots)
                    plan.slot_containers = [req.container_names] * len(slots)
                    self._plans[gkey] = plan
                    results[gkey] = plan
                    GANG_EVENTS.inc("batch_planned")
                i = j
            return results

    # -- bind-time barrier + single-committer commit -------------------------

    def bind(self, sched: ResourceScheduler, node: str, pod: Pod) -> None:
        req = request_from_pod(pod)
        if not self.is_gang_pod(req):
            sched.bind(node, pod)
            return
        reason = sched.admits(req)
        if reason is not None:  # a gang bind can arrive without filter
            raise RuntimeError(f"bind: {reason}")
        gkey = self.gang_key(pod, req)
        with self._lock:
            g = self._gangs.get(gkey)
            if g is None:
                g = _Gang(
                    name=gkey,
                    size=req.gang_size,
                    created=time.monotonic(),
                    cond=threading.Condition(),
                )
                self._gangs[gkey] = g
                GANG_EVENTS.inc("created")

        with g.cond:
            if g.failed:
                self._maybe_gc(gkey, g)
                raise RuntimeError(f"gang {gkey}: {g.failed}")
            if g.committed:
                raise RuntimeError(f"gang {gkey}: already committed")
            g.members[pod.key] = (node, pod)
            if len(g.members) >= g.size:
                # last arriver commits the WHOLE gang while the other
                # members' threads stay parked on the condition (they hold
                # no locks, so the commit runs without N-way GIL thrash)
                GANG_EVENTS.inc("barrier_tripped")
                g.t_barrier = time.monotonic()
                try:
                    # the commit span lives on the LAST arriver's trace
                    # (nested under its extender.bind span); every other
                    # member's trace records the outcome via its own
                    # audit entry from gang_note_bound
                    with TRACER.span(
                        "gang.commit", gang=gkey, members=g.size,
                    ):
                        self._commit_gang(sched, gkey, g)
                    g.committed = True
                    GANG_EVENTS.inc("bound")
                except Exception as e:
                    g.failed = str(e) or repr(e)  # failure channel is truthiness
                    GANG_EVENTS.inc("commit_failed")
                g.t_commit_end = time.monotonic()
                g.cond.notify_all()
            else:
                deadline = g.created + self.timeout
                with TRACER.span(
                    "gang.barrier.wait", pod=pod.key, gang=gkey,
                    arrived=len(g.members), size=g.size,
                ) as wsp:
                    while not g.committed and not g.failed:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            g.failed = (
                                f"timed out with {len(g.members)}/{g.size} "
                                "members"
                            )
                            GANG_EVENTS.inc("timeout")
                            g.cond.notify_all()
                            break
                        g.cond.wait(timeout=remaining)
                    if g.failed:
                        wsp.set_attr("failed", g.failed)
            if g.failed:
                g.members.pop(pod.key, None)
                self._maybe_gc(gkey, g)
                raise RuntimeError(f"gang {gkey}: {g.failed}")
            g.done += 1
            self._maybe_gc(gkey, g)

    def _commit_gang(self, sched: TPUUnitScheduler, gkey: str, g: _Gang) -> None:
        """All-or-nothing commit of every member (caller holds g.cond).

        Any failure leaves zero chips allocated and zero pods annotated; the
        only irreversible artifact is a Binding already accepted by the API
        server in phase 3, and such pods are stripped of their ledger entry
        (bound-but-unprovisioned, flagged via a Warning event)."""
        members = sorted(g.members.items())  # [(pod_key, (node, pod))]
        # phase telemetry onto the committer's open gang.commit span
        # (event appends are GIL-atomic, so pool threads could add too)
        csp = TRACER.current() or NOOP_SPAN
        with self._lock:
            plan = self._plans.get(gkey)
            plan_slots: dict[str, object] = {}
            plan_node_slices: dict[str, str] = (
                dict(plan.node_slices) if plan is not None else {}
            )
            if plan is not None:
                plan.committing = True
                # planned per-slot options: commit can APPLY them (validating
                # transact) instead of re-running the trade DFS per member.
                # Each slot carries its OWN planned shape (heterogeneous
                # gangs) — the cache check compares against that, not the
                # seed shape.
                for key, idx in plan.claims.items():
                    if idx < len(plan.options):
                        plan_slots[key] = (
                            plan.slots[idx],
                            plan.options[idx],
                            plan.slot_units[idx]
                            if idx < len(plan.slot_units)
                            else plan.member_units,
                            plan.slot_containers[idx]
                            if idx < len(plan.slot_containers)
                            else plan.member_containers,
                        )

        try:
            # phase 1: in-memory allocation, atomic under the scheduler lock
            # (this IS the feasibility re-check — no check-then-act window)
            allocated: list[tuple[Pod, str, object]] = []
            try:
                with sched.lock:
                    for key, (node, pod) in members:
                        opt = None
                        cached = plan_slots.get(key)
                        # full request identity, not just units: a pod
                        # recreated with identical units but renamed or
                        # reordered containers must NOT reuse the planned
                        # Option (its ContainerAllocs carry container names)
                        creq = request_from_pod(pod)
                        if (
                            cached is not None
                            and cached[0] == node
                            and creq.units == cached[2]
                            and creq.container_names == cached[3]
                        ):
                            try:
                                sched.gang_apply_option(node, pod, cached[1])
                                opt = cached[1]
                            except ValueError:
                                opt = None  # taken since planning → re-search
                        if opt is None:
                            opt = sched.gang_allocate(node, pod)
                        allocated.append((pod, node, opt))
                    if sched.JOURNAL.enabled:
                        # the all-or-nothing seal, INSIDE the same engine-
                        # lock hold as the members' bind records: no
                        # concurrent forget (it needs sched.lock) can
                        # interleave between a member bind and the admit,
                        # so replay's membership check can never trip on a
                        # legal mid-commit deletion.  Phase-2/3 failures
                        # journal balancing forgets + a gang_rollback.
                        sched.JOURNAL.record(
                            "gang_admit",
                            gang=gkey,
                            size=g.size,
                            members=[k for k, _ in members],
                            nodes=sorted(
                                {node for _, (node, _p) in members}
                            ),
                        )
            except Exception as e:
                with sched.lock:
                    for pod, node, opt in allocated:
                        sched.gang_unallocate(node, pod, opt)
                GANG_EVENTS.inc("stale_plan")
                raise RuntimeError(
                    f"member {len(allocated)}/{len(members)} no longer fits: {e}"
                ) from e
            csp.event("phase1_allocated", members=len(allocated))

            # phases 2+3 fan the API writes over the bounded pool in CHUNKS
            # (one future per ~16 members, not per member — future/queue
            # overhead is pure GIL churn at 256 members)
            def run_phase(fn):
                nchunk = 16
                chunks = [
                    allocated[i : i + nchunk]
                    for i in range(0, len(allocated), nchunk)
                ]

                def run_chunk(chunk):
                    out = []
                    for item in chunk:
                        t0 = time.perf_counter()
                        try:
                            fn(item)
                        except Exception as e:
                            return out, e  # keep partials for rollback scope
                        out.append((item[0].key, time.perf_counter() - t0))
                    return out, None

                err = None
                done: dict[str, float] = {}
                for res in self._commit_pool.map(run_chunk, chunks):
                    partial, chunk_err = res
                    err = err or chunk_err
                    done.update(partial)
                return err, done

            # DCN boundary (VERDICT r4 #3): when the plan STRADDLES slices
            # (last-resort placement), every member learns its own slice
            # and the gang's ordered slice list, so the launcher can build
            # a hierarchical mesh (outer DCN data axis × inner ICI axes).
            # Slice ids come from the PLAN (captured at ordering time) —
            # no API call on the commit path; nodes the plan doesn't know
            # (plan expired / steered member) fall back to one retried
            # lookup, and an unresolvable node is a LOUD warning, because
            # a missed boundary means a flat mesh silently riding DCN.
            node_slice: dict[str, str] = {}
            for _, (node, _p) in members:
                if node in node_slice:
                    continue
                if node in plan_node_slices:
                    node_slice[node] = plan_node_slices[node]
                    continue
                slice_id = None
                for _attempt in range(2):
                    try:
                        labels = (
                            self.clientset.get_node(node).metadata.labels
                            or {}
                        )
                        slice_id = labels.get(consts.LABEL_TPU_SLICE, "")
                        break
                    except Exception:
                        continue
                if slice_id is None:
                    log.warning(
                        "gang %s: cannot resolve slice for node %s; "
                        "DCN-boundary annotations may be missing and the "
                        "job may build a flat mesh across slices",
                        gkey, node,
                    )
                    slice_id = ""
                node_slice[node] = slice_id
            gang_slices = sorted({s for s in node_slice.values() if s})
            straddles = len(gang_slices) > 1

            # SPMD identity (every gang): the member's rank in the
            # deterministic sorted-member order and the ordered peer
            # list, so the workload side can form ONE cross-host mesh —
            # jax.distributed process_id = rank, num_processes = gang
            # size, coordinator = peer 0 (parallel/mesh.gang_mesh).
            rank_of = {key: i for i, (key, _) in enumerate(members)}
            peers = ",".join(key for key, _ in members)

            # phase 2: annotation ledger for ALL members (reversible)
            def annotate(item):
                pod, node, opt = item
                if FAULTS.enabled:
                    # the mid-gang-commit kill point (HA chaos gate):
                    # 'crash' here dies AFTER the phase-1 journal seal
                    # with zero/partial ledger writes — the follower's
                    # replay plus the takeover diff must reconcile it
                    # with no double-book; 'error' exercises the
                    # balancing rollback ledger-strip path
                    FAULTS.maybe_fire("gang.phase2")
                extra = {
                    consts.ANNOTATION_GANG_RANK: str(
                        rank_of.get(pod.key, 0)
                    ),
                    consts.ANNOTATION_GANG_PEERS: peers,
                }
                if straddles:
                    extra.update({
                        consts.ANNOTATION_SLICE: node_slice.get(node, ""),
                        consts.ANNOTATION_GANG_SLICES: ",".join(gang_slices),
                    })
                sched.gang_annotate(pod, opt, node, extra=extra)

            phase2_err, done2 = run_phase(annotate)
            secs: dict[str, float] = dict(done2)
            if phase2_err is not None:
                # strip ALL members (a strip of an unwritten pod no-ops), so
                # a member whose write outcome is ambiguous is covered too
                self._rollback(
                    sched, allocated, strip_keys={p.key for p, _, _ in allocated}
                )
                raise RuntimeError(f"annotation write failed: {phase2_err}")
            csp.event("phase2_annotated", members=len(done2))

            # phase 3: POST all bindings
            def post(item):
                pod, node, opt = item
                sched.gang_post_binding(pod, node)

            phase3_err, done3 = run_phase(post)
            for key, dt in done3.items():
                secs[key] = secs.get(key, 0.0) + dt
            if phase3_err is not None:
                # bindings can't be un-POSTed; strip EVERY member's ledger
                # entry + free all chips so the failure leaves no allocation
                self._rollback(
                    sched, allocated, strip_keys={p.key for p, _, _ in allocated}
                )
                for pod, node, _ in allocated:
                    sched._record_event(
                        pod, "Warning", "GangBindRolledBack",
                        f"gang {gkey} commit failed after some bindings were "
                        f"accepted; TPU allocation released",
                    )
                raise RuntimeError(f"binding POST failed: {phase3_err}")
            csp.event("phase3_bindings_posted", members=len(done3))

            # post-commit bookkeeping (events are best-effort API POSTs —
            # fan them out too, not serially on the committer thread)
            list(self._commit_pool.map(
                lambda it: _trap(lambda x: sched.gang_note_bound(x[0], x[2], x[1]), it),
                allocated,
            ))
            with self._lock:
                for key, dt in secs.items():
                    self.commit_secs[key] = dt
                    GANG_COMMIT.observe(value=dt)
                self._plans.pop(gkey, None)
        except Exception as e:
            with self._lock:
                self._plans.pop(gkey, None)  # stale either way
            if sched.JOURNAL.enabled:
                # phase rollbacks freed every allocation before any bind
                # record was journaled, so this is informational: a gang
                # that reached commit and left NOTHING bound
                sched.JOURNAL.record(
                    "gang_rollback",
                    gang=gkey,
                    size=g.size,
                    members=[k for k, _ in members],
                    reason=(str(e) or repr(e))[:200],
                )
            raise

    def _rollback(self, sched, allocated, strip_keys: set[str]) -> None:
        """Strip written annotations (parallel, best-effort) + free chips."""

        def strip(item):
            pod, _, _ = item
            if pod.key in strip_keys:
                try:
                    sched.gang_strip_annotations(pod)
                except Exception as e:  # best-effort; resync will catch it
                    log.warning("gang rollback: strip %s failed: %s", pod.key, e)

        list(self._commit_pool.map(strip, allocated))
        with sched.lock:
            for pod, node, opt in allocated:
                sched.gang_unallocate(node, pod, opt)

    # -- bookkeeping ---------------------------------------------------------

    def _maybe_gc(self, key: str, g: _Gang) -> None:
        """Drop finished/failed-and-drained gangs + their plans
        (caller holds g.cond)."""
        finished = g.done >= g.size or (g.failed and not g.members)
        if finished:
            with self._lock:
                if self._gangs.get(key) is g:
                    del self._gangs[key]
                if g.done >= g.size or g.failed:
                    self._plans.pop(key, None)

    def status(self) -> dict:
        with self._lock:
            return {
                "gangs": {
                    k: {
                        "size": g.size,
                        "arrived": len(g.members),
                        "done": g.done,
                        "committed": g.committed,
                        "failed": g.failed,
                        "age_s": round(time.monotonic() - g.created, 3),
                    }
                    for k, g in self._gangs.items()
                },
                "plans": {
                    k: {"slots": len(p.slots), "claimed": len(p.claims)}
                    for k, p in self._plans.items()
                },
            }
