"""Prometheus-style metrics (text exposition, stdlib only).

The reference has NO metrics (SURVEY §5: "No Prometheus metrics"); per-verb
latency histograms are required here to *prove* the <100ms p99 bind target
(BASELINE.md).  Exposed at /metrics in the standard text format.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Iterable

# Live TimedLocks (weak: engines/tests create many short-lived ones).
# _DRAIN_LOCK serializes every structural touch of the set and of the
# per-lock wait buffers' heads (drains, registration, GC flushes, the
# over-cap trim) — WeakSet iteration is not safe against concurrent adds.
_TIMED_LOCKS: "weakref.WeakSet" = weakref.WeakSet()
_DRAIN_LOCK = threading.Lock()
# Per-lock buffer cap when nothing ever scrapes LOCK_WAIT (the histogram
# path used to trim retained samples at 10k; the buffers must too).
_WAITS_CAP = 20000

# Lock-ordering enforcement (VERDICT r4 #9): each ranked TimedLock may
# only be acquired while every lock this thread already holds has a
# STRICTLY LOWER rank.  The codebase's documented hierarchy:
#     gang coordinator (10)  →  gang resizer (14)  →
#     defrag planner (15)  →  scheduler engine (20)  →
#     per-node allocator locks (30)
# (per-gang condition vars sit below 10; the resize lock —
# fleet/resize.py — serializes whole membership transactions and takes
# engine/node locks and the defrag planner's run_round inside them; the
# defrag planner lock serializes migration rounds and may be held while
# taking engine/node locks — the gang filter only calls the planner
# AFTER releasing its own lock, and resize/defrag never nest in the
# other order.)  An
# inversion raises immediately: it is a deadlock that hasn't happened
# yet, and the GIL hides it from every stress test.  The static
# analysis plane (analysis/lockdep.py, `make check-analysis`) checks
# the same rule over the whole call graph, including paths no test
# executes.
_HELD_RANKS = threading.local()


# Waits parked by _flush_orphan when a TimedLock dies (ADVICE r5 #1).
# Appends/dels are GIL-atomic list ops; the scrape-path _drain folds the
# parked batches into LOCK_WAIT.
_ORPHAN_WAITS: list[tuple[str, list]] = []
# Sample counts _flush_orphan DROPPED at the parking-list cap.  The
# finalizer may run on a thread already inside any metric lock, so it
# cannot call Counter.inc (non-reentrant _lock → self-deadlock); it
# appends here (GIL-atomic) and the scrape-path _drain folds the counts
# into METRICS_DROPPED.
_ORPHAN_DROPPED: list[int] = []


def _flush_orphan(name: str, waits: list) -> None:
    """weakref.finalize hook: park a dying TimedLock's buffered waits so
    counts/sums stay complete for locks that die between scrapes.

    This is a GC callback: it can run synchronously on ANY thread at any
    allocation — including one already inside _DRAIN_LOCK (a drain's
    observe_batch allocating) or holding LOCK_WAIT._lock.  A blocking
    acquire here self-deadlocked that thread (ADVICE r5 #1), so the
    finalizer takes NO locks at all: it moves the buffer into a global
    parking list with GIL-atomic list ops and lets the next scrape-path
    _drain commit the batch."""
    n = len(waits)
    if n:
        vals = waits[:n]
        del waits[:n]
        if len(_ORPHAN_WAITS) < 4096:
            _ORPHAN_WAITS.append((name, vals))
        else:
            # drop: when nothing ever scrapes, losing dying locks' tail
            # samples beats unbounded growth (same stance as _WAITS_CAP); a
            # bound-and-trim here would race the scrape-path slice/del pair.
            # The drop itself is COUNTED (satellite: never discard samples
            # silently) — via the parking list, not Counter.inc, because
            # this is a GC callback (see _ORPHAN_DROPPED)
            _ORPHAN_DROPPED.append(n)


class Counter:
    def __init__(self, name: str, help_: str, label_names: tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._values: dict[tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def inc(self, *labels: str, value: float = 1.0) -> None:
        with self._lock:
            self._values[labels] = self._values.get(labels, 0.0) + value

    def reset(self) -> None:
        """Drop every label series (scrape-time gauges rebuilt per scrape
        use this so vanished labels don't linger at stale values)."""
        with self._lock:
            self._values.clear()

    def remove(self, *labels: str) -> None:
        """Drop ONE label series (a removed node must not keep exporting
        a stale per-node gauge)."""
        with self._lock:
            self._values.pop(labels, None)

    def collect(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} counter"
        with self._lock:
            for labels, v in sorted(self._values.items()):
                yield f"{self.name}{_fmt_labels(self.label_names, labels)} {v}"


class Gauge(Counter):
    def set(self, *labels: str, value: float) -> None:
        with self._lock:
            self._values[labels] = value

    def replace(self, values: dict[tuple[str, ...], float]) -> None:
        """Swap the whole series set in ONE lock acquisition — for
        scrape-time gauges rebuilt per refresh: a racing collect sees
        either the old set or the new one, never a cleared-but-unfilled
        intermediate (the torn-scrape hazard of reset()+set() loops)."""
        with self._lock:
            self._values = dict(values)

    def collect(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} gauge"
        with self._lock:
            for labels, v in sorted(self._values.items()):
                yield f"{self.name}{_fmt_labels(self.label_names, labels)} {v}"


def _exact_quantile(sorted_samples: list, q: float) -> float:
    """Nearest-rank quantile over an ascending list (one rounding rule
    shared by Histogram.quantile and Histogram.summary)."""
    if not sorted_samples:
        return 0.0
    n = len(sorted_samples)
    return sorted_samples[min(n - 1, max(0, int(q * n + 0.5) - 1))]


DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)


class Histogram:
    def __init__(
        self,
        name: str,
        help_: str,
        label_names: tuple[str, ...] = (),
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
    ):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple[str, ...], list[int]] = {}
        self._sums: dict[tuple[str, ...], float] = {}
        self._totals: dict[tuple[str, ...], int] = {}
        self._samples: dict[tuple[str, ...], list[float]] = {}
        self._lock = threading.Lock()

    def observe(self, *labels: str, value: float) -> None:
        with self._lock:
            counts = self._counts.setdefault(labels, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[labels] = self._sums.get(labels, 0.0) + value
            self._totals[labels] = self._totals.get(labels, 0) + 1
            samples = self._samples.setdefault(labels, [])
            samples.append(value)
            if len(samples) > 10000:
                del samples[: len(samples) // 2]

    def observe_batch(self, *labels: str, values: list) -> None:
        """Fold many observations in ONE lock acquisition (the lazy
        TimedLock drain path)."""
        if not values:
            return
        with self._lock:
            counts = self._counts.setdefault(
                labels, [0] * len(self.buckets)
            )
            for v in values:
                for i, b in enumerate(self.buckets):
                    if v <= b:
                        counts[i] += 1
            self._sums[labels] = self._sums.get(labels, 0.0) + sum(values)
            self._totals[labels] = self._totals.get(labels, 0) + len(values)
            samples = self._samples.setdefault(labels, [])
            samples.extend(values)
            if len(samples) > 10000:
                del samples[: len(samples) // 2]

    def time(self, *labels: str):
        return _Timer(self, labels)

    def samples(self, *labels: str) -> list[float]:
        """Retained raw samples (bench/test use)."""
        with self._lock:
            return list(self._samples.get(labels, []))

    def summary(self) -> dict:
        """Exact per-label summary in ONE lock acquisition: counts/sums
        from the authoritative counters (never the trimmed sample
        buffer), quantiles/max from the retained samples.  Sorting
        happens AFTER the lock releases — observe() runs inside
        TimedLock.acquire with the instrumented lock already held, so a
        scrape must never stall it behind an O(n log n) sort.  The
        public read API for profile endpoints."""
        out = {}
        with self._lock:
            items = [
                (labels, self._totals[labels], self._sums[labels],
                 list(self._samples.get(labels, ())))
                for labels in self._totals
            ]
        for labels, total, s, samples in items:
            samples.sort()
            out[",".join(labels)] = {
                "acquisitions": total,
                "wait_total_s": round(s, 6),
                "wait_max_s": round(samples[-1], 6) if samples else 0.0,
                "wait_p50_s": round(_exact_quantile(samples, 0.5), 6),
                "wait_p99_s": round(_exact_quantile(samples, 0.99), 6),
            }
        return out

    def quantile(self, q: float, *labels: str) -> float:
        """Exact quantile from retained samples (for bench/tests).
        Copies under the lock, sorts OUTSIDE it — observe() runs with
        instrumented locks held, so no reader may stall it on a sort."""
        with self._lock:
            samples = list(self._samples.get(labels, ()))
        samples.sort()
        return _exact_quantile(samples, q)

    def collect(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} histogram"
        with self._lock:
            for labels in sorted(self._counts):
                counts = self._counts[labels]
                for b, c in zip(self.buckets, counts):
                    le = self.label_names + ("le",)
                    lv = labels + (repr(float(b)),)
                    yield f"{self.name}_bucket{_fmt_labels(le, lv)} {c}"
                le = self.label_names + ("le",)
                lv = labels + ("+Inf",)
                yield f"{self.name}_bucket{_fmt_labels(le, lv)} {self._totals[labels]}"
                yield (
                    f"{self.name}_sum{_fmt_labels(self.label_names, labels)} "
                    f"{self._sums[labels]}"
                )
                yield (
                    f"{self.name}_count{_fmt_labels(self.label_names, labels)} "
                    f"{self._totals[labels]}"
                )


class _Timer:
    def __init__(self, hist: Histogram, labels: tuple[str, ...]):
        self.hist = hist
        self.labels = labels

    def __enter__(self):
        self.start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(*self.labels, value=time.perf_counter() - self.start)
        return False


def _fmt_labels(names: tuple[str, ...], values: tuple[str, ...]) -> str:
    if not names:
        return ""
    pairs = ",".join(f'{n}="{v}"' for n, v in zip(names, values))
    return "{" + pairs + "}"


class Registry:
    def __init__(self):
        self._metrics: list = []
        self._lock = threading.Lock()

    def register(self, m):
        with self._lock:
            self._metrics.append(m)
        return m

    def expose(self) -> str:
        lines = []
        with self._lock:
            for m in self._metrics:
                lines.extend(m.collect())
        return "\n".join(lines) + "\n"


REGISTRY = Registry()

VERB_LATENCY = REGISTRY.register(
    Histogram(
        "tpu_scheduler_verb_duration_seconds",
        "Latency of extender verbs (filter/priorities/bind)",
        ("verb",),
    )
)
VERB_TOTAL = REGISTRY.register(
    Counter(
        "tpu_scheduler_verb_total",
        "Extender verb invocations by result",
        ("verb", "result"),
    )
)
CHIPS_ALLOCATED = REGISTRY.register(
    Gauge(
        "tpu_scheduler_chips_core_allocated",
        "Allocated core units per node",
        ("node",),
    )
)
GANG_EVENTS = REGISTRY.register(
    Counter(
        "tpu_scheduler_gang_events_total",
        "Gang lifecycle events",
        ("event",),
    )
)
GANG_COMMIT = REGISTRY.register(
    Histogram(
        "tpu_scheduler_gang_commit_seconds",
        "Per-member commit latency after the gang barrier trips "
        "(allocate + annotation write + binding; excludes barrier wait)",
    )
)
PLAN_CACHE = REGISTRY.register(
    Counter(
        "tpu_scheduler_plan_events_total",
        "Gang-plan fast-path events: native_kernel/python_kernel count "
        "plan_gang invocations, native_batch_kernel/python_batch_kernel "
        "count plan_gang_batch sweep invocations, hit/miss count the "
        "memoized per-member trade cache (hit = a congruent node state "
        "replayed a placement instead of re-running the DFS)",
        ("event",),
    )
)
METRICS_DROPPED = REGISTRY.register(
    Counter(
        "tpu_metrics_dropped_samples_total",
        "Samples discarded by bounded buffers, by reason: waits_cap = a "
        "TimedLock's wait buffer trimmed with nothing scraping "
        "LOCK_WAIT; orphan_cap = a dying lock's parked waits dropped at "
        "the 4096-entry orphan-list cap; trace_pin_cap = a pinned "
        "trace's parked span evicted at the tracer's pinned-span cap "
        "(an open pod trace or pinned stream outgrew the protected "
        "store).  Non-zero values mean the corresponding histograms/"
        "traces UNDERSTATE reality by that many samples",
        ("reason",),
    )
)
class LazyGauge(Gauge):
    """Gauge recomputed by a registered ``refresher`` at collect() time —
    for scrape-time values whose computation (e.g. the contiguous-box
    scan behind the fragmentation gauges) must stay OFF the bind path:
    the scraper pays it, never the scheduler.

    Refreshes are SINGLE-FLIGHT: two scrapes racing collect() must not
    both pay the scan (a slow refresher would double its cost exactly
    when scrapers pile up), and the late scraper must not export a value
    set the early one is still mid-computing.  A scraper that arrives
    while a refresh is running parks on the refresh lock and, once the
    winner finishes, exports the winner's fresh values WITHOUT re-running
    the refresher (the generation counter tells it a refresh completed
    while it waited)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.refresher = None
        self._refresh_lock = threading.Lock()
        self._refresh_gen = 0

    def collect(self):
        r = self.refresher
        if r is not None:
            gen0 = self._refresh_gen
            with self._refresh_lock:
                if self._refresh_gen == gen0:
                    # nobody refreshed while we waited for the lock —
                    # this scrape is the flight that pays the scan
                    try:
                        r()
                    except Exception:
                        # a broken refresher must not kill /metrics
                        pass
                    self._refresh_gen = gen0 + 1
        yield from super().collect()


FRAG_INDEX = REGISTRY.register(
    LazyGauge(
        "tpu_scheduler_mesh_fragmentation_index",
        "Per-node ICI-mesh fragmentation, computed at scrape time: "
        "1 - largest_free_contiguous_submesh / free_chips (0 = the free "
        "set is one contiguous box or the node is full)",
        ("node",),
    )
)
FREE_SUBMESH = REGISTRY.register(
    LazyGauge(
        "tpu_scheduler_largest_free_submesh_chips",
        "Largest fully-free contiguous axis-aligned submesh on the node "
        "(chips), computed at scrape time — the biggest whole-chip "
        "container that can still land with full ICI locality",
        ("node",),
    )
)
DEFRAG_EVENTS = REGISTRY.register(
    Counter(
        "tpu_scheduler_defrag_events_total",
        "Defragmentation planner lifecycle events: round_planned/"
        "round_executed/round_noop/round_failed, move_executed/"
        "move_rolled_back/rollback_failed, unblock_retry (a gang filter "
        "re-admitted after a round), unblock_rate_limited",
        ("event",),
    )
)
DEFRAG_ROUND = REGISTRY.register(
    Histogram(
        "tpu_scheduler_defrag_round_seconds",
        "Wall time of one defrag round (plan + journaled migrations)",
    )
)
DEFRAG_RECOVERED = REGISTRY.register(
    Gauge(
        "tpu_scheduler_defrag_recovered_chips",
        "Largest-free-contiguous-submesh gain (chips) of the most recent "
        "executed defrag round — capacity the round recovered for big "
        "whole-chip placements",
    )
)
FLEET_ROUTED = REGISTRY.register(
    Counter(
        "tpu_fleet_routed_total",
        "Front-door routing decisions by kind: affinity (prefix-digest "
        "match), least_loaded (fallback), failover (first choice "
        "unreachable, rerouted), aborted (relay broke after first "
        "client byte — never retried), no_replica (every replica "
        "down/draining → 503), exhausted (replicas looked routable but "
        "every connect/forward failed → 502)",
        ("kind",),
    )
)
FLEET_ROUTE_OVERHEAD = REGISTRY.register(
    Histogram(
        "tpu_fleet_route_overhead_seconds",
        "Router-added latency per request: route selection + backend "
        "connect + request forward, EXCLUDING the backend's own "
        "generation time (the relay loop is a byte pump; its cost is "
        "per-burst, not per-token)",
        buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0),
    )
)
FLEET_REPLICAS = REGISTRY.register(
    Gauge(
        "tpu_fleet_replicas",
        "Replica-set size by health state (up/warming/draining/down), "
        "refreshed by the router's health loop",
        ("state",),
    )
)
FLEET_EVENTS = REGISTRY.register(
    Counter(
        "tpu_fleet_autoscaler_events_total",
        "Autoscaler lifecycle events: scale_up/scale_down (executed), "
        "scale_up_failed/scale_down_failed, hold (evaluation with no "
        "action), cooldown_suppressed, bounds_suppressed, "
        "warming_suppressed (scale-up held while a replica pre-lowers "
        "its compile lattice), resize_executed/resize_failed",
        ("event",),
    )
)
FLEET_SCALE_LATENCY = REGISTRY.register(
    Histogram(
        "tpu_fleet_scale_seconds",
        "Wall time of one executed scale action (decision → gang "
        "admission/release through the scheduler surface → replica "
        "routable/drained)",
        buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0),
    )
)
KV_PAGES_RESIDENT = REGISTRY.register(
    Gauge(
        "tpu_kv_pages_resident",
        "Serving-engine KV page pool residency by kind, set at scrape "
        "time from live engine state: active (referenced by live "
        "slots), cached (prefix-cache registered, LRU-evictable), free",
        ("kind",),
    )
)
KV_PAGES_SHIPPED = REGISTRY.register(
    Gauge(
        "tpu_kv_pages_shipped",
        "Monotonic count of KV pages shipped replica-to-replica over "
        "the disaggregated data plane, by direction (exported/"
        "imported); exposed at scrape time from the engine's counters "
        "(the tpu_serve_spills stance)",
        ("direction",),
    )
)
KV_PREFIX_ADMISSIONS = REGISTRY.register(
    Gauge(
        "tpu_kv_prefix_admissions",
        "Monotonic admission-level prefix-cache outcomes (hit = at "
        "least one full cached page attached at admission, incl. "
        "adopted pages; miss = prefill from scratch), set at scrape "
        "time from engine counters",
        ("result",),
    )
)
KV_MIGRATIONS = REGISTRY.register(
    Counter(
        "tpu_kv_migrations_total",
        "Live KV session migrations by outcome: out (handoff accepted, "
        "continuation relayed), out_refused (destination refused — "
        "session resumed locally, exact), in (session adopted from a "
        "peer), shed (autoscaler-commanded rebalance executed), "
        "shed_failed",
        ("result",),
    )
)
COMPILE_CACHE_EVENTS = REGISTRY.register(
    Counter(
        "tpu_compile_cache_events_total",
        "Warm-start compile cache events: hit (in-memory executable "
        "reused), load (persistent entry deserialized — no lowering), "
        "miss (lower+compile paid), fill (entry persisted to the cache "
        "dir), coalesced (concurrent miss parked behind the "
        "single-flight winner), quarantined (corrupt entry moved aside, "
        "recompiled), persist_error (serialize/write failed — compile "
        "still served), fallback (AOT path error → jit dispatch)",
        ("event",),
    )
)
WARMUP_SECONDS = REGISTRY.register(
    Gauge(
        "tpu_warmup_seconds",
        "Wall time of the shape-lattice pre-lowering phase at pod start "
        "(0 until a warm-up has completed); the window the pod reports "
        "healthz 503 {warming:true} and the fleet router keeps it out "
        "of rotation",
    )
)
POLICY_EVALS = REGISTRY.register(
    Counter(
        "tpu_policy_evals_total",
        "Hot-loaded policy evaluations by verb (score/filter/preempt/"
        "defrag/kv) and outcome: ok, fault (budget trip / deadline / "
        "math fault → fell back to the incumbent built-in), or — for "
        "canary score decisions — the arm that decided (candidate/"
        "incumbent)",
        ("verb", "outcome"),
    )
)
POLICY_EVENTS = REGISTRY.register(
    Counter(
        "tpu_policy_events_total",
        "Policy-plane lifecycle events: load, gate_pass, gate_block "
        "(replay gate refused a worse candidate), promote, rollback "
        "(operator or automatic SLO rollback), fault",
        ("event",),
    )
)
LEADER_STATE = REGISTRY.register(
    Gauge(
        "tpu_leader_state",
        "Leader-election state of this replica: 1 = leading (serving "
        "verbs), 0.5 = fenced (stepping down: new verbs already 503, "
        "in-flight verbs draining, journal flushing), 0 = standby",
    )
)
HA_FOLLOW_LAG_SEQS = REGISTRY.register(
    Gauge(
        "tpu_ha_follow_lag_seqs",
        "Journal-shipping follower lag in sequence numbers: the "
        "leader's newest assigned seq minus the newest seq this "
        "follower has replayed (0 = caught up; alert when it grows — a "
        "takeover from a lagging follower pays the difference as diff "
        "resync)",
    )
)
HA_FOLLOW_LAG_SECONDS = REGISTRY.register(
    Gauge(
        "tpu_ha_follow_lag_seconds",
        "Journal-shipping follower lag in wall seconds: age of the "
        "newest replayed record while the follower is behind (0 when "
        "caught up)",
    )
)
HA_TAKEOVER_SECONDS = REGISTRY.register(
    Gauge(
        "tpu_ha_takeover_seconds",
        "Wall time of the most recent warm takeover: adopting the "
        "follower's replayed state plus the diff resync against the "
        "annotation ledger (0 until a takeover has happened; the "
        "journaled ha_takeover record carries the same number)",
    )
)


class _LockWaitHistogram(Histogram):
    """LOCK_WAIT with lazy ingestion: every read API drains the
    TimedLock wait buffers first.

    Why: observe() inside TimedLock.acquire runs with the instrumented
    lock ALREADY HELD, so its cost (histogram mutex + bucket loop)
    extends hold time at exactly the contention point and compounds
    across every queued waiter — the round-4 cfg5 gang-wall regression
    (42.9 → 78.5 ms) was precisely this.  Recording is now one
    GIL-atomic list append on the hot path; bucketing happens here, on
    the scrape/read path, where stalls are harmless."""

    def _drain(self) -> None:
        with _DRAIN_LOCK:  # guards WeakSet iteration vs concurrent adds
            for tl in list(_TIMED_LOCKS):
                tl._drain_locked(self)
            # fold in waits parked by dying locks' finalizers (which must
            # not lock — see _flush_orphan); the slice-then-del pair is
            # safe against concurrent finalizer appends landing at the tail
            n = len(_ORPHAN_WAITS)
            if n:
                parked = _ORPHAN_WAITS[:n]
                del _ORPHAN_WAITS[:n]
                for name, vals in parked:
                    self.observe_batch(name, values=vals)
            nd = len(_ORPHAN_DROPPED)
            if nd:
                counts = _ORPHAN_DROPPED[:nd]
                del _ORPHAN_DROPPED[:nd]
                METRICS_DROPPED.inc("orphan_cap", value=float(sum(counts)))

    def samples(self, *labels: str) -> list:
        self._drain()
        return super().samples(*labels)

    def summary(self) -> dict:
        self._drain()
        return super().summary()

    def quantile(self, q: float, *labels: str) -> float:
        self._drain()
        return super().quantile(q, *labels)

    def collect(self):
        self._drain()
        yield from super().collect()


LOCK_WAIT = REGISTRY.register(
    _LockWaitHistogram(
        "tpu_scheduler_lock_wait_seconds",
        "Time spent WAITING to acquire the engine-global scheduler lock "
        "and the gang coordinator lock (the mutex/block-profile parity "
        "slot: reference pprof.go:10-64 mounts Go's block/mutex profiles)",
        ("lock",),
    )
)


class TimedLock:
    """Lock/RLock wrapper that records acquisition WAIT time in LOCK_WAIT.

    The scheduler's single coarse lock is its scaling cliff (the
    reference's GPUUnitScheduler carries the same design, scheduler.go:44);
    CPU/heap/stack profiling existed here but nothing measured how long
    binds queue on the mutex.  Hold time is deliberately NOT measured —
    waiters' wait IS holders' hold, and wait is the operative signal.

    Recording is ONE GIL-atomic list append; the sample is bucketed into
    LOCK_WAIT lazily, when a reader scrapes.  observe() here would run
    with the instrumented lock already held, lengthening hold time at
    exactly the contention point and compounding across queued waiters
    (the round-4 cfg5 gang-wall regression)."""

    def __init__(
        self, name: str, reentrant: bool = False, rank: int | None = None
    ):
        self._inner = (
            threading.RLock() if reentrant else threading.Lock()
        )
        self._name = name
        self._rank = rank  # lock-order position; None = unranked
        # owner/depth: reentrant re-acquires by the holder wait 0 by
        # definition — sampling them would flood the histogram with ~0s
        # entries and mask real queueing (the signal this exists for).
        # _owner is written only by the holder; a racing reader sees
        # either None or another thread's id, and measures — correct
        # either way.
        self._owner: int | None = None
        self._depth = 0
        self._waits: list[float] = []
        with _DRAIN_LOCK:
            _TIMED_LOCKS.add(self)
        # a lock GC'd between scrapes must not drop its buffered waits
        # (the finalizer closes over the buffer, not the lock)
        weakref.finalize(self, _flush_orphan, name, self._waits)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        if self._owner == me:  # reentrant re-acquire: no wait, no sample
            ok = self._inner.acquire(blocking, timeout)
            if ok:
                self._depth += 1
            return ok
        if self._rank is not None and blocking and timeout < 0:
            # only INDEFINITE blocking acquires can deadlock; try-locks
            # and timeout-bounded acquires are legal in any order
            held = getattr(_HELD_RANKS, "stack", None)
            if held:
                top = max(held)  # releases may interleave; check the max
                if top[0] >= self._rank:
                    raise RuntimeError(
                        f"lock-order inversion: acquiring {self._name!r} "
                        f"(rank {self._rank}) while holding {top[1]!r} "
                        f"(rank {top[0]}) — locks must be taken in "
                        "strictly increasing rank order (see the rank "
                        "assignments for the documented hierarchy); this "
                        "ordering would deadlock under contention"
                    )
        t0 = time.perf_counter()
        ok = self._inner.acquire(blocking, timeout)
        if ok:  # failed acquires (timeout / non-blocking miss) are not
            # waits that ended in the lock — don't pollute the histogram
            self._owner = me
            self._depth = 1
            if self._rank is not None:
                if not hasattr(_HELD_RANKS, "stack"):
                    _HELD_RANKS.stack = []
                entry = (self._rank, self._name)
                _HELD_RANKS.stack.append(entry)
                # remember WHICH thread's stack holds the entry, so a
                # cross-thread release (legal on the plain-Lock variant)
                # still removes it from the acquirer's stack
                self._rank_entry = (_HELD_RANKS.stack, entry)
            self._waits.append(time.perf_counter() - t0)
            if len(self._waits) > _WAITS_CAP and _DRAIN_LOCK.acquire(
                blocking=False
            ):  # nothing is scraping: trim like the histogram would.
                # try-acquire keeps the hot path non-blocking; a losing
                # race just retries at the next over-cap acquire.
                try:
                    del self._waits[: _WAITS_CAP // 2]
                finally:
                    _DRAIN_LOCK.release()
                # count what was just discarded (satellite: no silent
                # drops).  One Counter.inc per ~10k acquisitions — off
                # the per-acquire path by construction
                METRICS_DROPPED.inc(
                    "waits_cap", value=float(_WAITS_CAP // 2)
                )
        return ok

    def _drain_locked(self, hist: Histogram) -> None:
        """Move buffered waits into the histogram (scrape path; caller
        holds _DRAIN_LOCK).  Atomic list ops only: concurrent hot-path
        appends land at the tail and survive the in-place del."""
        buf = self._waits
        n = len(buf)
        if n:
            vals = buf[:n]
            del buf[:n]
            hist.observe_batch(self._name, values=vals)

    def release(self) -> None:
        self._depth -= 1
        if self._depth == 0:
            self._owner = None
            if self._rank is not None:
                ref = getattr(self, "_rank_entry", None)
                if ref is not None:
                    stack, entry = ref
                    self._rank_entry = None
                    try:
                        stack.remove(entry)  # list ops are GIL-atomic,
                        # and this is the ACQUIRER's stack even when a
                        # different thread releases
                    except ValueError:
                        pass
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False
