"""metrics subpackage of elastic_gpu_scheduler_tpu."""
