"""tpu-elastic-scheduler: a TPU-native Kubernetes scheduling framework.

A from-scratch rebuild of the capabilities of elastic-ai/elastic-gpu-scheduler
(reference: /root/reference, a Go kube-scheduler extender for fractional/multi-card
GPU scheduling) retargeted to Cloud TPU:

- Extended resources ``elasticgpu.io/tpu-chip`` (100 units = 1 chip, fractional
  TensorCore sharing) and ``elasticgpu.io/tpu-hbm`` (GiB), replacing
  ``gpu-core``/``gpu-memory`` (reference: pkg/utils/types.go:6).
- Placement over an explicit ICI mesh topology: allocations carry mesh
  *coordinates*, not flat card indices (reference hands out anonymous indices,
  pkg/scheduler/gpu.go:100).
- Gang scheduling (all-or-nothing bind for SPMD replica groups) and
  contiguous-sub-slice search — net-new vs. the reference.
- A JAX/XLA workload plane (models/, ops/, parallel/) so scheduled placements
  translate directly into ``jax.sharding.Mesh`` axes for pjit/shard_map jobs.
"""

__version__ = "0.1.0"
