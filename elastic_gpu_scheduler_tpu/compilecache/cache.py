"""Persistent ahead-of-time compile cache: serialized XLA executables on
disk, keyed by (function fingerprint, input shapes, backend).

At millions-of-users traffic every shape-lattice miss at serving
admission eats a full XLA compile — a p99.9 cliff the scheduler, router
and autoscaler are all blind to (ROADMAP item 1).  This cache makes the
compile a one-time cost per (code, shape, backend) triple:

- **Entry format.**  ``<dir>/<key>.aotx``: an 8-byte magic, a
  length-prefixed JSON header carrying the key, a CRC32 of the payload
  and human-auditable metadata, then the payload — the pickled
  ``jax.experimental.serialize_executable.serialize`` triple
  (executable bytes, in_tree, out_tree).  Writes are atomic
  (tmp + rename); a torn or bit-flipped entry fails the CRC and is
  QUARANTINED (renamed ``.bad``) and recompiled, never fatal.
- **Single-flight.**  Concurrent misses on one key compile ONCE: the
  first caller owns the build, the rest park on an event and adopt the
  winner's executable (``coalesced`` counter).  A pod start that fans
  admission across handler threads cannot compile the same kernel N×.
- **Counters.**  hits / loads / misses / fills / coalesced /
  quarantined / persist_errors / fallbacks — exported as
  ``tpu_compile_cache_events_total`` and surfaced on ``/v1/stats`` so
  the fleet tooling (check-compile-cache, bench's compile section) can
  assert "second start on the same dir performs zero new lowerings".

Trust model: the cache dir is operator-owned state, same trust domain
as a model checkpoint dir — the CRC detects corruption, not tampering.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import struct
import threading
import zlib
from hashlib import blake2b
from typing import Callable, Optional

from ..metrics import COMPILE_CACHE_EVENTS

log = logging.getLogger("tpu-scheduler")

_MAGIC = b"TPUAOTC1"
_SUFFIX = ".aotx"


def cache_key(*parts) -> str:
    """Stable hex digest over the fingerprint parts (stringified in
    order).  Callers include everything that changes the lowered
    program: function tag + variant, model/engine config, input shapes
    and dtypes, mesh shape, backend, jax version."""
    h = blake2b(digest_size=16)
    for p in parts:
        h.update(repr(p).encode())
        h.update(b"\x00")
    return h.hexdigest()


class CompileCache:
    """In-memory + optional on-disk executable cache with single-flight
    compilation.  ``cache_dir=None`` keeps the single-flight memo and
    counters but persists nothing (warm-up still works; warmth just
    does not survive the process)."""

    def __init__(self, cache_dir: Optional[str] = None):
        self.cache_dir = cache_dir or None
        if self.cache_dir:
            try:
                os.makedirs(self.cache_dir, exist_ok=True)
            except OSError as e:
                # the stance everywhere in this module: the cache can
                # only ADD warmth, never take down serving — an
                # unwritable dir (root-owned hostPath, read-only fs)
                # degrades to in-memory-only, not a crash-looping pod
                log.warning(
                    "compile cache: cannot create %s (%s); running "
                    "without persistence", self.cache_dir, e,
                )
                self.cache_dir = None
        self._mem: dict[str, object] = {}  # key → loaded executable
        self._lock = threading.Lock()  # memo + inflight bookkeeping
        self._inflight: dict[str, threading.Event] = {}
        self.hits = 0
        self.loads = 0
        self.misses = 0
        self.fills = 0
        self.coalesced = 0
        self.quarantined = 0
        self.persist_errors = 0
        self.fallbacks = 0  # incremented by AotFunction

    # -- events --------------------------------------------------------------

    _EVENT_ATTR = {
        "hit": "hits",
        "load": "loads",
        "miss": "misses",
        "fill": "fills",
        "coalesced": "coalesced",
        "quarantined": "quarantined",
        "persist_error": "persist_errors",
        "fallback": "fallbacks",
    }

    def _event(self, name: str) -> None:
        attr = self._EVENT_ATTR[name]
        setattr(self, attr, getattr(self, attr) + 1)
        COMPILE_CACHE_EVENTS.inc(name)

    # -- disk format ---------------------------------------------------------

    def _path(self, key: str) -> str:
        return os.path.join(self.cache_dir, key + _SUFFIX)

    def _write_entry(self, key: str, payload: bytes, meta: dict) -> None:
        header = json.dumps({
            "key": key,
            "crc": zlib.crc32(payload) & 0xFFFFFFFF,
            "len": len(payload),
            "meta": meta,
        }, sort_keys=True).encode()
        path = self._path(key)
        tmp = path + f".tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<I", len(header)))
            f.write(header)
            f.write(payload)
        os.replace(tmp, path)  # atomic: readers see whole entries only

    def _read_entry(self, key: str) -> Optional[bytes]:
        """Payload bytes for a valid entry, None for absent, and a
        QUARANTINE (rename to .bad + None) for anything corrupt — a bad
        entry must cost one recompile, never a crash loop."""
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return None
        except OSError as e:
            log.warning("compile cache: unreadable entry %s: %s", path, e)
            return None
        try:
            if blob[: len(_MAGIC)] != _MAGIC:
                raise ValueError("bad magic")
            off = len(_MAGIC)
            (hlen,) = struct.unpack_from("<I", blob, off)
            off += 4
            header = json.loads(blob[off : off + hlen])
            off += hlen
            payload = blob[off:]
            if header.get("key") != key:
                raise ValueError("key mismatch")
            if len(payload) != int(header.get("len", -1)):
                raise ValueError("truncated payload")
            if (zlib.crc32(payload) & 0xFFFFFFFF) != int(header["crc"]):
                raise ValueError("CRC mismatch")
            return payload
        except (ValueError, KeyError, struct.error,
                json.JSONDecodeError) as e:
            self._event("quarantined")
            bad = path + ".bad"
            try:
                os.replace(path, bad)
            except OSError:
                try:
                    os.remove(path)
                except OSError:
                    pass
            log.warning(
                "compile cache: quarantined corrupt entry %s (%s)", path, e
            )
            return None

    def _load(self, key: str):
        """Deserialize a persistent entry into a callable executable, or
        None.  Deserialization failures quarantine like CRC failures:
        the bytes may be from an incompatible jaxlib."""
        if not self.cache_dir:
            return None
        payload = self._read_entry(key)
        if payload is None:
            return None
        try:
            from jax.experimental import serialize_executable

            blob, in_tree, out_tree = pickle.loads(payload)
            return serialize_executable.deserialize_and_load(
                blob, in_tree, out_tree
            )
        except Exception as e:  # noqa: BLE001 — any failure = recompile
            self._event("quarantined")
            try:
                os.replace(self._path(key), self._path(key) + ".bad")
            except OSError:
                pass
            log.warning(
                "compile cache: entry %s failed to deserialize (%s); "
                "quarantined", key, e,
            )
            return None

    def _persist(self, key: str, compiled, meta) -> None:
        if not self.cache_dir:
            return
        try:
            from jax.experimental import serialize_executable

            triple = serialize_executable.serialize(compiled)
            # meta may be a thunk: header metadata is only computed on
            # this (rare) persist path, never per dispatch
            self._write_entry(
                key, pickle.dumps(triple),
                meta() if callable(meta) else (meta or {}),
            )
            self._event("fill")
        except Exception as e:  # noqa: BLE001 — persistence is best-effort
            self._event("persist_error")
            log.warning(
                "compile cache: could not persist %s (%s); serving from "
                "the in-process executable", key, e,
            )

    # -- the one entry point -------------------------------------------------

    def get_or_compile(
        self, key: str, build: Callable[[], object], meta=None
    ):
        """The executable for ``key``: in-memory hit, else persistent
        load, else ``build()`` (lower+compile) + persist.  Concurrent
        callers for one key coalesce behind a single builder.  ``meta``
        (dict or zero-arg thunk) lands in the entry header — a thunk is
        only evaluated when an entry is actually written."""
        with self._lock:
            exe = self._mem.get(key)
            if exe is not None:
                self._event("hit")
                return exe
            ev = self._inflight.get(key)
            if ev is None:
                self._inflight[key] = threading.Event()
            # else: someone is building; fall through to wait
        if ev is not None:
            self._event("coalesced")
            ev.wait()
            with self._lock:
                exe = self._mem.get(key)
            if exe is not None:
                return exe
            # builder failed: take over the build ourselves
            return self.get_or_compile(key, build, meta)
        try:
            exe = self._load(key)
            if exe is not None:
                self._event("load")
            else:
                self._event("miss")
                exe = build()
                self._persist(key, exe, meta or {})
            with self._lock:
                self._mem[key] = exe
            return exe
        finally:
            with self._lock:
                ev2 = self._inflight.pop(key, None)
            if ev2 is not None:
                ev2.set()

    # -- introspection -------------------------------------------------------

    def entries(self) -> int:
        with self._lock:
            return len(self._mem)

    def disk_entries(self) -> int:
        if not self.cache_dir:
            return 0
        try:
            return sum(
                1 for n in os.listdir(self.cache_dir)
                if n.endswith(_SUFFIX)
            )
        except OSError:
            return 0

    def stats(self) -> dict:
        return {
            "dir": self.cache_dir or "",
            "entries": self.entries(),
            "disk_entries": self.disk_entries(),
            "hits": self.hits,
            "loads": self.loads,
            "misses": self.misses,
            "fills": self.fills,
            "coalesced": self.coalesced,
            "quarantined": self.quarantined,
            "persist_errors": self.persist_errors,
            "fallbacks": self.fallbacks,
        }
