"""AOT dispatch: route a jitted function's calls through the compile
cache.

``jax.jit``'s internal executable cache and the AOT
``lower().compile()`` path are separate worlds — pre-compiling via AOT
does not warm the jit call path.  So when the warm-start plane is on,
the engine calls THROUGH the AOT executables: :class:`AotFunction`
wraps a jitted function, keys executables by the call's input
shapes/dtypes (plus the wrapper's static fingerprint), and serves every
call from the cache — a shape seen at warm-up (or in a previous
process, via the persistent cache) never compiles again.

Safety stance: the jit path remains the fallback.  Any error in key
derivation, cache lookup, deserialization or AOT lowering falls back to
``jitfn(*args)`` (counted, logged once per wrapper) — the cache can
only ever add warmth, never take down serving.  Execution errors from a
successfully-built executable propagate exactly as the jit path's
would.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional, Sequence

from .cache import CompileCache, cache_key

log = logging.getLogger("tpu-scheduler")


def _shape_key(args) -> tuple:
    """(shape, dtype) per pytree leaf — the dynamic half of the cache
    key.  None subtrees contribute no leaves, which is exactly how the
    jit cache distinguishes the engine's variant calls too (the static
    half already carries the variant tuple)."""
    import jax
    import numpy as np

    out = []
    for leaf in jax.tree_util.tree_leaves(args):
        out.append((tuple(np.shape(leaf)), str(getattr(leaf, "dtype", ""))))
    return tuple(out)


class AotFunction:
    """A jitted function routed through a :class:`CompileCache`.

    ``fingerprint_parts`` must capture everything static that changes
    the lowered program (tag, variant, engine/model config, mesh shape,
    backend, jax version) — the per-call input shapes are appended
    automatically.
    """

    def __init__(
        self,
        jitfn,
        cache: CompileCache,
        fingerprint_parts: Sequence,
        tag: str = "",
    ):
        self._jit = jitfn
        self.cache = cache
        self.tag = tag or "aot"
        self._fp = tuple(fingerprint_parts)
        self._warned = False
        # key-string memo: hashing the fingerprint repr per dispatch is
        # measurable on the decode hot loop; shape_key → full key
        self._keys: dict[tuple, str] = {}
        self._keys_lock = threading.Lock()

    # -- keys ----------------------------------------------------------------

    def key_for(self, args) -> str:
        sk = _shape_key(args)
        k = self._keys.get(sk)
        if k is None:
            k = cache_key(self.tag, self._fp, sk)
            with self._keys_lock:
                self._keys[sk] = k
        return k

    # -- build (warm-up path: lower+compile, never execute) ------------------

    def build(self, *args):
        """Ensure the executable for these args' shapes exists (memory,
        disk, or freshly compiled) WITHOUT executing it — the shape-
        lattice warm-up's primitive.  Returns the executable.  ``meta``
        is a thunk: the entry-header metadata (a second pytree flatten
        + a ~2KB repr) is only worth paying on the persist path, never
        on the per-dispatch hit path."""
        key = self.key_for(args)
        return self.cache.get_or_compile(
            key,
            lambda: self._jit.lower(*args).compile(),
            meta=lambda: {
                "tag": self.tag,
                "shapes": repr(_shape_key(args))[:2048],
            },
        )

    # -- dispatch ------------------------------------------------------------

    def __call__(self, *args):
        try:
            exe = self.build(*args)
        except Exception as e:  # noqa: BLE001 — cache must never 500 serving
            self.cache._event("fallback")
            if not self._warned:
                self._warned = True
                log.warning(
                    "compile cache: AOT path for %s failed (%s); falling "
                    "back to jit dispatch (logged once)", self.tag, e,
                )
            return self._jit(*args)
        return exe(*args)


def wrap(jitfn, cache: Optional[CompileCache], fingerprint_parts, tag: str):
    """``AotFunction`` when a cache is active, the jitted function
    itself otherwise — call sites stay identical either way."""
    if cache is None:
        return jitfn
    return AotFunction(jitfn, cache, fingerprint_parts, tag=tag)
