"""Warm-start compilation plane (ROADMAP item 1).

Three pieces, composing into "a replica never eats an XLA compile on
the serving path":

- :mod:`.cache` — the persistent AOT compile cache: serialized XLA
  executables keyed by (function fingerprint, input shapes, backend),
  CRC-checked entries under ``--compile-cache-dir``, single-flight
  in-process compilation, hit/load/miss/fill counters
  (``tpu_compile_cache_events_total``).
- :mod:`.aot` — :class:`AotFunction`, the dispatch wrapper that routes
  a jitted function's calls through the cache (jit stays the fallback).
- :mod:`.lattice` — shape-lattice pre-lowering: enumerate the engine's
  (batch, length)-bucket lattice at pod start and lower every fused
  kernel BEFORE the pod reports Ready (``tpu_warmup_seconds``); the
  fleet router's ``warming`` replica state and the autoscaler's
  scale-up suppression gate traffic on the result.

See OPERATIONS.md "Compilation warm-start" for the runbook and
``make check-compile-cache`` for the CI gate.
"""

from .aot import AotFunction, wrap
from .cache import CompileCache, cache_key
from .lattice import (
    WarmupState,
    start_warmup_thread,
    warmup_engine,
)

__all__ = [
    "AotFunction",
    "CompileCache",
    "WarmupState",
    "cache_key",
    "start_warmup_thread",
    "warmup_engine",
    "wrap",
]
