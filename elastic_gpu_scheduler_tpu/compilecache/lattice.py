"""Shape-lattice pre-lowering: warm every serving-admission shape
BEFORE the pod reports Ready.

The serving engine buckets its dispatch shapes (prefill pad lengths and
page-table widths round up to powers of two), so the set of programs
admission can demand is a small, enumerable lattice —
``InferenceEngine.aot_signatures`` — not an open set.  ``warmup_engine``
walks that lattice through the AOT compile cache (lower + compile /
load, never execute), publishing progress through a :class:`WarmupState`
the HTTP plane surfaces:

- ``/healthz`` answers ``503 {"warming": true}`` while the lattice
  builds, so the fleet router holds the replica in the ``warming``
  state and routes ZERO traffic into the compile storm;
- ``/v1/stats`` carries the state + fill/load counters, which is what
  lets check-compile-cache assert a second process start on the same
  cache dir performs zero new lowerings;
- the decision journal gets one ``warmup`` annotation record (lattice
  size + fill time) so the flight recorder can reconstruct when a
  replica actually became warm.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from ..metrics import WARMUP_SECONDS

log = logging.getLogger("tpu-scheduler")

WARMUP_STATES = ("none", "warming", "ready", "error")


class WarmupState:
    """Mutable warm-up progress, written by the warm-up thread and read
    by HTTP handler threads (GIL-atomic attribute loads, the repo's
    standard cross-thread stance for advisory state)."""

    def __init__(self):
        self.state = "none"
        self.lattice_size = 0
        self.built = 0
        self.fills = 0
        self.loads = 0
        self.errors = 0
        self.wall_s = 0.0
        self.started_at = 0.0
        self.detail = ""

    @property
    def warming(self) -> bool:
        return self.state == "warming"

    def to_dict(self) -> dict:
        return {
            "state": self.state,
            "lattice_size": self.lattice_size,
            "built": self.built,
            "fills": self.fills,
            "loads": self.loads,
            "errors": self.errors,
            "wall_s": round(self.wall_s, 3),
            "detail": self.detail,
        }


def warmup_engine(
    engine,
    state: Optional[WarmupState] = None,
    variants: str = "minimal",
    journal: bool = True,
) -> WarmupState:
    """Pre-lower the engine's shape lattice through its compile cache.

    Per-point failures are counted and skipped, never fatal — a shape
    the warm-up could not build simply compiles on first use, exactly
    as it would have without a warm-up; the pod still becomes Ready.
    Returns the (possibly caller-provided) WarmupState, ``state.state``
    ∈ ready | error (error only when the lattice itself could not be
    enumerated)."""
    st = state if state is not None else WarmupState()
    cache = getattr(engine, "compile_cache", None)
    if cache is None:
        st.state = "ready"
        st.detail = "no compile cache attached; nothing to pre-lower"
        return st
    t0 = time.perf_counter()
    st.state = "warming"
    st.started_at = time.time()
    fills0, loads0 = cache.fills, cache.loads
    try:
        sigs = engine.aot_signatures(variants=variants)
    except Exception as e:  # noqa: BLE001 — a broken lattice must not
        # keep the pod unready forever; surface and serve cold
        st.state = "error"
        st.detail = f"lattice enumeration failed: {e}"[:300]
        log.exception("warm-up: lattice enumeration failed")
        return st
    st.lattice_size = len(sigs)
    for label, fn, args in sigs:
        try:
            fn.build(*args)
            st.built += 1
        except Exception as e:  # noqa: BLE001 — skip, compile on first use
            st.errors += 1
            log.warning("warm-up: %s failed to pre-lower: %s", label, e)
        st.fills = cache.fills - fills0
        st.loads = cache.loads - loads0
        st.wall_s = time.perf_counter() - t0
    st.wall_s = time.perf_counter() - t0
    st.state = "ready"
    st.detail = (
        f"{st.built}/{st.lattice_size} lattice shapes warm "
        f"({st.fills} compiled+persisted, {st.loads} loaded) in "
        f"{st.wall_s:.2f}s"
    )
    WARMUP_SECONDS.set(value=st.wall_s)
    log.info("warm-up: %s", st.detail)
    if journal:
        from ..journal import JOURNAL

        if JOURNAL.enabled:
            JOURNAL.record(
                "warmup",
                lattice_size=st.lattice_size,
                built=st.built,
                fills=st.fills,
                loads=st.loads,
                errors=st.errors,
                wall_s=round(st.wall_s, 3),
                cache_dir=cache.cache_dir or "",
            )
    return st


def start_warmup_thread(
    engine, state: WarmupState, variants: str = "minimal"
) -> threading.Thread:
    """Run ``warmup_engine`` on a daemon thread: the HTTP server is
    already up and answering ``/healthz`` 503 {warming} while the
    lattice builds, which is the whole readiness-gating contract."""
    state.state = "warming"  # visible before the thread's first slice
    t = threading.Thread(
        target=warmup_engine,
        args=(engine, state),
        kwargs={"variants": variants},
        name="compile-warmup",
        daemon=True,
    )
    t.start()
    return t
