"""Router-shard tier: N ``FleetRouter`` instances behind rendezvous
hashing on the prefix-digest chain.

The single fleet router holds two things that must survive scale-out:
the ``PrefixIndex`` affinity map (prefix digest → replica holding the
KV pages) and the SLO journey stream.  Sharding by client or by random
pick would scatter a session's requests across routers and destroy
both.  This ring steers every request by its FIRST page digest — the
root of the prefix chain, identical for every continuation of the same
prefix — so one prefix always lands on one router shard, whose local
affinity map then works exactly as before.

Rendezvous (highest-random-weight) hashing, not a ring of vnodes: the
owner of key *k* is the shard maximizing ``blake2b(shard_name ‖ k)``.
A shard joining or dying re-steers only the keys it wins or held
(~1/n), and every survivor computes ownership independently — no
coordination, no token ring to rebalance.  Journeys still assemble
fleet-wide because every router shard records into the process-global
SLO plane (``/debug/trace/<id>`` answers from any shard).

The routers keep their own ``ReplicaSet``s (each polls the backends
itself): router death then loses nothing but its affinity map, and the
re-steered prefixes warm the new owner's map on first miss — the
bounded hit-rate dip tests/test_fleet.py pins down.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Optional

from ..utils import prefixdigest

__all__ = ["RouterRing", "rendezvous_owner"]


def rendezvous_owner(names: list[str], key: bytes) -> Optional[str]:
    """Highest-random-weight owner of ``key`` among ``names``."""
    best = None
    best_w = b""
    for name in sorted(names):  # sorted: ties broken deterministically
        w = hashlib.blake2b(
            name.encode() + b"\x00" + key, digest_size=8
        ).digest()
        if best is None or w > best_w:
            best, best_w = name, w
    return best


class RouterRing:
    def __init__(self, page_size: int = 4, max_pages: int = 1):
        self.page_size = int(page_size)
        # only the chain ROOT steers (digest[0] is shared by every
        # continuation of the prefix — deeper links would split them)
        self.max_pages = max(1, int(max_pages))
        self._lock = threading.Lock()
        self._routers: dict[str, object] = {}  # name → FleetRouter
        self.steered = 0
        self.unkeyed = 0  # no full page: steered by whole-prompt hash

    # -- membership (join / death re-steer happens implicitly: owners
    # -- are recomputed per request over the CURRENT member set) -------------

    def add_router(self, name: str, router) -> None:
        with self._lock:
            self._routers[name] = router

    def remove_router(self, name: str):
        with self._lock:
            return self._routers.pop(name, None)

    def routers(self) -> dict:
        with self._lock:
            return dict(self._routers)

    # -- steering ------------------------------------------------------------

    def steer_key(self, body: dict) -> bytes:
        """The consistent-hash key for one request: the root link of
        the prefix-digest chain (same derivation as the routers' own
        affinity map — adapter-seeded, page-size aligned), falling back
        to a whole-prompt hash when no full page exists (nothing is
        cacheable, so ANY stable spread works)."""
        prompt = body.get("prompt")
        if not isinstance(prompt, list):
            return b""
        adapter = str(body.get("adapter", ""))
        seed = (
            prefixdigest.prefix_seed(0)
            if not adapter
            else b"adapter:" + adapter.encode()
        )
        try:
            digests = prefixdigest.page_digests(
                prompt, self.page_size, max_pages=self.max_pages, seed=seed,
            )
        except (OverflowError, TypeError, ValueError):
            digests = []
        if digests:
            return digests[0]
        raw = b",".join(str(t).encode() for t in prompt)
        return hashlib.blake2b(raw, digest_size=16).digest()

    def route(self, body: dict) -> tuple[Optional[str], Optional[object]]:
        """(shard name, FleetRouter) owning this request — None/None
        when the ring is empty."""
        with self._lock:
            names = list(self._routers)
        if not names:
            return None, None
        key = self.steer_key(body)
        if not key:
            self.unkeyed += 1
            key = b"\x00"
        owner = rendezvous_owner(names, key)
        self.steered += 1
        with self._lock:
            return owner, self._routers.get(owner)

    # -- introspection -------------------------------------------------------

    def aggregate_affinity(self) -> dict:
        """Fleet-wide affinity hit rate folded across router shards —
        comparable to a single router's ``debug_state()['affinity']``."""
        hits = requests = 0
        per_shard = {}
        for name, router in sorted(self.routers().items()):
            try:
                aff = router.debug_state().get("affinity") or {}
            except Exception:
                aff = {}
            h, r = aff.get("hits", 0), aff.get("requests", 0)
            hits += h
            requests += r
            per_shard[name] = {"hits": h, "requests": r}
        return {
            "hits": hits,
            "requests": requests,
            "hit_rate": (hits / requests) if requests else 0.0,
            "per_shard": per_shard,
        }

    def debug_state(self) -> dict:
        return {
            "routers": sorted(self.routers()),
            "page_size": self.page_size,
            "steered": self.steered,
            "unkeyed": self.unkeyed,
            "affinity": self.aggregate_affinity(),
        }
