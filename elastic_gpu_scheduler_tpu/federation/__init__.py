"""Federated control plane: many scheduler processes, one fleet.

Every plane before this package was exactly one process wide — PR 9
bought a single engine 10k nodes and the HA work (journal shipping,
warm takeover, fenced step-down) only ever protected ONE leader.  This
package partitions the problem the way the capacity index already
buckets it, keeping per-partition decisions exact (the Tesserae
decomposition):

``shard``      — one ``SchedulerShard`` per (region, generation,
                 topology-class) key: its own ``TPUUnitScheduler``,
                 its own ``Journal`` (per-shard stream — the unit the
                 cross-shard conservation audit folds over), kill /
                 revive hooks for chaos harnesses.
``frontdoor``  — the thin federation tier: routes single pods off
                 aggregate ``status_summary`` capacity pulled from
                 every shard, admits CROSS-shard gangs via two-phase
                 admission composed from the split-phase gang
                 primitives (``gang_allocate`` / ``gang_unallocate``),
                 journals each phase as a ``fed_gang`` record, and
                 serves the federated ``GET /scheduler/status?summary=1``
                 fold with per-shard staleness stamps.
``ring``       — the data-plane shard tier: multiple ``FleetRouter``
                 instances behind rendezvous (HRW) hashing on the
                 ``utils/prefixdigest`` chain, so ``PrefixIndex``
                 affinity and the SLO journey stream survive router
                 scale-out with ~1/n re-steer on join/death.
``audit``      — the cross-shard ``fed_gang`` agreement + conservation
                 audit over a directory of per-shard journals (the
                 journal CLI's multi-shard mode calls into this).

Fault sites (``faultinject``): ``fed.prepare`` fires before each
shard's phase-1 reservation, ``fed.commit`` before each commit record —
the chaos gate (tools/check_federation.py) kills shard leaders at both.
"""

from .frontdoor import FederationFrontDoor
from .ring import RouterRing
from .shard import SchedulerShard, shard_key, shard_key_for_entry

__all__ = [
    "FederationFrontDoor",
    "RouterRing",
    "SchedulerShard",
    "shard_key",
    "shard_key_for_entry",
]
