"""Cross-shard journal audit: fold per-shard replays, check fed_gang
agreement.

A single shard's replay can prove its OWN stream is conserved (binds
balance forgets, prepare/commit/abort seals hold locally) but cannot
see the other participants of a federated gang.  This module reads a
directory of per-shard journal directories — the layout a federation
writes (``<root>/<shard>/journal-000000.log``; shard ids with ``/`` in
them flatten into nested subdirectories) — replays each stream
independently, then audits the two-phase transactions ACROSS streams:

  * every shard a transaction declares as a participant must have
    journaled at least one ``fed_gang`` record for it (a silent
    participant means its reservation was never sealed or its journal
    was lost — either way the conservation story has a hole);
  * all participants must reach the SAME terminal phase — one shard
    committing while another aborts is the double-booking/lost-chips
    split-brain the protocol exists to prevent;
  * no transaction may end unresolved (terminal ``prepare``): that is
    a reservation nobody decided, chips pinned until a recovery that
    never ran.

The journal CLI (``python -m elastic_gpu_scheduler_tpu.journal replay
--dir <root>``) calls into this automatically when ``--dir`` holds
shard subdirectories instead of segments.
"""

from __future__ import annotations

import os
from typing import Optional

from ..journal import read_journal, segment_paths
from ..journal.replay import ReplayResult, replay

__all__ = ["audit_federation", "cross_shard_violations", "shard_journal_dirs"]


def shard_journal_dirs(root: str) -> dict[str, str]:
    """Map shard id → journal directory for every subdirectory of
    ``root`` (recursively) that holds journal segments.  Empty when
    ``root`` itself is a plain single-journal directory."""
    out: dict[str, str] = {}
    if not os.path.isdir(root) or segment_paths(root):
        return out
    for dirpath, _dirnames, _filenames in sorted(os.walk(root)):
        if dirpath != root and segment_paths(dirpath):
            out[os.path.relpath(dirpath, root)] = dirpath
    return out


def cross_shard_violations(results: dict[str, ReplayResult]) -> list[str]:
    """The fed_gang agreement audit over already-replayed shard
    streams (keyed by shard id)."""
    out: list[str] = []
    # txn → shard id → this shard's view
    txns: dict[str, dict[str, dict]] = {}
    for sid, res in sorted(results.items()):
        for txn, fg in res.fed_gangs.items():
            txns.setdefault(txn, {})[sid] = fg
    for txn, views in sorted(txns.items()):
        declared: set[str] = set()
        for fg in views.values():
            declared.update(fg.get("shards") or [])
        terminals = {}
        for sid, fg in sorted(views.items()):
            phases = fg.get("phases") or ["?"]
            terminals[sid] = phases[-1]
        kinds = set(terminals.values())
        # a declared participant with NO record only matters when the
        # transaction committed somewhere: commit requires EVERY shard
        # to have sealed a prepare, so silence then means a reservation
        # was never journaled (or the stream was truncated).  Under an
        # abort, silence is the expected shape of a shard whose
        # phase-1 faulted before it reserved anything.
        if "commit" in kinds:
            for sid in sorted(declared):
                if sid in views:
                    continue
                if sid in results:
                    out.append(
                        f"fed_gang {txn}: committed, but declared "
                        f"participant {sid} journaled no record for it "
                        "— its prepare was never sealed (or its stream "
                        "was truncated)"
                    )
                else:
                    out.append(
                        f"fed_gang {txn}: committed, but declared "
                        f"participant {sid} has no journal in the "
                        "audited set — cannot prove conservation"
                    )
        if "prepare" in kinds:
            stuck = sorted(s for s, t in terminals.items() if t == "prepare")
            out.append(
                f"fed_gang {txn}: unresolved on shard(s) {stuck} — "
                "prepared but never committed or aborted"
            )
            kinds.discard("prepare")
        if len(kinds) > 1:
            out.append(
                f"fed_gang {txn}: participants disagree on the outcome "
                f"({terminals}) — all-or-nothing violated across shards"
            )
    return out


def audit_federation(
    root: str, dirs: Optional[dict[str, str]] = None
) -> dict:
    """Replay every shard journal under ``root`` and run the
    cross-shard agreement audit.  Returns per-shard summaries plus the
    combined violation list (per-shard violations prefixed with the
    shard id, then the cross-shard findings)."""
    dirs = dirs if dirs is not None else shard_journal_dirs(root)
    results: dict[str, ReplayResult] = {}
    violations: list[str] = []
    shards: dict[str, dict] = {}
    for sid, path in sorted(dirs.items()):
        res = replay(read_journal(path))
        results[sid] = res
        shards[sid] = res.summary()
        violations.extend(f"[{sid}] {v}" for v in res.violations)
    cross = cross_shard_violations(results)
    violations.extend(cross)
    return {
        "federated": True,
        "shards": shards,
        "fed_gangs": sorted({
            txn
            for res in results.values()
            for txn in res.fed_gangs
        }),
        "cross_shard_violations": cross,
        "violations": violations,
        "results": results,
    }
