"""The federation front door: route pods, admit cross-shard gangs.

One thin tier in front of N ``SchedulerShard``s.  It holds NO chip
state — every placement decision is made by a shard's own exact engine;
the front door only (a) picks WHICH shard off aggregate
``status_summary`` capacity (refreshed out-of-band, served with
per-shard staleness stamps), and (b) coordinates cross-shard gangs as a
two-phase transaction composed from the split-phase gang primitives
the single-process coordinator already uses:

  phase 1 (reserve)   per participating shard, in deterministic shard
                      order: ``gang_allocate`` every local member under
                      the shard's engine lock, then journal a
                      ``fed_gang phase=prepare`` record INSIDE the same
                      hold — the per-shard all-or-nothing seal.
  decision            all shards prepared ⇒ the transaction IS
                      committed (recorded in the coordinator's decision
                      log before any commit record is written); any
                      phase-1 failure ⇒ abort.
  phase 2 (commit)    journal ``fed_gang phase=commit`` on every shard.
                      A shard that dies here resolves FORWARD on
                      revive: its journal shows the prepare, the
                      decision log says commit.
  abort               compensating rollback in REVERSE shard order —
                      ``gang_unallocate`` every reserved member, then
                      journal ``fed_gang phase=abort``.  Dead shards
                      are skipped: their revive presumes abort (the
                      coordinator never commits without every prepare).

Fault sites: ``fed.prepare`` fires before each shard's reservation,
``fed.commit`` before each commit record — tools/check_federation.py
and the check-ha chaos phase kill shard leaders at both.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from ..faultinject import FAULTS
from .shard import SchedulerShard

log = logging.getLogger("tpu-federation")


class FederationFrontDoor:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self.shards: dict[str, SchedulerShard] = {}
        # txn id → "commit" | "abort": the coordinator's decision log.
        # Written BEFORE any commit record, read by shard revive to
        # resolve in-doubt prepares (``SchedulerShard.revive`` defaults
        # to presumed-abort when a txn is missing here).
        self.decisions: dict[str, str] = {}
        self._txn_serial = 0
        self._summaries: dict[str, tuple[dict, float]] = {}
        self._lock = threading.Lock()
        self._server: Optional[ThreadingHTTPServer] = None
        self.routed = 0
        self.route_failures = 0
        self.gangs_admitted = 0
        self.gangs_aborted = 0
        self.wall_clock = time.time  # injectable for tests
        # chaos hook: called as (txn, shard_id) after each shard's
        # phase-1 completes (reservation sealed + ledger annotated).
        # The chaos gates kill a shard leader HERE — the deterministic
        # "died with a journaled prepare" window the recovery paths and
        # the cross-shard audit must survive.  None in production.
        self.on_prepared = None

    # -- membership ----------------------------------------------------------

    def add_shard(self, shard: SchedulerShard) -> None:
        with self._lock:
            self.shards[shard.shard_id] = shard

    def live_shards(self) -> list[SchedulerShard]:
        with self._lock:
            return [s for s in self.shards.values() if not s.dead]

    # -- federated status (the routing signal + the status satellite) --------

    def refresh_summaries(
        self, top_k: int = 10, generations: bool = False
    ) -> dict:
        """Pull ``status_summary`` from every live shard and stamp it.
        A dead shard keeps its LAST summary (with a growing staleness
        stamp) — routing off slightly stale capacity self-corrects at
        bind time; routing off a vanished summary cannot."""
        out = {}
        for sid, shard in sorted(self.shards.items()):
            if shard.dead:
                continue
            try:
                s = shard.status_summary(top_k=top_k, generations=generations)
            except Exception as e:  # a flapping shard must not block the rest
                log.warning("summary pull from shard %s failed: %s", sid, e)
                continue
            with self._lock:
                self._summaries[sid] = (s, self.wall_clock())
            out[sid] = s
        return out

    def federated_summary(self, top_k: int = 10) -> dict:
        """Fold every shard's summary into one response: capacity and
        generation sums, a re-merged top-K fragmented list, summed index
        stats — plus a per-shard staleness stamp so a consumer can see
        exactly how old each slice of the fold is."""
        now = self.wall_clock()
        with self._lock:
            summaries = dict(self._summaries)
            dead = {
                sid for sid, s in self.shards.items() if s.dead
            }
        capacity = {
            "core_total": 0, "core_avail": 0,
            "hbm_total": 0, "hbm_avail": 0, "free_chips": 0,
        }
        generations: dict[str, dict] = {}
        top: list[dict] = []
        index = {"folds": 0, "entries": 0, "buckets": 0}
        have_index = False
        nodes = pods = 0
        stamps = {}
        for sid, (s, at) in sorted(summaries.items()):
            stamps[sid] = {
                "at": at,
                "stale_s": max(0.0, now - at),
                "dead": sid in dead,
            }
            nodes += s.get("nodes", 0)
            pods += s.get("pods", 0)
            for k in capacity:
                capacity[k] += (s.get("capacity") or {}).get(k, 0)
            for gen, g in (s.get("generations") or {}).items():
                agg = generations.setdefault(
                    gen, {"nodes": 0, "free_chips": 0, "free_core": 0}
                )
                for k in agg:
                    agg[k] += g.get(k, 0)
            for entry in s.get("top_fragmented") or []:
                top.append({**entry, "shard": sid})
            idx = s.get("index")
            if idx:
                have_index = True
                index["folds"] += idx.get("folds", 0)
                index["entries"] += idx.get("entries", 0)
                index["buckets"] += idx.get("buckets", 0)
        top.sort(
            key=lambda e: (-e.get("fragmentation_index", 0.0),
                           e.get("node", ""))
        )
        out = {
            "federated": True,
            "summary": True,
            "shards": stamps,
            "nodes": nodes,
            "pods": pods,
            "capacity": capacity,
            "generations": generations,
            "top_fragmented": top[:top_k],
        }
        if have_index:
            out["index"] = index
        return out

    # -- single-pod routing --------------------------------------------------

    def _shard_order(self, generation: Optional[str]) -> list[str]:
        """Shards by descending free core from the stamped summaries
        (capacity-aware routing); a generation hint filters to shards
        whose summary shows free chips of that generation."""
        with self._lock:
            summaries = dict(self._summaries)
        scored = []
        for sid, (s, _at) in summaries.items():
            shard = self.shards.get(sid)
            if shard is None or shard.dead:
                continue
            if generation is not None:
                g = (s.get("generations") or {}).get(generation)
                if not g or g.get("free_chips", 0) <= 0:
                    continue
            scored.append(
                (-(s.get("capacity") or {}).get("core_avail", 0), sid)
            )
        return [sid for _neg, sid in sorted(scored)]

    def route_pod(
        self,
        pod,
        candidates: Optional[list[str]] = None,
        generation: Optional[str] = None,
        max_candidates: int = 32,
    ) -> dict:
        """Pick a shard off aggregate capacity, then run the normal
        assume → score → bind verbs against that shard's exact engine.
        Capacity summaries are stamped, not fresh — a shard that looks
        free but fills up mid-route simply fails filter and the next
        shard in capacity order is tried (stale routing self-corrects
        at bind time, never double-books: only engines commit)."""
        if not self._summaries:
            self.refresh_summaries()
        order = self._shard_order(generation)
        tried = []
        for sid in order:
            shard = self.shards[sid]
            names = candidates or shard.node_names
            if not names:
                continue
            names = names[:max_candidates] if max_candidates else names
            tried.append(sid)
            fit, errors = shard.engine.assume(names, pod)
            if not fit:
                continue
            scores = shard.engine.score(fit, pod)
            node = max(zip(scores, fit))[1]
            try:
                shard.engine.bind(node, pod)
            except Exception as e:
                log.info("route %s: bind on %s/%s failed: %s",
                         pod.key, sid, node, e)
                continue
            self.routed += 1
            return {"ok": True, "shard": sid, "node": node}
        self.route_failures += 1
        return {
            "ok": False, "shard": None, "node": None,
            "error": f"no shard admitted {pod.key} "
                     f"(tried {tried or 'none — no capacity summaries'})",
        }

    # -- cross-shard gangs: two-phase admission ------------------------------

    def admit_gang(
        self,
        gang_key: str,
        members: list[tuple[str, str, object]],
        size: Optional[int] = None,
    ) -> dict:
        """``members``: (shard_id, node_name, pod) per gang member.
        All-or-nothing across shards: every shard reserves (phase 1) or
        every reservation is compensated in reverse order."""
        by_shard: dict[str, list[tuple[str, object]]] = {}
        for sid, node, pod in members:
            by_shard.setdefault(sid, []).append((node, pod))
        shard_order = sorted(by_shard)
        with self._lock:
            self._txn_serial += 1
            txn = f"{gang_key}#{self._txn_serial}"
        size = size if size is not None else len(members)
        prepared: list[tuple[SchedulerShard, str, list]] = []
        try:
            # phase 1: reserve on every shard, deterministic order
            for sid in shard_order:
                shard = self.shards.get(sid)
                if shard is None or shard.dead:
                    raise RuntimeError(f"shard {sid} is unavailable")
                FAULTS.maybe_fire("fed.prepare")
                local = by_shard[sid]
                allocated: list = []
                with shard.engine.lock:
                    try:
                        for node, pod in local:
                            opt = shard.engine.gang_allocate(
                                node, pod, source="fed_gang"
                            )
                            allocated.append((pod, node, opt))
                        if shard.JOURNAL.enabled:
                            # the per-shard seal, inside the same lock
                            # hold as the members' bind records (the
                            # gang_admit discipline)
                            shard.JOURNAL.record(
                                "fed_gang", phase="prepare", txn=txn,
                                gang=gang_key, size=size,
                                members=[p.key for _n, p in local],
                                shards=shard_order, shard=sid,
                            )
                    except Exception:
                        # partial LOCAL reservation: free inside this
                        # hold so no other verb ever sees it
                        for pod, node, opt in reversed(allocated):
                            shard.engine.gang_unallocate(
                                node, pod, opt, source="fed_gang_rollback"
                            )
                        raise
                prepared.append((shard, sid, allocated))
                # 2PC correctness: the prepare is only a prepare once it
                # is DURABLE — a leader killed after acking phase 1 must
                # find the sealed reservation in its journal on revive,
                # or recovery has nothing to resolve while the ledger
                # annotations below quietly re-charge the members
                if shard.JOURNAL.enabled and not shard.JOURNAL.flush():
                    raise RuntimeError(
                        f"shard {sid}: prepare for {txn} never became "
                        "durable"
                    )
                # ledger writes complete phase 1: a revived shard's cold
                # rebuild re-charges exactly the members annotated here,
                # so commit-recovery finds them live and abort-recovery
                # has something to strip.  A failure aborts the whole
                # transaction (the decision is only made after EVERY
                # shard both reserved and annotated).
                for pod, node, opt in allocated:
                    shard.engine.gang_annotate(pod, opt, node)
                if self.on_prepared is not None:
                    self.on_prepared(txn, sid)
        except Exception as e:
            self.decisions[txn] = "abort"
            self._compensate(txn, gang_key, prepared, str(e))
            self.gangs_aborted += 1
            return {
                "ok": False, "txn": txn, "gang": gang_key,
                "shards": shard_order, "error": str(e) or repr(e),
            }

        # decision point: every shard holds its reservation — the
        # transaction is committed BEFORE any commit record is written,
        # so a shard that dies mid-phase-2 resolves forward on revive
        self.decisions[txn] = "commit"
        unresolved = []
        for shard, sid, allocated in prepared:
            try:
                FAULTS.maybe_fire("fed.commit")
                if shard.dead:
                    raise RuntimeError(f"shard {sid} died before commit")
                with shard.engine.lock:
                    if shard.JOURNAL.enabled:
                        shard.JOURNAL.record(
                            "fed_gang", phase="commit", txn=txn,
                            gang=gang_key,
                            members=[p.key for p, _n, _o in allocated],
                            shards=shard_order, shard=sid,
                        )
            except Exception as e:
                # the decision stands — this shard's journal shows an
                # unresolved prepare until its revive reads the
                # decision log and journals the commit
                log.warning("fed_gang %s: commit record on shard %s "
                            "deferred to recovery: %s", txn, sid, e)
                unresolved.append(sid)
        self.gangs_admitted += 1
        out = {
            "ok": True, "txn": txn, "gang": gang_key,
            "shards": shard_order,
        }
        if unresolved:
            out["unresolved"] = unresolved
        return out

    def _compensate(
        self, txn: str, gang_key: str, prepared: list, reason: str
    ) -> None:
        """Reverse-order compensating rollback of every reserved shard.
        Dead shards are skipped — their journals keep the unresolved
        prepare and revive presumes abort from the decision log."""
        for shard, sid, allocated in reversed(prepared):
            if shard.dead:
                continue
            for pod, _node, _opt in allocated:
                try:
                    shard.engine.gang_strip_annotations(pod)
                except Exception as e:  # best-effort; resync catches it
                    log.warning("fed_gang %s: strip %s on %s failed: %s",
                                txn, pod.key, sid, e)
            with shard.engine.lock:
                for pod, node, opt in reversed(allocated):
                    shard.engine.gang_unallocate(
                        node, pod, opt, source="fed_gang_rollback"
                    )
                if shard.JOURNAL.enabled:
                    shard.JOURNAL.record(
                        "fed_gang", phase="abort", txn=txn,
                        gang=gang_key,
                        members=[p.key for p, _n, _o in allocated],
                        shards=sorted(self.shards), shard=sid,
                        reason=(reason or "")[:200],
                    )

    # -- introspection -------------------------------------------------------

    def debug_state(self) -> dict:
        with self._lock:
            stamps = {
                sid: {"at": at, "stale_s": max(0.0, self.wall_clock() - at)}
                for sid, (_s, at) in sorted(self._summaries.items())
            }
        return {
            "shards": {
                sid: s.debug_state()
                for sid, s in sorted(self.shards.items())
            },
            "summaries": stamps,
            "decisions": dict(self.decisions),
            "routed": self.routed,
            "route_failures": self.route_failures,
            "gangs_admitted": self.gangs_admitted,
            "gangs_aborted": self.gangs_aborted,
        }

    # -- HTTP (the status-aggregation satellite) -----------------------------

    def start(self) -> int:
        handler = _make_handler(self)
        self._server = ThreadingHTTPServer((self.host, self.port), handler)
        self.port = self._server.server_address[1]
        t = threading.Thread(
            target=self._server.serve_forever, name="fed-frontdoor",
            daemon=True,
        )
        t.start()
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


def _make_handler(fd: FederationFrontDoor):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):  # quiet
            pass

        def _json(self, code: int, obj) -> None:
            raw = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

        def do_GET(self):  # noqa: N802 (stdlib handler name)
            u = urlparse(self.path)
            q = parse_qs(u.query)
            if u.path == "/healthz":
                self._json(200, {"ok": True, "role": "fed-frontdoor"})
                return
            if u.path == "/scheduler/status":
                top_k = int(q.get("top_k", ["10"])[0])
                if q.get("summary", ["0"])[0] in ("1", "true"):
                    fd.refresh_summaries(
                        top_k=top_k,
                        generations=q.get("generations", ["0"])[0]
                        in ("1", "true"),
                    )
                    self._json(200, fd.federated_summary(top_k=top_k))
                else:
                    self._json(200, {
                        "schedulers": [
                            s.engine.status()
                            for s in fd.live_shards()
                        ],
                    })
                return
            if u.path == "/debug/federation":
                self._json(200, fd.debug_state())
                return
            self._json(404, {"error": f"no route {u.path}"})

    return Handler
