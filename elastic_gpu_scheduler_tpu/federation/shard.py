"""One federation shard: a whole single-leader control plane, scoped to
one (region, generation, topology-class) slice of the fleet.

A shard is deliberately NOT a new kind of scheduler — it is the same
``TPUUnitScheduler`` every pre-federation deployment runs, with two
bindings swapped: its own ``Journal`` instance (via
``SchedulerConfig.journal``) so every mutation lands in a per-shard
segment directory, and its own clientset over the shard's node slice.
PR 13's standby machinery composes unchanged: a follower pointed at a
shard's ``/journal/stream`` ships THIS journal, and warm takeover swaps
state into THIS engine — one standby chain per shard.

``kill()`` / ``revive()`` are the chaos-harness surface: ``kill``
aborts the journal writer mid-write (the kill -9 torn tail) and marks
the shard dead; ``revive`` repairs + reopens the journal, cold-rebuilds
a fresh engine from the annotation ledger, and resolves any in-doubt
``fed_gang`` reservation the dead leader left behind — compensating
rollback (presumed abort) unless the front door's decision log says the
transaction committed.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional, Union

from ..journal import Journal, read_journal
from ..journal.replay import replay
from ..scheduler.scheduler import SchedulerConfig, TPUUnitScheduler

log = logging.getLogger("tpu-federation")


def shard_key(region: str, generation: str, topo_class: str) -> str:
    """The shard id: the (region, generation, topology-class) triple the
    capacity index already buckets by, flattened to one routable name."""
    return f"{region}/{generation}/{topo_class}"


def shard_key_for_entry(region: str, entry) -> str:
    """Shard id for a live ``core.index.IndexEntry`` — generation and
    topology class come from the entry itself (via the index's own
    ``topo_class`` derivation), so placement buckets and shard
    ownership stay in lockstep."""
    from ..core.index import topo_class

    return shard_key(region, entry.generation, topo_class(entry.topo_key))


class SchedulerShard:
    def __init__(
        self,
        shard_id: str,
        clientset,
        journal_dir: str,
        node_names: Optional[list[str]] = None,
        priority: str = "binpack",
        fsync: str = "off",
        max_segment_bytes: int = 64 << 20,
        placement_index: bool = True,
    ):
        from ..policy import resolve_rater

        self.shard_id = shard_id
        self.clientset = clientset
        self.journal_dir = journal_dir
        # candidate set the front door filters over (the shard's node
        # slice; allocators materialize lazily on first assume/bind)
        self.node_names: list[str] = list(node_names or [])
        self.rater = resolve_rater(priority)
        self._fsync = fsync
        self._max_segment_bytes = int(max_segment_bytes)
        self._placement_index = bool(placement_index)
        self.dead = False
        self.kills = 0
        self.JOURNAL = Journal()
        self.JOURNAL.configure(
            journal_dir, fsync=fsync, max_segment_bytes=max_segment_bytes
        )
        self.engine = self._build_engine()

    def _build_engine(self) -> TPUUnitScheduler:
        config = SchedulerConfig(
            clientset=self.clientset,
            rater=self.rater,
            placement_index=self._placement_index,
            journal=self.JOURNAL,
        )
        return TPUUnitScheduler(config, name=f"tpushare/{self.shard_id}")

    def warm(self) -> int:
        """Materialize an allocator for every node in the shard's slice
        (they otherwise build lazily on first assume/bind).  The front
        door routes off ``status_summary`` capacity, and a cold shard
        summarizes to zero nodes — harnesses and servers warm at boot
        so the first summary already shows the real slice.  Returns the
        number of live allocators."""
        for name in self.node_names:
            self.engine._get_allocator(name)
        with self.engine.lock:
            return len(self.engine.allocators)

    # -- summaries (what the front door routes off) --------------------------

    def status_summary(self, top_k: int = 10, generations: bool = False) -> dict:
        s = self.engine.status_summary(top_k=top_k, generations=generations)
        s["shard"] = self.shard_id
        return s

    # -- chaos surface -------------------------------------------------------

    def kill(self) -> None:
        """Shard-leader death: the journal writer dies mid-write (torn
        tail on disk, exactly what kill -9 leaves) and the shard stops
        answering.  In-memory engine state is abandoned — a dead
        leader's memory is gone; only its journal and the annotation
        ledger survive."""
        self.kills += 1
        self.dead = True
        self.JOURNAL.abort()

    def revive(
        self,
        decisions: Union[dict, Callable[[str], Optional[str]], None] = None,
    ) -> dict:
        """Bring a killed shard back: repair + reopen the journal
        (sequence numbering resumes after the truncated tear),
        cold-rebuild a fresh engine from the annotation ledger, then
        resolve every in-doubt ``fed_gang`` the dead leader left
        prepared-but-undecided.  ``decisions`` maps txn id → "commit" /
        "abort" (the front door's decision log, or a callable); unknown
        transactions are presumed aborted — the coordinator only
        commits after EVERY shard prepared, so an unresolved prepare
        with no recorded decision cannot have committed anywhere."""
        self.JOURNAL.configure(
            self.journal_dir, fsync=self._fsync,
            max_segment_bytes=self._max_segment_bytes,
        )
        # cold rebuild re-charges whatever the dead leader had annotated
        # (journals node_add + bind(source=replay) into the reopened
        # stream — the same records a restarting single leader writes),
        # then the slice re-warms so summaries report full capacity
        self.engine = self._build_engine()
        self.warm()
        self.dead = False
        return self.resolve_in_doubt(decisions)

    def resolve_in_doubt(
        self,
        decisions: Union[dict, Callable[[str], Optional[str]], None] = None,
    ) -> dict:
        """Terminate every ``fed_gang`` txn whose last local phase is
        still ``prepare``: journal a ``commit`` (decision says the fleet
        committed — the rebuilt members stay) or compensate — free any
        rebuilt member via ``gang_unallocate``, strip its ledger entry,
        and journal the ``abort``.  Idempotent: a resolved txn has a
        terminal record and is skipped on the next call."""
        if not self.JOURNAL.flush():
            log.warning("shard %s: journal flush before in-doubt scan "
                        "failed", self.shard_id)
        res = replay(read_journal(self.journal_dir))
        decide = (
            decisions if callable(decisions)
            else (decisions or {}).get
        )
        resolved = {"committed": [], "aborted": []}
        for txn, fg in sorted(res.fed_gangs.items()):
            phases = fg.get("phases") or []
            if not phases or phases[-1] != "prepare":
                continue
            decision = decide(txn) or "abort"
            members = list(fg.get("members") or [])
            if decision == "commit":
                with self.engine.lock:
                    self.JOURNAL.record(
                        "fed_gang", phase="commit", txn=txn,
                        gang=fg.get("gang"), members=members,
                        shards=fg.get("shards") or [],
                        shard=self.shard_id, recovered=True,
                    )
                resolved["committed"].append(txn)
                continue
            # compensating rollback, reverse reservation order.  Two
            # shapes per member: rebuilt from its ledger annotation
            # (free the live charge — gang_unallocate journals the
            # balancing forget) or journal-only (the leader died after
            # sealing the prepare but before the annotation landed, so
            # the rebuild found nothing — journal a bare forget so the
            # STREAM balances; there is no memory to free).
            for key in reversed(members):
                entry = self.engine.pod_maps.get(key)
                if entry is not None:
                    node, opt = entry
                    ns, _, name = key.partition("/")
                    try:
                        pod = self.clientset.get_pod(ns, name)
                    except Exception:
                        continue
                    self.engine.gang_unallocate(
                        node, pod, opt, source="fed_gang_recovery"
                    )
                    try:
                        self.engine.gang_strip_annotations(pod)
                    except Exception as e:  # best-effort; resync wins
                        log.warning("shard %s: strip %s failed: %s",
                                    self.shard_id, key, e)
                elif key in res.pods:
                    lp = res.pods[key]
                    self.JOURNAL.record(
                        "forget", pod=key, uid=lp.uid, node=lp.node,
                        option=None, gang=lp.gang,
                        source="fed_gang_recovery",
                    )
            with self.engine.lock:
                self.JOURNAL.record(
                    "fed_gang", phase="abort", txn=txn,
                    gang=fg.get("gang"), members=members,
                    shards=fg.get("shards") or [],
                    shard=self.shard_id, recovered=True,
                    reason="in-doubt recovery: presumed abort",
                )
            resolved["aborted"].append(txn)
        # seal the terminal records: recovery isn't done until the
        # commit/abort outcomes are on disk (an auditor reading the
        # segments must never see the in-doubt state we just resolved)
        if (resolved["committed"] or resolved["aborted"]) and \
                not self.JOURNAL.flush():
            log.warning("shard %s: journal flush after in-doubt "
                        "resolution failed", self.shard_id)
        return resolved

    def debug_state(self) -> dict:
        return {
            "shard": self.shard_id,
            "dead": self.dead,
            "kills": self.kills,
            "nodes": len(self.node_names),
            "journal_dir": self.journal_dir,
            "last_seq": self.JOURNAL.last_seq(),
        }
