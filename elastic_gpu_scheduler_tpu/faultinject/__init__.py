"""Deterministic fault-injection plane (process-global ``FAULTS``).

None of the control plane's crash paths — leader death mid-gang-commit,
a journal writer's torn tail under kill -9, the router losing a replica
mid-stream, an apiserver flap — were exercised by INJECTED faults before
this module; they were only covered where a test happened to simulate
them by hand.  ``FAULTS`` is the TRACER/JOURNAL-pattern singleton that
fixes that: code threads named **sites** through its I/O edges, a test
(or the chaos gate, tools/check_ha.py) loads a seeded **plan**, and the
same failure schedule replays exactly on every run.

Sites (``FAULTS.maybe_fire(site)`` — one attribute check when off):

    k8s.request        RestClientset._req (every real apiserver call)
    k8s.update_pod     FakeClientset.update_pod (the annotation ledger)
    k8s.bind           FakeClientset.bind (the Binding subresource)
    k8s.list_pods      FakeClientset.list_pods (resync / rebuild reads)
    lease.acquire      LeaderElector._try_acquire (lease get/create/CAS)
    lease.renew        LeaderElector._renew
    journal.write      journal writer thread, per record written
    journal.fsync      journal writer thread, per fsync
    gang.phase2        gang commit, between the phase-1 seal and the
                       first annotation write (the mid-commit kill point)
    router.connect     FleetRouter._forward backend connect
    router.probe       ReplicaSet._http_get health/stats probe
    ship.stream        /journal/stream handler, per request (leader side)
    ship.follow        JournalFollower, per poll (follower side)
    serve.request      inference /v1/completions handler, before
                       admission (the SLO plane's latency-injection
                       point: a ``delay`` plan here degrades TTFT/e2e
                       without failing anything — check-slo's fault)
    fed.prepare        federation front door, before each shard's
                       phase-1 gang reservation (an ``error`` here
                       aborts the cross-shard transaction and drives
                       the compensating rollback path)
    fed.commit         federation front door, before each shard's
                       phase-2 commit record (a fault here leaves the
                       shard in-doubt — resolved forward from the
                       decision log on revive)

Kinds:

    error       raise ``InjectedFault`` (an ``OSError`` — existing
                failure handling treats it like a real I/O error)
    timeout     sleep ``delay_s`` then raise ``InjectedTimeout``
                (a ``TimeoutError``)
    delay       sleep ``delay_s`` and RETURN — pure added latency, no
                failure (SLO-breach drills: the request succeeds, just
                slower)
    partition   raise ``InjectedPartition`` (a ``ConnectionError``) —
                the socket-level look of a network partition
    torn-write  no raise: ``maybe_fire`` RETURNS the plan and the call
                site implements the tear (the journal writer emits a
                partial record then fails the batch — byte-for-byte what
                kill -9 mid-write leaves on disk)
    crash       ``os._exit(137)`` — the process dies as if SIGKILLed.
                Only subprocess-driven tests/gates use this kind.

A plan is a small dict (JSON over CLI ``--fault-plan``, env
``TPU_FAULT_PLAN``, or ``POST /faults/load``)::

    {"site": "lease.renew", "kind": "error",
     "p": 0.05,        # per-call probability (seeded RNG), and/or
     "nth": 12,        # fire on the 12th call at the site (1-based)
     "count": 1,       # max fires (default unlimited)
     "delay_s": 0.05}  # timeout kind: how long the hang lasts

Determinism: every plan draws from ONE seeded ``random.Random`` (the
registry's ``seed``), and per-site call counters are exact — the same
plan + the same call sequence fires the same faults.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

__all__ = [
    "FAULTS",
    "FaultPlan",
    "FaultRegistry",
    "InjectedFault",
    "InjectedPartition",
    "InjectedTimeout",
    "KINDS",
]

KINDS = ("error", "timeout", "delay", "partition", "torn-write", "crash")


class InjectedFault(OSError):
    """A fault-plane 'error' firing.  OSError: every I/O edge with a
    site already handles the OSError family."""


class InjectedTimeout(TimeoutError):
    """A fault-plane 'timeout' firing (TimeoutError ⊂ OSError)."""


class InjectedPartition(ConnectionError):
    """A fault-plane 'partition' firing (ConnectionError ⊂ OSError)."""


class FaultPlan:
    def __init__(
        self,
        site: str,
        kind: str,
        p: float = 0.0,
        nth: int = 0,
        count: int = 0,
        delay_s: float = 0.05,
    ):
        if kind not in KINDS:
            raise ValueError(f"fault kind {kind!r} not in {KINDS}")
        if not site:
            raise ValueError("fault plan needs a site")
        if p <= 0.0 and nth <= 0:
            raise ValueError(
                f"fault plan for {site!r} needs p > 0 and/or nth > 0"
            )
        self.site = site
        self.kind = kind
        self.p = min(max(float(p), 0.0), 1.0)
        self.nth = int(nth)
        self.count = int(count)  # 0 = unlimited
        self.delay_s = max(0.0, float(delay_s))
        self.fired = 0

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        if not isinstance(d, dict):
            # a plans list containing strings/numbers must be a
            # structured client error, never an AttributeError-500
            raise ValueError(
                f"fault plan entry must be an object, got {type(d).__name__}"
            )
        return cls(
            site=str(d.get("site", "")),
            kind=str(d.get("kind", "error")),
            p=float(d.get("p", 0.0)),
            nth=int(d.get("nth", 0)),
            count=int(d.get("count", 0)),
            delay_s=float(d.get("delay_s", 0.05)),
        )

    def to_dict(self) -> dict:
        return {
            "site": self.site, "kind": self.kind, "p": self.p,
            "nth": self.nth, "count": self.count, "delay_s": self.delay_s,
            "fired": self.fired,
        }


class FaultRegistry:
    """Process-global fault registry.  ``enabled`` is False until a plan
    loads; every site guards with ``if FAULTS.enabled:`` first, so the
    production cost is one attribute load per site."""

    def __init__(self):
        self.enabled = False
        self._lock = threading.Lock()
        self._plans: dict[str, list[FaultPlan]] = {}  # site → plans
        self._calls: dict[str, int] = {}  # site → call count (1-based)
        self._fires: dict[str, int] = {}  # site → fires
        self.seed = 0
        self._rng = None  # seeded random.Random while enabled

    # -- configuration -------------------------------------------------------

    def configure(self, plans: list, seed: int = 0) -> None:
        """Replace ALL plans (empty list disables).  ``plans`` entries
        are FaultPlan objects or plain dicts."""
        import random

        parsed = [
            p if isinstance(p, FaultPlan) else FaultPlan.from_dict(p)
            for p in plans
        ]
        with self._lock:
            self._plans = {}
            for p in parsed:
                self._plans.setdefault(p.site, []).append(p)
            self._calls = {}
            self._fires = {}
            self.seed = int(seed)
            self._rng = random.Random(self.seed)
            self.enabled = bool(self._plans)

    def configure_from_env(self) -> bool:
        """Load ``TPU_FAULT_PLAN`` (JSON: a plan list, or
        {"seed": N, "plans": [...]}); returns True when a plan loaded."""
        raw = os.environ.get("TPU_FAULT_PLAN", "")
        if not raw:
            return False
        self.load_json(raw)
        return self.enabled

    def load_json(self, raw: str) -> None:
        spec = json.loads(raw)
        try:
            if isinstance(spec, list):
                self.configure(spec)
            elif isinstance(spec, dict):
                plans = spec.get("plans") or []
                if not isinstance(plans, list):
                    raise ValueError('"plans" must be a list')
                self.configure(plans, seed=int(spec.get("seed", 0)))
            else:
                raise ValueError(
                    "fault plan JSON must be a list or an object"
                )
        except (TypeError, AttributeError) as e:
            # wrong-typed FIELDS inside otherwise-valid JSON ({"p": []},
            # a string where a plan object belongs): one error type for
            # callers (the HTTP route answers 400, the CLI exits 2)
            raise ValueError(f"malformed fault plan: {e}") from None

    def clear(self) -> None:
        self.configure([])

    # -- the site hook -------------------------------------------------------

    def maybe_fire(self, site: str):
        """Called at a fault site.  Returns None (no fault) or the
        FaultPlan of a fired ``torn-write`` (the caller implements the
        tear); other kinds raise/exit and never return."""
        if not self.enabled:
            return None
        with self._lock:
            plans = self._plans.get(site)
            if not plans:
                return None
            n = self._calls.get(site, 0) + 1
            self._calls[site] = n
            firing = None
            for p in plans:
                if p.count and p.fired >= p.count:
                    continue
                if (p.nth and n == p.nth) or (
                    p.p and self._rng.random() < p.p
                ):
                    p.fired += 1
                    self._fires[site] = self._fires.get(site, 0) + 1
                    firing = p
                    break
            if firing is None:
                return None
            kind = firing.kind
            delay = firing.delay_s
        # act OUTSIDE the lock: a timeout's sleep (or a crash) must not
        # hold the registry against every other site
        if kind == "error":
            raise InjectedFault(f"injected fault at {site}")
        if kind == "timeout":
            import time

            time.sleep(delay)
            raise InjectedTimeout(f"injected timeout at {site}")
        if kind == "delay":
            import time

            time.sleep(delay)
            return None  # pure latency: the call proceeds normally
        if kind == "partition":
            raise InjectedPartition(f"injected partition at {site}")
        if kind == "crash":
            os._exit(137)
        return firing  # torn-write: the site implements the tear

    # -- introspection (/debug/faults) ---------------------------------------

    def debug_state(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "seed": self.seed,
                "plans": [
                    p.to_dict()
                    for plans in self._plans.values()
                    for p in plans
                ],
                "calls": dict(self._calls),
                "fires": dict(self._fires),
            }


# Process-global instance (TRACER/JOURNAL/PROFILER pattern): sites import
# this and check .enabled first.
FAULTS = FaultRegistry()

# one env probe at import so subprocess-driven chaos (tools/check_ha.py
# spawning a leader with TPU_FAULT_PLAN set) needs no plumbing
try:
    FAULTS.configure_from_env()
except (ValueError, json.JSONDecodeError):  # a bad env plan must not
    pass  # poison every import — the CLI surfaces the parse error
