"""Profile-aware placement scoring: measured behavior over pure geometry.

The in-tree raters (core/rater.py) score geometry — ICI locality,
packing, spread.  ROADMAP item 2 wants dispatch that also weighs
*measured* workload behavior: place each class on the TPU generation
where its tokens/s/chip is highest (Gavel's heterogeneity-aware tables)
and keep classes that measurably slow each other down off shared chips
(BandPilot's contention signal).  :class:`ProfileAwareRater` is the
reference consumer of the profile observatory's output — and, run
through ``journal.replay.what_if``, the proof that the flight recorder
doubles as the offline promotion harness: recorded workload re-scored
under recorded profiles, no live cluster touched.

``what_if`` drives the two extension hooks:

- ``observe_profile(rec)`` — called for every ``profile`` journal record
  in stream order, so scores use the profiles as they stood at that
  point of the recording;
- ``set_workload(wclass, node=, generation=)`` — called before each
  re-placed bind with the recorded pod's workload class and the target
  node's TPU generation (from the ``node_add`` record).

Both hooks are duck-typed: raters without them replay exactly as before.
"""

from __future__ import annotations

from typing import Optional

from ..core.allocator import ChipSet, Option, Rater
from ..core.rater import ICILocality
from ..utils.consts import DEFAULT_WORKLOAD_CLASS


class ProfileAwareRater(Rater):
    """Wrap a geometry rater; scale its score by measured per-class
    throughput on the target generation and by the class' worst measured
    interference ratio when the placement shares chips.

    Scoring stays bounded in the base rater's [0, 100] range:

        score = base * (0.5 + 0.5 * tput_factor * interference_factor)

    - ``tput_factor``: this class' EWMA tokens/s/chip on the target
      node's generation, normalized by its best generation (1.0 on the
      best-measured hardware, lower elsewhere; 1.0 when unprofiled).
    - ``interference_factor``: when any fractional alloc lands on a
      chip that already has tenants, the class' WORST measured
      co-location ratio (floored at 0.1); 1.0 for exclusive placements
      or unprofiled classes.

    Neither planner shortcut applies (scores depend on per-node
    generation and live chip occupancy), so both opt-out flags stay
    False — same stance as the Random rater.
    """

    name = "profile-aware"
    translation_invariant = False
    whole_chip_compact_first = False

    def __init__(self, base: Optional[Rater] = None):
        self.base = base or ICILocality()
        # class → {generation: tokens/s/chip}
        self.tput: dict[str, dict[str, float]] = {}
        # class → {neighbor class: co/solo ratio}
        self.interference: dict[str, dict[str, float]] = {}
        self._wclass = DEFAULT_WORKLOAD_CLASS
        self._generation = "unknown"
        self.profiles_seen = 0

    # -- what_if hooks -------------------------------------------------------

    def observe_profile(self, rec: dict) -> None:
        """Ingest one journal ``profile`` record (latest wins per key —
        the stream is time-ordered)."""
        for cls, p in (rec.get("profiles") or {}).items():
            row = self.tput.setdefault(cls, {})
            for gen, tps in (p.get("tput") or {}).items():
                row[gen] = float(tps)
        for cls, pairs in (rec.get("interference") or {}).items():
            row = self.interference.setdefault(cls, {})
            for ncls, ratio in pairs.items():
                row[ncls] = float(ratio)
        self.profiles_seen += 1

    def set_workload(
        self,
        wclass: Optional[str],
        node: Optional[str] = None,
        generation: Optional[str] = None,
    ) -> None:
        self._wclass = wclass or DEFAULT_WORKLOAD_CLASS
        self._generation = generation or "unknown"

    # -- scoring -------------------------------------------------------------

    def _tput_factor(self) -> float:
        row = self.tput.get(self._wclass)
        if not row:
            return 1.0
        best = max(row.values())
        if best <= 0:
            return 1.0
        here = row.get(self._generation)
        if here is None:
            # unmeasured generation: mildly below the best-known one, so
            # measured-good hardware wins ties without zeroing the rest
            return 0.75
        return max(0.0, min(1.0, here / best))

    def _interference_factor(self, chips: ChipSet, option: Option) -> float:
        row = self.interference.get(self._wclass)
        if not row:
            return 1.0
        shares = False
        for a in option.allocs:
            if a.whole or not a.needs_tpu:
                continue
            for c in a.coords:
                ch = chips.chips[c]
                # rate() sees post-assignment state: the chip had other
                # tenants iff its pre-assignment usage was non-zero
                before_avail = ch.core_avail + a.core
                if before_avail < ch.core_total:
                    shares = True
                    break
            if shares:
                break
        if not shares:
            return 1.0
        # the ChipSet does not expose NEIGHBOR classes, so be
        # conservative: assume the worst measured pairing for this class
        return max(0.1, min(1.0, min(row.values())))

    def rate(self, chips: ChipSet, option: Option) -> float:
        base = self.base.rate(chips, option)
        factor = self._tput_factor() * self._interference_factor(
            chips, option
        )
        return base * (0.5 + 0.5 * factor)
