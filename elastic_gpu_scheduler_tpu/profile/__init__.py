"""Workload profiling & interference observatory.

The observability plane so far sees *decisions* (spans in ``tracing/``,
the durable journal in ``journal/``) but not *behavior*: nothing
measures what a workload actually achieves on its chips.  ROADMAP item 2
(contention- and heterogeneity-aware dispatch) needs exactly that signal
— BandPilot-style contention-aware dispatch consumes measured per-class
throughput and co-location slowdown, and Gavel's heterogeneity-aware
policies are built on per-workload throughput-per-accelerator-type
tables (PAPERS.md).  This module is the telemetry layer that produces
both:

- **Sample collection** (hot path = one list append, like the
  TimedLock wait buffers): the serving engine's step loop emits
  per-step samples (tokens, step wall, batch slot occupancy, host gap,
  queue depth, KV-page footprint) via :meth:`WorkloadProfiler.record_step`;
  the device plugin emits per-chip occupancy samples at Allocate via
  :meth:`WorkloadProfiler.record_chip`.  Collection is sampling-knob
  gated (``--profile-sample`` / ``TPU_PROFILE_SAMPLE``, same stance as
  ``--trace-sample``) and NOTHING here ever touches the device or the
  bind path: aggregation happens lazily when a reader (scrape,
  ``/debug/profiles``, the journal flush) folds the raw buffers.

- **Profile aggregation**: samples roll up into per-workload-class
  profiles — EWMA tokens/s/chip keyed by TPU generation (the Gavel
  table), reservoir-sampled step-latency quantiles, occupancy/host-gap/
  queue-depth means — keyed by the ``elasticgpu.io/workload-class`` pod
  annotation (default class ``default``).  For fractional ``tpushare``
  tenants sharing a chip, solo-vs-co-located throughput lands in an
  interference matrix keyed by (class, neighbor-class) pairs: the
  contention surface ROADMAP item 2 names.  Co-tenancy is learned from
  the scheduler's bind/forget commits (:meth:`note_bind` /
  :meth:`note_unbind`) — O(chips) dict ops under the commit lock.

- **Export + replay**: profiles surface at ``GET /debug/profiles`` (both
  servers), as Prometheus series (``tpu_workload_tokens_per_sec``,
  ``tpu_interference_slowdown_ratio``, ``tpu_workload_step_seconds``),
  and as periodic ``profile`` records in the decision journal — replay
  treats them as annotations (never allocator mutations), and
  ``what_if`` feeds them to profile-aware raters
  (:mod:`elastic_gpu_scheduler_tpu.profile.rater`), turning the flight
  recorder into the offline promotion harness ROADMAP items 2 and 4
  call for.

Process-global instance ``PROFILER``, same pattern as ``tracing.TRACER``
and ``journal.JOURNAL``: emission sites check ``.enabled`` first (one
attribute load when profiling is off).

Deployment note: per-class profiles aggregate within one process.  The
scheduler process owns the cluster-wide co-tenancy map and binds'
class/generation tags; a serving pod profiles its own steps.  The
journal is the cross-process join: every enabled process' ``profile``
records land in the same replayable stream.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Optional

from ..metrics import (
    REGISTRY,
    Counter,
    Histogram,
    LazyGauge,
    _exact_quantile,
)
from ..utils.consts import DEFAULT_WORKLOAD_CLASS

__all__ = [
    "DEFAULT_WORKLOAD_CLASS",
    "PROFILER",
    "WorkloadProfiler",
    "generation_preference",
]


def generation_preference(profiles: dict, wclass: str) -> list:
    """TPU generations ordered by ``wclass``'s measured tokens/s/chip,
    best first, from a profiles dict (``Profiler.profiles()`` live, or a
    journal-recorded snapshot offline) — THE ranking the fleet
    autoscaler places scale-outs by; one definition so live and offline
    scoring can never drift."""
    tps = (profiles.get(wclass) or {}).get("tokens_per_sec_per_chip") or {}
    return [
        g for g, _ in sorted(tps.items(), key=lambda kv: (-kv[1], kv[0]))
    ]

PROFILE_TOKENS = REGISTRY.register(
    LazyGauge(
        "tpu_workload_tokens_per_sec",
        "Measured per-class decode throughput in tokens/s per chip, EWMA "
        "over profiled engine steps, keyed by workload class (the "
        "elasticgpu.io/workload-class pod annotation) and TPU generation "
        "— the Gavel-style throughput-per-accelerator-type table, "
        "refreshed at scrape time from the profile buffers",
        ("wclass", "generation"),
    )
)
INTERFERENCE_RATIO = REGISTRY.register(
    LazyGauge(
        "tpu_interference_slowdown_ratio",
        "Co-located vs solo throughput ratio per (class, neighbor-class) "
        "pair for fractional tenants sharing a chip (1.0 = no measured "
        "contention, 0.5 = this class runs at half speed next to that "
        "neighbor) — the contention matrix a profile-aware rater "
        "consumes",
        ("wclass", "neighbor"),
    )
)
PROFILE_STEP_SECONDS = REGISTRY.register(
    Histogram(
        "tpu_workload_step_seconds",
        "Profiled engine step wall time per workload class (folded from "
        "the sample ring at scrape time)",
        ("wclass",),
    )
)
PROFILE_SAMPLES = REGISTRY.register(
    Counter(
        "tpu_profile_samples_total",
        "Profile samples folded into aggregates, by kind (step = engine "
        "step samples, chip = device-plugin occupancy samples)",
        ("kind",),
    )
)
PROFILE_DROPPED = REGISTRY.register(
    Counter(
        "tpu_profile_dropped_samples_total",
        "Profile samples discarded because the raw ring buffer hit its "
        "cap with no reader folding it — non-zero means profiles "
        "UNDERSTATE activity by that many samples",
        ("kind",),
    )
)


class _Ewma:
    """Exponentially-weighted moving average; first observation seeds."""

    __slots__ = ("value", "n")

    def __init__(self):
        self.value = 0.0
        self.n = 0

    def update(self, x: float, alpha: float) -> None:
        self.n += 1
        if self.n == 1:
            self.value = float(x)
        else:
            self.value += alpha * (float(x) - self.value)


class _Reservoir:
    """Algorithm-R reservoir: a bounded uniform sample of an unbounded
    stream, so latency quantiles stay exact-ish without unbounded
    memory.  Deterministic RNG — profiles must be reproducible in CI."""

    __slots__ = ("k", "n", "samples", "_rng")

    def __init__(self, k: int, seed: int = 0xC0FFEE):
        self.k = k
        self.n = 0
        self.samples: list[float] = []
        self._rng = random.Random(seed)

    def add(self, x: float) -> None:
        self.n += 1
        if len(self.samples) < self.k:
            self.samples.append(float(x))
            return
        j = self._rng.randrange(self.n)
        if j < self.k:
            self.samples[j] = float(x)

    def quantiles(self, qs=(0.5, 0.95, 0.99)) -> list[float]:
        s = sorted(self.samples)
        return [_exact_quantile(s, q) for q in qs]


class _ClassProfile:
    """Aggregated behavior of one workload class (fold-path only: every
    mutation happens under the profiler's fold lock)."""

    __slots__ = (
        "tput", "latency", "occupancy", "host_gap_ms", "queue_depth",
        "hbm_pages", "samples", "tokens",
    )

    def __init__(self, reservoir_k: int):
        self.tput: dict[str, _Ewma] = {}  # generation → tokens/s/chip
        self.latency = _Reservoir(reservoir_k)
        self.occupancy = _Ewma()  # active slots / max_batch
        self.host_gap_ms = _Ewma()
        self.queue_depth = _Ewma()
        self.hbm_pages = _Ewma()  # estimated KV-page footprint
        self.samples = 0
        self.tokens = 0

    def as_dict(self) -> dict:
        p50, p95, p99 = self.latency.quantiles()
        return {
            "tokens_per_sec_per_chip": {
                gen: round(e.value, 3) for gen, e in sorted(self.tput.items())
            },
            "step_ms": {
                "p50": round(p50 * 1e3, 3),
                "p95": round(p95 * 1e3, 3),
                "p99": round(p99 * 1e3, 3),
            },
            "slot_occupancy": round(self.occupancy.value, 4),
            "host_gap_ms": round(self.host_gap_ms.value, 4),
            "queue_depth": round(self.queue_depth.value, 3),
            "hbm_pages": round(self.hbm_pages.value, 2),
            "samples": self.samples,
            "tokens": self.tokens,
        }


class WorkloadProfiler:
    """Per-class performance profiles + co-tenant contention telemetry.

    Concurrency model (mirrors metrics.LOCK_WAIT): the HOT path —
    ``record_step`` / ``record_chip`` — is a stride check plus one
    GIL-atomic list append; all bucketing, EWMA folding and neighbor
    resolution happen under ``_fold_lock`` on the READER's thread
    (scrape, /debug/profiles, journal flush).  ``note_bind`` /
    ``note_unbind`` run at the scheduler's commit points under its
    engine lock, so they are O(chips) dict ops behind a plain internal
    lock that is never held while calling out."""

    def __init__(self):
        self.enabled = False
        self.sample = 0.0
        self.stride = 1
        self.ewma_alpha = 0.2
        self.reservoir_k = 512
        self.journal_interval_s = 30.0
        self._cap = 20000  # raw-buffer bound, same stance as _WAITS_CAP
        # identity of THIS process' serving workload (serve.py sets it);
        # record_step falls back to it when no explicit identity rides
        # the sample
        self._id_pod = ""
        self._id_class = DEFAULT_WORKLOAD_CLASS
        self._id_generation = "unknown"
        self._id_chips = 1
        self._id_neighbors: tuple[str, ...] = ()
        # raw sample rings (appends are GIL-atomic; trimmed via try-lock)
        self._step_buf: list[tuple] = []
        self._chip_buf: list[tuple] = []
        self._step_n = 0  # stride counter (sampling without RNG cost)
        self.dropped_steps = 0
        self.dropped_chips = 0
        # fold-path state
        self._fold_lock = threading.Lock()
        self._profiles: dict[str, _ClassProfile] = {}
        self._solo: dict[str, _Ewma] = {}  # class → solo tokens/s/chip
        self._pairs: dict[tuple[str, str], _Ewma] = {}  # (cls, ncls) → co
        self._chip_occ: dict[tuple[str, str], dict] = {}  # (node, coord)
        self._folded = {"step": 0, "chip": 0}
        self._journal_at = 0.0
        self._journal_seqs = 0
        # co-tenancy (scheduler commit path).  _tenancy_gen bumps on
        # every bind/unbind: step samples stamp it at record time, and
        # the fold attributes interference ONLY when the sample's gen
        # still matches — a sample buffered before a neighbor arrived
        # must not feed that (class, neighbor) pair (fold-time-only
        # resolution would misattribute whole solo windows).
        self._tenancy_lock = threading.Lock()
        self._tenancy_gen = 0
        self._pod_tenancy: dict[str, tuple] = {}
        self._chip_tenants: dict[tuple[str, str], dict[str, str]] = {}
        # node → {workload class: live pod count} — maintained with the
        # tenancy map so the policy plane's filter verb can answer
        # "which classes are resident on this node" in O(classes)
        # (never a scan over the pod map)
        self._node_classes: dict[str, dict[str, int]] = {}
        # ONE gauge carries the refresher: a single run rebuilds both
        # series sets (replace()), and the registry collects the gauges
        # in registration order within a scrape — registering it twice
        # would double-pay the fold per scrape
        PROFILE_TOKENS.refresher = self._refresh_gauges

    # -- lifecycle -----------------------------------------------------------

    def configure(
        self,
        sample: float = 1.0,
        ewma_alpha: float = 0.2,
        reservoir_k: int = 512,
        journal_interval_s: float = 30.0,
    ) -> None:
        """Enable (sample > 0) or disable (sample <= 0) profiling.
        ``sample`` is a step-sampling rate like ``--trace-sample``:
        1.0 profiles every engine step, 0.25 every 4th — implemented as
        a deterministic stride so the hot path never draws randomness."""
        self.sample = max(0.0, min(1.0, float(sample)))
        self.stride = max(1, round(1.0 / self.sample)) if self.sample else 1
        self.ewma_alpha = min(1.0, max(0.001, float(ewma_alpha)))
        self.reservoir_k = max(16, int(reservoir_k))
        self.journal_interval_s = max(0.1, float(journal_interval_s))
        self.enabled = self.sample > 0.0

    def set_identity(
        self,
        pod: str = "",
        wclass: str = DEFAULT_WORKLOAD_CLASS,
        generation: str = "unknown",
        chips: int = 1,
        neighbors: tuple[str, ...] = (),
    ) -> None:
        """Who THIS process' serving engine is (serve.py wires it from
        flags/env): pod key, workload class, TPU generation, chip count,
        and — when the pod knows its fractional co-tenants (the
        ``TPU_COTENANT_CLASSES`` env the node agent can set) — their
        classes, so even a lone serving pod can contribute interference
        samples without the scheduler's tenancy map."""
        self._id_pod = pod
        self._id_class = wclass or DEFAULT_WORKLOAD_CLASS
        self._id_generation = generation or "unknown"
        self._id_chips = max(1, int(chips))
        self._id_neighbors = tuple(neighbors)

    def reset(self) -> None:
        """Drop every buffer/aggregate (tests, CI soaks)."""
        with self._fold_lock, self._tenancy_lock:
            del self._step_buf[:]
            del self._chip_buf[:]
            self._step_n = 0
            self.dropped_steps = self.dropped_chips = 0
            self._profiles.clear()
            self._solo.clear()
            self._pairs.clear()
            self._chip_occ.clear()
            self._folded = {"step": 0, "chip": 0}
            self._journal_at = 0.0
            self._journal_seqs = 0
            self._tenancy_gen = 0
            self._pod_tenancy.clear()
            self._chip_tenants.clear()
            self._node_classes.clear()

    # -- hot path ------------------------------------------------------------

    def record_step(
        self,
        tokens: int,
        wall_s: float,
        slots_active: int = 0,
        slots_total: int = 1,
        host_gap_ms: float = 0.0,
        queue_depth: int = 0,
        hbm_pages: int = 0,
        pod: Optional[str] = None,
        wclass: Optional[str] = None,
        generation: Optional[str] = None,
        chips: Optional[int] = None,
        neighbors: Optional[tuple] = None,
    ) -> bool:
        """One engine-step sample.  Returns True when the sample was
        captured (stride-sampled otherwise).  Cost when profiling is on:
        a counter increment + one tuple append; identity defaults to
        :meth:`set_identity`.  NEVER touches device state — callers pass
        host-side counters only, so steady-state decode stays at zero
        additional host→device uploads."""
        if not self.enabled:
            return False
        self._step_n += 1
        if self._step_n % self.stride:
            return False
        # neighbors: an EXPLICIT tuple (even empty = "known solo") wins;
        # None = unknown — the fold resolves via the co-tenancy map,
        # gated on the stamped tenancy generation so only samples taken
        # under the CURRENT tenancy feed the interference EWMAs
        if neighbors is None:
            neighbors = self._id_neighbors if self._id_neighbors else None
        buf = self._step_buf
        buf.append((
            pod if pod is not None else self._id_pod,
            wclass if wclass is not None else self._id_class,
            generation if generation is not None else self._id_generation,
            chips if chips is not None else self._id_chips,
            neighbors,
            self._tenancy_gen,
            int(tokens), float(wall_s), int(slots_active),
            max(1, int(slots_total)), float(host_gap_ms),
            int(queue_depth), int(hbm_pages),
        ))
        if len(buf) > self._cap and self._fold_lock.acquire(blocking=False):
            # nothing is folding: trim like the TimedLock wait buffers —
            # try-acquire keeps this path non-blocking, and the drop is
            # COUNTED (never silently discard samples)
            try:
                n = self._cap // 2
                del buf[:n]
                self.dropped_steps += n
            finally:
                self._fold_lock.release()
        return True

    def record_chip(
        self,
        node: str,
        coord: str,
        core_units: int,
        core_total: int,
        tenant: str = "",
    ) -> None:
        """Per-chip occupancy sample from the device-plugin path (one
        append; folded into per-chip utilization for /debug/profiles)."""
        if not self.enabled:
            return
        buf = self._chip_buf
        buf.append((
            node, coord, int(core_units), max(1, int(core_total)),
            tenant or "",
        ))
        if len(buf) > self._cap and self._fold_lock.acquire(blocking=False):
            try:
                n = self._cap // 2
                del buf[:n]
                self.dropped_chips += n
            finally:
                self._fold_lock.release()

    # -- co-tenancy (scheduler commit path) ----------------------------------

    def note_bind(
        self,
        pod_key: str,
        node: str,
        wclass: str,
        generation: str,
        coords: tuple,
        fractional: bool,
    ) -> None:
        """Learn a committed placement (called at the scheduler's bind/
        migrate commit, possibly under its engine lock — O(chips) dict
        ops only; the internal lock is never held while calling out)."""
        if not self.enabled:
            return
        coords = tuple(str(c) for c in coords)
        with self._tenancy_lock:
            self._tenancy_gen += 1
            old = self._pod_tenancy.get(pod_key)
            if old is not None:
                self._evict_tenancy_locked(pod_key, old)
            self._pod_tenancy[pod_key] = (
                node, wclass, generation, coords, bool(fractional)
            )
            for c in coords:
                self._chip_tenants.setdefault((node, c), {})[pod_key] = wclass
            row = self._node_classes.setdefault(node, {})
            row[wclass] = row.get(wclass, 0) + 1

    def note_unbind(self, pod_key: str) -> None:
        if not self.enabled:
            return
        with self._tenancy_lock:
            old = self._pod_tenancy.pop(pod_key, None)
            if old is not None:
                self._tenancy_gen += 1
                self._evict_tenancy_locked(pod_key, old)

    def _evict_tenancy_locked(self, pod_key: str, entry: tuple) -> None:
        node, cls, _gen, coords, _frac = entry
        for c in coords:
            tenants = self._chip_tenants.get((node, c))
            if tenants is not None:
                tenants.pop(pod_key, None)
                if not tenants:
                    del self._chip_tenants[(node, c)]
        row = self._node_classes.get(node)
        if row is not None:
            n = row.get(cls, 0) - 1
            if n > 0:
                row[cls] = n
            else:
                row.pop(cls, None)
                if not row:
                    del self._node_classes[node]

    def classes_on_node(self, node: str) -> tuple[str, ...]:
        """Distinct workload classes with live pods on ``node`` (the
        policy filter verb's interference input source)."""
        with self._tenancy_lock:
            row = self._node_classes.get(node)
            return tuple(sorted(row)) if row else ()

    def neighbors_of(self, pod_key: str) -> tuple[str, ...]:
        """Distinct co-tenant classes sharing any of the pod's chips
        (empty = solo).  Used by the fold path and by tests."""
        return self._neighbors_and_gen(pod_key)[1]

    def _neighbors_and_gen(self, pod_key: str) -> tuple[int, tuple]:
        """(tenancy generation, neighbor classes) in ONE lock hold, so
        the fold can match a sample's stamped generation against exactly
        the map it resolves neighbors from."""
        with self._tenancy_lock:
            gen = self._tenancy_gen
            entry = self._pod_tenancy.get(pod_key)
            if entry is None:
                return gen, ()
            node, _cls, _gen, coords, _frac = entry
            out: set[str] = set()
            for c in coords:
                for pk, cls in self._chip_tenants.get((node, c), {}).items():
                    if pk != pod_key:
                        out.add(cls)
            return gen, tuple(sorted(out))

    # -- fold path (reader threads) ------------------------------------------

    def _fold(self) -> None:
        """Drain the raw rings into the aggregates.  Slice-then-del is
        safe against concurrent hot-path appends landing at the tail
        (the TimedLock drain pattern); runs under the fold lock so two
        racing readers never double-apply a sample."""
        with self._fold_lock:
            n = len(self._step_buf)
            steps = self._step_buf[:n]
            del self._step_buf[:n]
            m = len(self._chip_buf)
            chips = self._chip_buf[:m]
            del self._chip_buf[:m]
            alpha = self.ewma_alpha
            lat_batches: dict[str, list[float]] = {}
            for (
                pod, wclass, gen, nchips, neighbors, tgen, tokens, wall_s,
                active, total, gap_ms, qdepth, pages,
            ) in steps:
                prof = self._profiles.get(wclass)
                if prof is None:
                    prof = self._profiles[wclass] = _ClassProfile(
                        self.reservoir_k
                    )
                tps = (tokens / wall_s / max(1, nchips)) if wall_s > 0 else 0.0
                prof.tput.setdefault(gen, _Ewma()).update(tps, alpha)
                prof.latency.add(wall_s)
                prof.occupancy.update(active / total, alpha)
                prof.host_gap_ms.update(gap_ms, alpha)
                prof.queue_depth.update(qdepth, alpha)
                prof.hbm_pages.update(pages, alpha)
                prof.samples += 1
                prof.tokens += tokens
                lat_batches.setdefault(wclass, []).append(wall_s)
                # interference: an EXPLICIT neighbor tuple on the sample
                # wins; otherwise resolve via the co-tenancy map — but
                # ONLY when the sample's stamped tenancy generation still
                # matches the map's (a sample buffered before a neighbor
                # arrived/left must not be attributed to the new regime;
                # such stale samples still feed throughput/latency, just
                # not the interference EWMAs)
                if neighbors is not None:
                    ncls: Optional[tuple] = tuple(neighbors)
                elif pod:
                    cur_gen, resolved = self._neighbors_and_gen(pod)
                    ncls = resolved if tgen == cur_gen else None
                else:
                    ncls = ()
                if ncls is not None and (tokens or wall_s):
                    if not ncls:
                        self._solo.setdefault(wclass, _Ewma()).update(
                            tps, alpha
                        )
                    else:
                        for nc in ncls:
                            self._pairs.setdefault(
                                (wclass, nc), _Ewma()
                            ).update(tps, alpha)
            for (node, coord, units, total, tenant) in chips:
                occ = self._chip_occ.setdefault(
                    (node, coord),
                    {"util": _Ewma(), "samples": 0, "tenants": set()},
                )
                occ["util"].update(units / total, alpha)
                occ["samples"] += 1
                if tenant:
                    occ["tenants"].add(tenant)
                    if len(occ["tenants"]) > 16:
                        occ["tenants"].pop()
            self._folded["step"] += n
            self._folded["chip"] += m
        # metric counters + histograms OUTSIDE the fold lock (their own
        # locks suffice; a scrape mid-update reads a consistent snapshot)
        if n:
            PROFILE_SAMPLES.inc("step", value=float(n))
        if m:
            PROFILE_SAMPLES.inc("chip", value=float(m))
        for wclass, vals in lat_batches.items():
            PROFILE_STEP_SECONDS.observe_batch(wclass, values=vals)
        if self.dropped_steps or self.dropped_chips:
            with self._fold_lock:
                ds, self.dropped_steps = self.dropped_steps, 0
                dc, self.dropped_chips = self.dropped_chips, 0
            if ds:
                PROFILE_DROPPED.inc("step", value=float(ds))
            if dc:
                PROFILE_DROPPED.inc("chip", value=float(dc))

    # -- read APIs -----------------------------------------------------------

    def profiles(self) -> dict:
        """Per-class profiles (folds first)."""
        self._fold()
        with self._fold_lock:
            return self._profiles_locked()

    def _profiles_locked(self) -> dict:
        return {
            cls: prof.as_dict()
            for cls, prof in sorted(self._profiles.items())
        }

    def generation_preference(self, wclass: str) -> list:
        """TPU generations ordered by this class's measured tokens/s/chip,
        best first — the fleet autoscaler's scale-out placement signal
        (Gavel's heterogeneity policy on live numbers).  Empty when the
        class was never profiled (callers then keep the scheduler's own
        score order)."""
        return generation_preference(self.profiles(), wclass)

    def interference_matrix(self) -> dict:
        """{class: {neighbor: ratio}} — co-located tokens/s/chip divided
        by the class' solo tokens/s/chip.  A pair appears only once both
        regimes were observed; ratio < 1 means measured slowdown."""
        self._fold()
        with self._fold_lock:
            return self._matrix_locked()

    def _matrix_locked(self) -> dict:
        out: dict[str, dict[str, float]] = {}
        for (cls, ncls), co in sorted(self._pairs.items()):
            solo = self._solo.get(cls)
            if solo is None or solo.value <= 0 or co.n == 0:
                continue
            out.setdefault(cls, {})[ncls] = round(
                co.value / solo.value, 4
            )
        return out

    def debug_state(self) -> dict:
        """The /debug/profiles payload."""
        self._fold()
        with self._fold_lock:
            profiles = self._profiles_locked()
            matrix = self._matrix_locked()
            chip_occ = {
                f"{node}/{coord}": {
                    "core_util": round(occ["util"].value, 4),
                    "samples": occ["samples"],
                    "tenants": sorted(occ["tenants"]),
                }
                for (node, coord), occ in sorted(self._chip_occ.items())
            }
            folded = dict(self._folded)
            pending = len(self._step_buf) + len(self._chip_buf)
            solo = {
                cls: round(e.value, 3) for cls, e in sorted(self._solo.items())
            }
        with self._tenancy_lock:
            tenancy = {
                pk: {
                    "node": node, "class": cls, "generation": gen,
                    "chips": list(coords), "fractional": frac,
                }
                for pk, (node, cls, gen, coords, frac) in sorted(
                    self._pod_tenancy.items()
                )
            }
        return {
            "enabled": self.enabled,
            "sample": self.sample,
            "identity": {
                "pod": self._id_pod,
                "class": self._id_class,
                "generation": self._id_generation,
                "chips": self._id_chips,
            },
            "folded": folded,
            "pending": pending,
            "journal_records": self._journal_seqs,
            "profiles": profiles,
            "solo_tokens_per_sec_per_chip": solo,
            "interference": matrix,
            "chip_occupancy": chip_occ,
            "tenancy": tenancy,
        }

    # -- journal integration -------------------------------------------------

    def snapshot_for_journal(self) -> dict:
        """Compact profile snapshot for a journal ``profile`` record —
        everything a profile-aware rater needs to re-score recorded
        workload offline (folds first)."""
        self._fold()
        with self._fold_lock:
            profiles = self._profiles_locked()
            matrix = self._matrix_locked()
        return {
            "profiles": {
                cls: {
                    "tput": p["tokens_per_sec_per_chip"],
                    "p50_ms": p["step_ms"]["p50"],
                    "p99_ms": p["step_ms"]["p99"],
                    "occupancy": p["slot_occupancy"],
                    "samples": p["samples"],
                }
                for cls, p in profiles.items()
            },
            "interference": matrix,
        }

    def maybe_journal(self, force: bool = False) -> Optional[int]:
        """Land a ``profile`` record in the decision journal when the
        interval elapsed (or ``force``).  Cheap when not due: one time
        compare.  The record is an ANNOTATION — replay never mutates
        allocator state from it (journal/replay.py)."""
        from ..journal import JOURNAL

        if not self.enabled or not JOURNAL.enabled:
            return None
        now = time.monotonic()
        if not force and now - self._journal_at < self.journal_interval_s:
            return None
        self._journal_at = now
        snap = self.snapshot_for_journal()
        if not snap["profiles"]:
            return None
        from ..tracing import TRACER

        with TRACER.span(
            "profile.flush",
            classes=len(snap["profiles"]),
            pairs=sum(len(v) for v in snap["interference"].values()),
        ):
            seq = JOURNAL.record("profile", **snap)
        if seq is not None:
            self._journal_seqs += 1
        return seq

    # -- metrics export (LazyGauge refresher; scrape-time only) --------------

    def _refresh_gauges(self) -> None:
        # ONE fold serves both gauges (the refresher is registered on
        # PROFILE_TOKENS only; the registry collects in registration
        # order, so INTERFERENCE_RATIO exports the same refresh)
        self._fold()
        with self._fold_lock:
            profiles = self._profiles_locked()
            matrix = self._matrix_locked()
        tokens: dict[tuple[str, ...], float] = {}
        for cls, p in profiles.items():
            for gen, tps in p["tokens_per_sec_per_chip"].items():
                tokens[(cls, gen)] = tps
        ratios: dict[tuple[str, ...], float] = {}
        for cls, row in matrix.items():
            for ncls, ratio in row.items():
                ratios[(cls, ncls)] = ratio
        # whole-dict swap per gauge: one lock acquisition, so a racing
        # scrape can never observe a cleared-but-unfilled series set
        PROFILE_TOKENS.replace(tokens)
        INTERFERENCE_RATIO.replace(ratios)


def configure_from_env() -> None:
    """Apply ``TPU_PROFILE_SAMPLE`` — same contract (and same default-ON
    stance) as ``TPU_TRACE_SAMPLE``: unset means 1.0, the per-sample
    cost is one ring append and the budgets are CI-enforced; 0
    disables."""
    raw = os.environ.get("TPU_PROFILE_SAMPLE", "1")
    try:
        PROFILER.configure(sample=float(raw))
    except ValueError:
        PROFILER.configure(sample=1.0)


PROFILER = WorkloadProfiler()
configure_from_env()
