"""Sharding rules for the flagship transformer (GSPMD-style).

The recipe from the public scaling playbook: pick a mesh, annotate array
shardings with PartitionSpecs, let XLA insert the collectives.

Parameter layout (models/transformer.py pytree):

    embed        (V, D)        → (tensor, fsdp)     vocab-sharded embed
    layers/*     stacked (L, ...) leaves; per-leaf rules below
    attn wq/wk/wv (L, D, H)    → (-, fsdp, tensor)  column-parallel
    attn wo      (L, H, D)     → (-, tensor, fsdp)  row-parallel
    mlp w_in/w_gate (L, D, F)  → (-, fsdp, tensor)  column-parallel
    mlp w_out    (L, F, D)     → (-, tensor, fsdp)  row-parallel
    norms        (L, D)        → replicated
    unembed      (D, V)        → (fsdp, tensor)

Activations: (batch, seq, d_model) → (("data","fsdp"), "seq", None) — batch
sharded over data×fsdp, sequence over the seq axis (ring attention handles
cross-shard attention).
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def param_specs(params: Any, pipeline: bool = False) -> Any:
    """PartitionSpec pytree matching models.transformer.init_params output.

    With ``pipeline=True`` the stacked layer axis (leading L) is sharded over
    the ``pipe`` mesh axis so each pipeline stage owns its layer group
    (parallel/pipeline.py)."""

    lead = "pipe" if pipeline else None

    def spec_for(path: tuple[str, ...], leaf) -> P:
        name = "/".join(path)
        nd = leaf.ndim
        in_layers = "layers" in name
        if "pos_embed" in name or "cls_token" in name:
            return P()  # small positional/cls params: replicated
        if "patch_embed" in name:
            return P("fsdp", "tensor")  # (patch_dim, D) dense projection
        if "unembed" in name:  # must precede the "embed" substring check
            return P("fsdp", "tensor")
        if "embed" in name:
            return P("tensor", "fsdp")
        if name.endswith("head"):
            return P("fsdp", None)  # (D, n_classes): classes too small to shard
        if "moe_gate" in name:
            return P(lead) if in_layers else P()  # router: replicated
        if any(k in name for k in ("wq", "wk", "wv", "w_in", "w_gate")):
            # nd==4 → MoE expert-stacked (L, E, D, F): experts over "expert"
            if nd == 4:
                return P(lead, "expert", "fsdp", "tensor")
            # stacked over layers: leading L axis pipe-sharded when pipelining
            return P(lead, "fsdp", "tensor") if nd == 3 else P("fsdp", "tensor")
        if any(k in name for k in ("wo", "w_out")):
            if nd == 4:
                return P(lead, "expert", "tensor", "fsdp")
            return P(lead, "tensor", "fsdp") if nd == 3 else P("tensor", "fsdp")
        if in_layers and nd >= 1:
            return P(lead)  # per-layer norms
        return P()  # scalars / final norm: replicated

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    specs = []
    for path, leaf in flat:
        keys = tuple(
            getattr(k, "key", getattr(k, "idx", str(k))) for k in path
        )
        specs.append(spec_for(tuple(str(k) for k in keys), leaf))
    return jax.tree_util.tree_unflatten(treedef, specs)


def batch_spec() -> P:
    """Tokens/labels (batch, seq): batch over data+fsdp, seq over seq axis."""
    return P(("data", "fsdp"), "seq")


def activation_spec() -> P:
    """(batch, seq, d_model) activations."""
    return P(("data", "fsdp"), "seq", None)


def _fit_spec(spec: P, mesh: Mesh, shape) -> P:
    """Best-effort restriction of a spec to what ``mesh`` and ``shape``
    allow: axes the mesh doesn't have are dropped (a pure-tensor serving
    mesh has no fsdp/expert/pipe), and a dim that doesn't divide by its
    axes' total size replicates instead of erroring (arbitrary checkpoints
    — e.g. an odd vocab under tensor=2 — must still load)."""
    fitted = []
    for i, ax in enumerate(spec):
        axes = ax if isinstance(ax, (tuple, list)) else (ax,) if ax else ()
        kept = tuple(a for a in axes if a in mesh.axis_names)
        div = 1
        for a in kept:
            div *= mesh.shape[a]
        if not kept or shape[i] % div != 0:
            fitted.append(None)
        else:
            fitted.append(kept if isinstance(ax, (tuple, list)) else kept[0])
    return P(*fitted)


def shard_params(
    params: Any, mesh: Mesh, pipeline: bool = False, strict: bool = True
) -> Any:
    """Place params under the sharding rules.  ``strict=False`` fits each
    leaf's spec to the mesh and shape via ``_fit_spec`` — the mode for
    serving arbitrary checkpoints on arbitrary meshes (and for restoring
    onto a smaller mesh than a job trained on)."""
    specs = param_specs(params, pipeline=pipeline)
    if strict:
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, specs,
        )
    return jax.tree.map(
        lambda x, s: jax.device_put(
            x, NamedSharding(mesh, _fit_spec(s, mesh, x.shape))
        ),
        params, specs,
    )


def constrain(x, mesh: Mesh, spec: P):
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
