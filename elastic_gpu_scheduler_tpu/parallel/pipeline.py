"""Pipeline parallelism (GPipe-style) over the ``pipe`` mesh axis.

Layers are stacked on a leading L axis (models/transformer.py); sharding L
over ``pipe`` gives each stage L/PP layers.  Microbatches march through the
stages with one ``lax.ppermute`` hop per step — the classic GPipe schedule
with M + PP - 1 steps and bubble fraction (PP-1)/(M+PP-1).

Composition: the shard_map here is *manual only over pipe* (plus ``seq``
when sequence parallelism is active — see below); all other mesh axes
(data/fsdp/expert/tensor) stay automatic, so XLA keeps sharding the
per-stage matmuls and MoE dispatch as usual.

sp × pp: ring attention's own shard_map cannot nest inside this one, so
when both are requested the caller passes ``seq_axis`` — the manual region
widens to {pipe, seq}, activations enter sequence-sharded, and the layer fn
calls ``parallel.ring.ring_attention`` directly (its ppermute collectives
run on the seq axis of this same manual region).

No reference analogue (SURVEY §2 #19): this is the PP slot of the workload
plane's dp/fsdp/ep/pp/tp/sq axes.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.jaxcompat import pcast


def pipeline_apply(
    layer_fn: Callable,  # (x_mb, layer_params) -> (x_mb, aux_scalar)
    stacked_params,  # pytree, leaves (L, ...) with L % pp == 0
    x: jax.Array,  # (M, mb, S, D) microbatched activations
    mesh: Mesh,
    seq_axis: str = None,  # widen the manual region to {pipe, seq_axis}
) -> tuple[jax.Array, jax.Array]:
    """Run all layers over all microbatches; returns (y (M,mb,S,D), aux)."""
    pp = mesh.shape["pipe"]
    if pp == 1:
        def scan_body(h, lp):
            h2, aux = layer_fn(h, lp)
            return h2, aux

        M = x.shape[0]
        flat = x.reshape((-1,) + x.shape[2:])
        out, aux = lax.scan(scan_body, flat, stacked_params)
        return out.reshape(x.shape), jnp.sum(aux)

    M = x.shape[0]
    T = M + pp - 1

    manual_axes = ("pipe",) + ((seq_axis,) if seq_axis else ())

    def stage_fn(params_local, x_mb):
        stage = lax.axis_index("pipe")
        vary = lambda a: pcast(a, manual_axes, to="varying")

        def run_layers(h):
            def body(h, lp):
                h2, aux = layer_fn(h, lp)
                return h2, aux

            h, aux = lax.scan(body, h, params_local)
            return h, jnp.sum(aux)

        # carries must be varying over EVERY manual axis (x_mb is seq-varying
        # when seq_axis is set; zeros alone would be replicated).  aux is
        # typed over all manual axes too: MoE layers compute their router
        # load-balance aux from seq-LOCAL activations, so it is seq-varying
        # and the closing psum must reduce the seq axis as well (dense
        # layers' constant aux just gets multiplied by the seq size, which
        # the final divide undoes).
        state0 = vary(jnp.zeros(x_mb.shape[1:], x_mb.dtype))
        # fresh zeros, NOT zeros_like(x_mb): zeros_like inherits x_mb's
        # seq-varying type and pcast refuses to re-vary an already-varying axis
        outputs0 = vary(jnp.zeros(x_mb.shape, x_mb.dtype))
        aux0 = vary(jnp.zeros((), jnp.float32))

        def step(t, carry):
            state, outputs, aux_total = carry
            # stage 0 ingests microbatch t (x_mb is already seq-varying, so
            # only the pipe axis needs casting here)
            inject = pcast(x_mb[jnp.where(t < M, t, 0)], "pipe", to="varying")
            state = jnp.where(stage == 0, inject, state)
            state, aux = run_layers(state)
            # this stage held microbatch (t - stage); is it a real one?
            mb_idx = t - stage
            valid = (mb_idx >= 0) & (mb_idx < M)
            aux_total = aux_total + jnp.where(valid, aux, 0.0)
            # last stage emits microbatch t - (pp - 1)
            out_idx = t - (pp - 1)
            write = (stage == pp - 1) & (out_idx >= 0)
            slot = jnp.clip(out_idx, 0, M - 1)
            updated = lax.dynamic_update_index_in_dim(outputs, state, slot, 0)
            outputs = jnp.where(write, updated, outputs)
            # advance the pipeline one hop
            perm = [(i, (i + 1) % pp) for i in range(pp)]
            state = lax.ppermute(state, "pipe", perm)
            return state, outputs, aux_total

        _, outputs, aux_total = lax.fori_loop(
            0, T, step, (state0, outputs0, aux0)
        )
        # results live on the last stage; zero elsewhere → psum broadcasts
        is_last = (stage == pp - 1).astype(outputs.dtype)
        outputs = lax.psum(outputs * is_last, "pipe")
        # every stage contributed its own layers' aux, once per microbatch;
        # divide by M so the aux scale matches the unpipelined full-batch
        # scan, and average over seq shards (MoE aux is per-shard)
        seq_n = lax.psum(1, seq_axis) if seq_axis else 1
        aux_total = lax.psum(aux_total, manual_axes) / (M * seq_n)
        return outputs, aux_total

    from ..utils.jaxcompat import shard_map

    x_spec = P(None, None, seq_axis, None) if seq_axis else P()
    y, aux = shard_map(
        stage_fn,
        mesh=mesh,
        in_specs=(P("pipe"), x_spec),
        out_specs=(x_spec, P()),
        axis_names=set(manual_axes),
    )(stacked_params, x)
    return y, aux


def microbatch(x: jax.Array, n_micro: int) -> jax.Array:
    """(B, ...) → (M, B/M, ...)."""
    B = x.shape[0]
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible by {n_micro} microbatches")
    return x.reshape((n_micro, B // n_micro) + x.shape[1:])


def unmicrobatch(x: jax.Array) -> jax.Array:
    return x.reshape((-1,) + x.shape[2:])
