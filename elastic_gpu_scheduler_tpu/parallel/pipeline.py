"""Pipeline parallelism (GPipe-style) over the ``pipe`` mesh axis.

Layers are stacked on a leading L axis (models/transformer.py); sharding L
over ``pipe`` gives each stage L/PP layers.  Microbatches march through the
stages with one ``lax.ppermute`` hop per step — the classic GPipe schedule
with M + PP - 1 steps and bubble fraction (PP-1)/(M+PP-1).

Composition: the shard_map here is *manual only over pipe*; all other mesh
axes (data/fsdp/expert/tensor) stay automatic, so XLA keeps sharding the
per-stage matmuls and MoE dispatch as usual.  Sequence parallelism (ring
attention, its own shard_map) does not nest inside the pipeline in this
version — pp composes with dp/fsdp/ep/tp; sp composes with everything except
pp.

No reference analogue (SURVEY §2 #19): this is the PP slot of the workload
plane's dp/fsdp/ep/pp/tp/sq axes.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply(
    layer_fn: Callable,  # (x_mb, layer_params) -> (x_mb, aux_scalar)
    stacked_params,  # pytree, leaves (L, ...) with L % pp == 0
    x: jax.Array,  # (M, mb, S, D) microbatched activations
    mesh: Mesh,
) -> tuple[jax.Array, jax.Array]:
    """Run all layers over all microbatches; returns (y (M,mb,S,D), aux)."""
    pp = mesh.shape["pipe"]
    if pp == 1:
        def scan_body(h, lp):
            h2, aux = layer_fn(h, lp)
            return h2, aux

        M = x.shape[0]
        flat = x.reshape((-1,) + x.shape[2:])
        out, aux = lax.scan(scan_body, flat, stacked_params)
        return out.reshape(x.shape), jnp.sum(aux)

    M = x.shape[0]
    T = M + pp - 1

    def stage_fn(params_local, x_mb):
        stage = lax.axis_index("pipe")
        vary = lambda a: lax.pcast(a, "pipe", to="varying")

        def run_layers(h):
            def body(h, lp):
                h2, aux = layer_fn(h, lp)
                return h2, aux

            h, aux = lax.scan(body, h, params_local)
            return h, jnp.sum(aux)

        state0 = vary(jnp.zeros(x_mb.shape[1:], x_mb.dtype))
        outputs0 = vary(jnp.zeros_like(x_mb))
        aux0 = vary(jnp.zeros((), jnp.float32))

        def step(t, carry):
            state, outputs, aux_total = carry
            # stage 0 ingests microbatch t
            inject = x_mb[jnp.where(t < M, t, 0)]
            state = jnp.where(stage == 0, vary(inject), state)
            state, aux = run_layers(state)
            # this stage held microbatch (t - stage); is it a real one?
            mb_idx = t - stage
            valid = (mb_idx >= 0) & (mb_idx < M)
            aux_total = aux_total + jnp.where(valid, aux, 0.0)
            # last stage emits microbatch t - (pp - 1)
            out_idx = t - (pp - 1)
            write = (stage == pp - 1) & (out_idx >= 0)
            slot = jnp.clip(out_idx, 0, M - 1)
            updated = lax.dynamic_update_index_in_dim(outputs, state, slot, 0)
            outputs = jnp.where(write, updated, outputs)
            # advance the pipeline one hop
            perm = [(i, (i + 1) % pp) for i in range(pp)]
            state = lax.ppermute(state, "pipe", perm)
            return state, outputs, aux_total

        _, outputs, aux_total = lax.fori_loop(
            0, T, step, (state0, outputs0, aux0)
        )
        # results live on the last stage; zero elsewhere → psum broadcasts
        is_last = (stage == pp - 1).astype(outputs.dtype)
        outputs = lax.psum(outputs * is_last, "pipe")
        # every stage contributed its own layers' aux, once per microbatch;
        # divide by M so the aux scale matches the unpipelined full-batch scan
        aux_total = lax.psum(aux_total, "pipe") / M
        return outputs, aux_total

    y, aux = jax.shard_map(
        stage_fn,
        mesh=mesh,
        in_specs=(P("pipe"), P()),
        out_specs=(P(), P()),
        axis_names={"pipe"},
    )(stacked_params, x)
    return y, aux


def microbatch(x: jax.Array, n_micro: int) -> jax.Array:
    """(B, ...) → (M, B/M, ...)."""
    B = x.shape[0]
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible by {n_micro} microbatches")
    return x.reshape((n_micro, B // n_micro) + x.shape[1:])


def unmicrobatch(x: jax.Array) -> jax.Array:
    return x.reshape((-1,) + x.shape[2:])
