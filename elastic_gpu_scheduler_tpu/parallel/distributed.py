"""Multi-host initialization: one call per process before building meshes.

Scales the workload plane to multi-host slices the way the scheduler scales
placement: each pod of a gang runs one JAX process; ``jax.distributed``
forms the global device view over ICI/DCN, after which the same
``jax.sharding.Mesh`` code paths span hosts — XLA routes collectives over
ICI within a slice and DCN across slices (SURVEY §2 #20: the TPU-native
replacement for the reference ecosystem's NCCL/MPI backend is exactly
XLA's collective runtime; nothing here implements transports).

Environment contract (set by the gang's pod template / launcher):

    TPU_COORDINATOR_ADDRESS  host:port of process 0 (or GKE's
                             MEGASCALE/JAX defaults)
    TPU_NUM_PROCESSES        gang size
    TPU_PROCESS_ID           this member's index (e.g. from the pod name
                             ordinal or the jobset completion index)

On TPU VMs with libtpu, ``jax.distributed.initialize()`` can also infer
everything from the TPU metadata — so all variables are optional there.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

log = logging.getLogger("tpu-launcher")


def maybe_initialize_distributed(
    coordinator: str = "",
    num_processes: int = 0,
    process_id: int = -1,
) -> bool:
    """Initialize jax.distributed when a multi-process env is configured.

    Returns True if distributed mode is active.  Safe no-op single-process.
    """
    coordinator = coordinator or os.environ.get("TPU_COORDINATOR_ADDRESS", "")
    if num_processes <= 0:
        num_processes = int(os.environ.get("TPU_NUM_PROCESSES", "0") or 0)
    if process_id < 0:
        process_id = int(os.environ.get("TPU_PROCESS_ID", "-1") or -1)

    if num_processes <= 1 and not coordinator:
        return False
    kwargs = {}
    if coordinator:
        kwargs["coordinator_address"] = coordinator
    if num_processes > 0:
        kwargs["num_processes"] = num_processes
    if process_id >= 0:
        kwargs["process_id"] = process_id
    try:
        jax.distributed.initialize(**kwargs)
        log.info(
            "jax.distributed: process %d/%d, %d global devices",
            jax.process_index(),
            jax.process_count(),
            jax.device_count(),
        )
        return True
    except RuntimeError as e:
        if "already initialized" in str(e).lower():
            return True
        raise


def process_info() -> tuple[int, int]:
    """(process_index, process_count) — (0, 1) when not distributed."""
    try:
        return jax.process_index(), jax.process_count()
    except Exception:
        return 0, 1
