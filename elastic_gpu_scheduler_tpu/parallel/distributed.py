"""Multi-host initialization: one call per process before building meshes.

Scales the workload plane to multi-host slices the way the scheduler scales
placement: each pod of a gang runs one JAX process; ``jax.distributed``
forms the global device view over ICI/DCN, after which the same
``jax.sharding.Mesh`` code paths span hosts — XLA routes collectives over
ICI within a slice and DCN across slices (SURVEY §2 #20: the TPU-native
replacement for the reference ecosystem's NCCL/MPI backend is exactly
XLA's collective runtime; nothing here implements transports).

Environment contract (set by the gang's pod template / launcher):

    TPU_COORDINATOR_ADDRESS  host:port of process 0 (or GKE's
                             MEGASCALE/JAX defaults)
    TPU_NUM_PROCESSES        gang size
    TPU_PROCESS_ID           this member's index (e.g. from the pod name
                             ordinal or the jobset completion index)

On TPU VMs with libtpu, ``jax.distributed.initialize()`` can also infer
everything from the TPU metadata — so all variables are optional there.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

log = logging.getLogger("tpu-launcher")


def maybe_initialize_distributed(
    coordinator: str = "",
    num_processes: int = 0,
    process_id: int = -1,
) -> bool:
    """Initialize jax.distributed when a multi-process env is configured.

    Returns True if distributed mode is active.  Safe no-op single-process.
    """
    coordinator = coordinator or os.environ.get("TPU_COORDINATOR_ADDRESS", "")
    if num_processes <= 0:
        num_processes = int(os.environ.get("TPU_NUM_PROCESSES", "0") or 0)
    if process_id < 0:
        process_id = int(os.environ.get("TPU_PROCESS_ID", "-1") or -1)

    if num_processes <= 1 and not coordinator:
        return False
    # CPU backend: XLA's default CPU client has no cross-process
    # collectives ("Multiprocess computations aren't implemented on the
    # CPU backend") — switch to the gloo implementation BEFORE any
    # backend initializes, so multi-process CPU simulation (tests, dev
    # boxes) runs the same global-mesh code path real slices do.  Only
    # when CPU was explicitly selected: on TPU the default is correct.
    plats = os.environ.get("JAX_PLATFORMS", "")
    try:
        plats = getattr(jax.config, "jax_platforms", None) or plats
    except Exception:
        pass
    if "cpu" in (plats or "").split(","):
        try:
            jax.config.update(
                "jax_cpu_collectives_implementation", "gloo"
            )
        except Exception:  # unknown option on this jaxlib: keep defaults
            pass
    kwargs = {}
    if coordinator:
        kwargs["coordinator_address"] = coordinator
    if num_processes > 0:
        kwargs["num_processes"] = num_processes
    if process_id >= 0:
        kwargs["process_id"] = process_id
    try:
        jax.distributed.initialize(**kwargs)
        log.info(
            "jax.distributed: process %d/%d, %d global devices",
            jax.process_index(),
            jax.process_count(),
            jax.device_count(),
        )
        return True
    except RuntimeError as e:
        if "already initialized" in str(e).lower():
            return True
        raise


def process_info() -> tuple[int, int]:
    """(process_index, process_count) — (0, 1) when not distributed."""
    try:
        return jax.process_index(), jax.process_count()
    except Exception:
        return 0, 1


DEFAULT_COORDINATOR_PORT = 8476


def gang_info_from_annotations(
    annotations: dict,
) -> tuple[int, int, list[str]]:
    """(rank, size, ordered peer keys) from the gang commit's bind
    annotations (scheduler/gang.py phase 2).  The peer list is
    authoritative for size when present; rank defaults to 0 and size to
    the ``gang-size`` annotation (or 1) for pods bound before this
    ledger field existed."""
    from ..utils import consts

    ann = annotations or {}
    peers = [
        p for p in ann.get(consts.ANNOTATION_GANG_PEERS, "").split(",") if p
    ]
    try:
        rank = int(ann.get(consts.ANNOTATION_GANG_RANK, "0"))
    except ValueError:
        rank = 0
    if peers:
        size = len(peers)
    else:
        try:
            size = int(ann.get(consts.ANNOTATION_GANG_SIZE, "1") or 1)
        except ValueError:
            size = 1
    return rank, max(1, size), peers


def initialize_for_gang(
    annotations: dict,
    coordinator: str = "",
    coordinator_port: int = 0,
) -> bool:
    """Initialize ``jax.distributed`` for a scheduler-bound gang member:
    process_id = the member's journaled gang rank, num_processes = gang
    size, coordinator = rank 0.

    Coordinator resolution order: explicit argument →
    ``TPU_COORDINATOR_ADDRESS`` → derived from peer 0's pod name (in a
    headless-Service/jobset deployment the pod name IS the stable DNS
    host) on ``coordinator_port`` (default TPU_COORDINATOR_PORT or
    8476).  A gang of one is a no-op: single-process serving/training
    keeps its exact historical boot path.  Returns True when the global
    (cross-host) device view is active."""
    rank, size, peers = gang_info_from_annotations(annotations)
    if size <= 1:
        return False
    if not coordinator:
        coordinator = os.environ.get("TPU_COORDINATOR_ADDRESS", "")
    if not coordinator and peers:
        host = peers[0].rsplit("/", 1)[-1]  # "ns/name" → name
        port = coordinator_port or int(
            os.environ.get("TPU_COORDINATOR_PORT", "0")
            or DEFAULT_COORDINATOR_PORT
        )
        coordinator = f"{host}:{port}"
    if not coordinator:
        raise ValueError(
            f"gang of {size} needs a coordinator address (no gang-peers "
            "annotation, no TPU_COORDINATOR_ADDRESS)"
        )
    return maybe_initialize_distributed(
        coordinator=coordinator, num_processes=size, process_id=rank
    )
