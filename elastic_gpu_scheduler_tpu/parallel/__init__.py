"""Parallelism: 6-axis mesh, sharding rules, ring attention, pipeline."""

from .mesh import AXES, MeshSpec, make_mesh, mesh_from_allocation
from .ring import ring_attention, ring_attention_sharded

__all__ = [
    "AXES", "MeshSpec", "make_mesh", "mesh_from_allocation",
    "ring_attention", "ring_attention_sharded",
]
