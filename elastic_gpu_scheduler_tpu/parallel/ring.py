"""Ring attention: sequence/context parallelism over the ``seq`` mesh axis.

Long-context support, TPU-native: Q/K/V are sharded along the sequence axis
across the ``seq`` devices; K/V shards rotate around the ICI ring via
``lax.ppermute`` while each device accumulates its queries' attention with
the numerically-stable running (max, sum, acc) merge — so a sequence N× the
per-chip memory fits, and every hop is one ICI neighbor transfer (the
scheduler's contiguous placement makes the ring physical).

No reference analogue (the reference schedules pods; SURVEY §2 #19 maps this
capability slot to topology-aware placement + this workload-side
implementation).

Usage: inside ``shard_map`` (``ring_attention``), or let
``ring_attention_sharded`` wrap it for a mesh with axes (data, fsdp, tensor,
seq).  Degenerates to one local flash block when the seq axis has size 1.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import NEG_INF, _use_pallas, flash_block_stats


def _block_attend(q, k, v, q_offset, k_offset, causal, scale):
    """Scaled blockwise attention stats: returns (scores_exp·v, max, sumexp).

    q: (B,H,Sq,D) local queries; k/v: (B,H,Sk,D) a rotating shard.
    Offsets are the shards' global sequence starts, for causal masking.
    On TPU the Pallas stats kernel (ops/attention.flash_block_stats) computes
    the same triple without materializing the (Sq, Sk) score matrix in HBM.
    """
    if _use_pallas() and q.shape[2] % 128 == 0 and k.shape[2] % 128 == 0:
        return flash_block_stats(
            q, k, v, q_offset, k_offset, causal=causal, sm_scale=scale
        )
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        q_ids = q_offset + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_ids = k_offset + lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(q_ids[None, None] >= k_ids[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)  # (B,H,Sq)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    pv = jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return pv, m, l


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "seq",
    causal: bool = True,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """Call inside shard_map with q,k,v sequence-sharded on ``axis_name``.

    Shapes (local): (B, H, S_local, D) → (B, H, S_local, D).
    """
    scale = q.shape[-1] ** -0.5 if sm_scale is None else sm_scale
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    s_local = q.shape[2]
    qf = q.astype(jnp.float32)
    q_offset = my_idx * s_local

    # derive carries from qf so they inherit its varying-axes type (plain
    # zeros would be "replicated" and fail the fori_loop carry-type check)
    acc0 = jnp.zeros_like(qf)
    m0 = jnp.full_like(qf[..., 0], NEG_INF)
    l0 = jnp.zeros_like(qf[..., 0])

    def step(j, carry):
        acc, m_i, l_i, k_cur, v_cur = carry
        src = (my_idx - j) % n  # which shard k_cur/v_cur originated from
        pv, m_blk, l_blk = _block_attend(
            qf, k_cur, v_cur, q_offset, src * s_local, causal, scale
        )
        m_new = jnp.maximum(m_i, m_blk)
        alpha = jnp.exp(m_i - m_new)
        beta = jnp.exp(m_blk - m_new)
        acc = acc * alpha[..., None] + pv * beta[..., None]
        l_new = l_i * alpha + l_blk * beta

        # rotate k/v one hop around the ring; the last iteration's rotation
        # would be discarded, so skip it (saves one full K/V ICI hop per call)
        def rotate(kv):
            perm = [(p_, (p_ + 1) % n) for p_ in range(n)]
            return (
                lax.ppermute(kv[0], axis_name, perm),
                lax.ppermute(kv[1], axis_name, perm),
            )

        k_nxt, v_nxt = lax.cond(j < n - 1, rotate, lambda kv: kv, (k_cur, v_cur))
        return acc, m_new, l_new, k_nxt, v_nxt

    acc, m_i, l_i, _, _ = lax.fori_loop(
        0, n, step, (acc0, m0, l0, k.astype(jnp.float32), v.astype(jnp.float32))
    )
    l_safe = jnp.where(l_i == 0.0, 1.0, l_i)
    return (acc / l_safe[..., None]).astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """shard_map wrapper: (B,H,S,D) with batch on data+fsdp, heads on tensor,
    sequence on seq."""
    spec = P(("data", "fsdp"), "tensor", "seq", None)
    fn = functools.partial(
        ring_attention, axis_name="seq", causal=causal, sm_scale=sm_scale
    )
    from ..utils.jaxcompat import shard_map

    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)
