"""Device-mesh construction: the scheduler → JAX workload bridge.

The scheduler hands a pod its chip allocation as ICI mesh *coordinates* in
annotations (core/annotations.py).  This module turns that allocation into a
``jax.sharding.Mesh`` whose axis layout matches the physical ICI links, so
XLA collectives (psum / all_gather / reduce_scatter / ppermute) ride ICI
rather than hopping hosts — the placement property the scheduler worked to
provide (north star, BASELINE.json).

Axis convention for the flagship model (parallel/sharding.py):

    data    — pure data parallelism (gradient psum)
    fsdp    — fully-sharded data parallel (param all-gather / grad
              reduce-scatter)
    expert  — expert parallelism for MoE layers (models/moe.py)
    pipe    — pipeline parallelism over layer groups (parallel/pipeline.py)
    tensor  — tensor/model parallelism (Megatron-style sharded matmuls)
    seq     — sequence/context parallelism (ring attention, parallel/ring.py)

No analogous code exists in the reference (it schedules containers, not
meshes — SURVEY §2 #19/#20); this is the TPU-native capability that slot
maps to.
"""

from __future__ import annotations


from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from ..core.topology import Coord, parse_coord

AXES = ("data", "fsdp", "expert", "pipe", "tensor", "seq")


@dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape: axis name → size.  Product must equal #devices."""

    data: int = 1
    fsdp: int = 1
    expert: int = 1
    pipe: int = 1
    tensor: int = 1
    seq: int = 1

    @property
    def sizes(self) -> dict[str, int]:
        return {
            "data": self.data,
            "fsdp": self.fsdp,
            "expert": self.expert,
            "pipe": self.pipe,
            "tensor": self.tensor,
            "seq": self.seq,
        }

    @property
    def num_devices(self) -> int:
        return (
            self.data * self.fsdp * self.expert * self.pipe * self.tensor
            * self.seq
        )

    @classmethod
    def for_devices(
        cls, n: int, tensor: int = 1, seq: int = 1, fsdp: Optional[int] = None
    ) -> "MeshSpec":
        """Default factoring: given tensor/seq, put the rest in fsdp (or
        split data×fsdp when ``fsdp`` is given)."""
        rest, r = divmod(n, tensor * seq)
        if r:
            raise ValueError(f"{n} devices not divisible by tensor*seq={tensor*seq}")
        if fsdp is None:
            return cls(data=1, fsdp=rest, tensor=tensor, seq=seq)
        data, r = divmod(rest, fsdp)
        if r:
            raise ValueError(f"residual {rest} not divisible by fsdp={fsdp}")
        return cls(data=data, fsdp=fsdp, tensor=tensor, seq=seq)


def make_mesh(
    spec: MeshSpec, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build a Mesh over the given (or all) devices, ICI-ordered when the
    devices expose coords (real TPU), enumeration-ordered otherwise (CPU)."""
    devs = list(devices) if devices is not None else list(jax.devices())
    if len(devs) != spec.num_devices:
        raise ValueError(
            f"mesh spec needs {spec.num_devices} devices, have {len(devs)}"
        )
    devs = _ici_order(devs)
    arr = np.array(devs, dtype=object).reshape(
        spec.data, spec.fsdp, spec.expert, spec.pipe, spec.tensor, spec.seq
    )
    return Mesh(arr, AXES)


def _ici_order(devs: list[jax.Device]) -> list[jax.Device]:
    """Sort devices by physical mesh coordinates when available so adjacent
    mesh positions are ICI neighbors."""

    def key(d):
        c = getattr(d, "coords", None)
        if c is None:
            return (0, d.id)
        return (0, *tuple(c), getattr(d, "core_on_chip", 0))

    try:
        return sorted(devs, key=key)
    except TypeError:  # heterogeneous keys; keep enumeration order
        return devs


def _slice_partition(
    devs: list[jax.Device], n_slices: int
) -> list[list[jax.Device]]:
    """Partition devices into per-slice groups.

    Real multi-slice TPU devices expose ``slice_index``; group by it (and
    ICI-order within each slice).  CPU simulation has no slice attribute:
    contiguous equal chunks stand in, which preserves the property the
    hierarchical mesh needs — each group's devices are "ICI-local" to
    each other and the boundary between groups is the DCN."""
    by_slice: dict[int, list[jax.Device]] = {}
    for d in devs:
        si = getattr(d, "slice_index", None)
        if si is None:
            by_slice = {}
            break
        by_slice.setdefault(si, []).append(d)
    if by_slice:
        if len(by_slice) != n_slices:
            # the hardware's slice count is authoritative; chunking a
            # 3-real-slice device list into 2 "slices" would put a DCN
            # boundary inside an "ICI-local" group — fail loudly instead
            raise ValueError(
                f"devices span {len(by_slice)} hardware slices but the "
                f"gang annotation says {n_slices}; stale placement?"
            )
        return [_ici_order(by_slice[k]) for k in sorted(by_slice)]
    if len(devs) % n_slices:
        raise ValueError(
            f"{len(devs)} devices not divisible by {n_slices} slices"
        )
    per = len(devs) // n_slices
    return [devs[i * per : (i + 1) * per] for i in range(n_slices)]


def hierarchical_mesh(
    spec: MeshSpec,
    n_slices: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Multi-slice mesh for a gang that STRADDLES the DCN boundary.

    The DATA axis is the outer, slowest-varying axis and spans slices —
    pure data parallelism's one gradient all-reduce per step is the only
    collective that can afford DCN latency (the scaling-book recipe).
    Every other axis (fsdp/expert/pipe/tensor/seq) lays out INSIDE one
    slice, so param all-gathers, grad reduce-scatters, TP reductions and
    ring hops all ride ICI.

    Device order is slice-major: with ``data`` leading the axis tuple,
    the slice boundary falls exactly between data-axis blocks, so XLA's
    intra-slice collectives get replica groups wholly within a slice and
    the cross-slice all-reduce pairs same-position devices across slices
    (test_sharding_collectives.py asserts this on the lowered HLO).

    Requires ``spec.data % n_slices == 0`` and the per-slice device count
    to equal ``(data // n_slices) × fsdp × expert × pipe × tensor × seq``.
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    if spec.data % n_slices:
        raise ValueError(
            f"data axis {spec.data} must be divisible by {n_slices} "
            "slices (the DCN boundary lives inside the data axis)"
        )
    if len(devs) != spec.num_devices:
        raise ValueError(
            f"mesh spec needs {spec.num_devices} devices, have {len(devs)}"
        )
    groups = _slice_partition(devs, n_slices)
    inner = spec.num_devices // spec.data
    per_slice = (spec.data // n_slices) * inner
    for g in groups:
        if len(g) != per_slice:
            raise ValueError(
                f"slice group of {len(g)} devices != {per_slice} "
                "(= data/n_slices × inner axes); the gang placement does "
                "not tile the mesh spec"
            )
    flat = [d for g in groups for d in g]  # slice-major
    arr = np.array(flat, dtype=object).reshape(
        spec.data, spec.fsdp, spec.expert, spec.pipe, spec.tensor, spec.seq
    )
    return Mesh(arr, AXES)


def classify_replica_groups(
    hlo_text: str, per_slice: int
) -> tuple[list[list[int]], list[list[int]]]:
    """Parse every replica group out of compiled HLO and split them into
    (cross_slice, intra_slice) by whether a group's device ids fall on
    both sides of the ``per_slice`` boundary.  The hierarchical-mesh
    evidence check shared by tests/test_sharding_collectives.py and the
    driver's dryrun config E."""
    import re

    groups = [
        [int(x) for x in g.split(",")]
        for m in re.finditer(r"replica_groups=\{(\{[0-9,{}]+\})\}", hlo_text)
        for g in re.findall(r"\{([0-9,]+)\}", m.group(1))
    ]
    crosses = [g for g in groups if len({d // per_slice for d in g}) > 1]
    intra = [
        g for g in groups
        if len(g) > 1 and len({d // per_slice for d in g}) == 1
    ]
    return crosses, intra


def gang_slices_from_annotations(annotations: dict[str, str]) -> list[str]:
    """The ordered slice list a straddling gang's commit wrote (empty for
    single-slice placements — scheduler/gang.py annotates only when the
    plan crosses the DCN)."""
    from ..utils import consts

    raw = annotations.get(consts.ANNOTATION_GANG_SLICES, "")
    return [s for s in raw.split(",") if s]


def coords_from_annotations(
    annotations: dict[str, str], container: str
) -> list[Coord]:
    """Parse the scheduler's chip-coordinate annotation for a container."""
    from ..utils import consts

    raw = annotations.get(consts.ANNOTATION_CONTAINER_PREFIX + container, "")
    return [parse_coord(p) for p in raw.split(",") if p]


def gang_rank_order(devs: list[jax.Device]) -> list[jax.Device]:
    """Global device order for a multi-host gang mesh: gang-rank-major
    (process id == the scheduler's journaled gang rank by construction —
    parallel/distributed.initialize_for_gang), ICI-ordered within each
    member's chips.  Every process computes this order from the SAME
    global ``jax.devices()`` list, so all gang members agree on the
    mesh layout without exchanging a byte beyond jax.distributed's own
    handshake."""

    def key(d):
        c = getattr(d, "coords", None)
        pi = getattr(d, "process_index", 0)
        if c is None:
            return (pi, 0, d.id)
        return (pi, 0, *tuple(c), getattr(d, "core_on_chip", 0))

    try:
        return sorted(devs, key=key)
    except TypeError:  # heterogeneous keys; keep enumeration order
        return devs


def gang_mesh(
    spec: MeshSpec,
    annotations: Optional[dict] = None,
    coordinator: str = "",
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """ONE SPMD mesh for a scheduler-planned gang, single- or
    multi-host.

    The scheduler already plans multi-node gangs and journals each
    member's rank + ordered peer list at commit
    (``elasticgpu.io/gang-rank`` / ``gang-peers``); this consumes that
    ledger: ``jax.distributed`` initializes with process_id = rank and
    coordinator = peer 0 (so the planned placement IS the process
    layout), then the global device view is laid out gang-rank-major /
    ICI-ordered-within-member and reshaped to ``spec``.  Collectives
    ride ICI within a member's chips and the cross-host fabric between
    members — the mesh the fleet's live gang resize drains and reshards
    around.

    A gang of one (or no gang annotations at all) builds EXACTLY
    ``make_mesh(spec)`` — single-host parity is a tested invariant, so
    existing single-process deployments keep their mesh bit-for-bit.

    ``coordinator`` overrides the derived peer-0 address (tests, or
    deployments whose coordinator DNS differs from the pod name).
    """
    from .distributed import gang_info_from_annotations, initialize_for_gang

    rank, size, _peers = gang_info_from_annotations(annotations or {})
    if size > 1 and devices is None:
        initialize_for_gang(annotations or {}, coordinator=coordinator)
    devs = list(devices) if devices is not None else list(jax.devices())
    if size <= 1:
        return make_mesh(spec, devs)
    if len(devs) != spec.num_devices:
        raise ValueError(
            f"gang mesh spec needs {spec.num_devices} devices, have "
            f"{len(devs)} across {size} members"
        )
    flat = gang_rank_order(devs)
    arr = np.array(flat, dtype=object).reshape(
        spec.data, spec.fsdp, spec.expert, spec.pipe, spec.tensor, spec.seq
    )
    return Mesh(arr, AXES)


def mesh_from_allocation(
    annotations: dict[str, str],
    container: str,
    spec: MeshSpec,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the job's Mesh from its pod's allocation annotations.

    On real TPU hardware, devices whose ``.coords`` match the allocated chip
    coordinates are selected and laid out in allocation order (the scheduler
    allocated a contiguous sub-box, so allocation order == ICI order).  When
    device coords are unavailable (CPU simulation / tests), the first
    ``spec.num_devices`` devices stand in.
    """
    alloc = coords_from_annotations(annotations, container)
    devs = list(devices) if devices is not None else list(jax.devices())
    by_coord = {}
    for d in devs:
        c = getattr(d, "coords", None)
        if c is not None:
            by_coord[tuple(c)] = d
    chosen: list[jax.Device] = []
    if alloc and by_coord:
        for c in alloc:
            d = by_coord.get(tuple(c))
            if d is None:
                break
            chosen.append(d)
    if len(chosen) != spec.num_devices:
        chosen = devs[: spec.num_devices]
    return make_mesh(spec, chosen)
