"""Device-mesh construction: the scheduler → JAX workload bridge.

The scheduler hands a pod its chip allocation as ICI mesh *coordinates* in
annotations (core/annotations.py).  This module turns that allocation into a
``jax.sharding.Mesh`` whose axis layout matches the physical ICI links, so
XLA collectives (psum / all_gather / reduce_scatter / ppermute) ride ICI
rather than hopping hosts — the placement property the scheduler worked to
provide (north star, BASELINE.json).

Axis convention for the flagship model (parallel/sharding.py):

    data    — pure data parallelism (gradient psum)
    fsdp    — fully-sharded data parallel (param all-gather / grad
              reduce-scatter)
    expert  — expert parallelism for MoE layers (models/moe.py)
    pipe    — pipeline parallelism over layer groups (parallel/pipeline.py)
    tensor  — tensor/model parallelism (Megatron-style sharded matmuls)
    seq     — sequence/context parallelism (ring attention, parallel/ring.py)

No analogous code exists in the reference (it schedules containers, not
meshes — SURVEY §2 #19/#20); this is the TPU-native capability that slot
maps to.
"""

from __future__ import annotations


from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from ..core.topology import Coord, parse_coord

AXES = ("data", "fsdp", "expert", "pipe", "tensor", "seq")


@dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape: axis name → size.  Product must equal #devices."""

    data: int = 1
    fsdp: int = 1
    expert: int = 1
    pipe: int = 1
    tensor: int = 1
    seq: int = 1

    @property
    def sizes(self) -> dict[str, int]:
        return {
            "data": self.data,
            "fsdp": self.fsdp,
            "expert": self.expert,
            "pipe": self.pipe,
            "tensor": self.tensor,
            "seq": self.seq,
        }

    @property
    def num_devices(self) -> int:
        return (
            self.data * self.fsdp * self.expert * self.pipe * self.tensor
            * self.seq
        )

    @classmethod
    def for_devices(
        cls, n: int, tensor: int = 1, seq: int = 1, fsdp: Optional[int] = None
    ) -> "MeshSpec":
        """Default factoring: given tensor/seq, put the rest in fsdp (or
        split data×fsdp when ``fsdp`` is given)."""
        rest, r = divmod(n, tensor * seq)
        if r:
            raise ValueError(f"{n} devices not divisible by tensor*seq={tensor*seq}")
        if fsdp is None:
            return cls(data=1, fsdp=rest, tensor=tensor, seq=seq)
        data, r = divmod(rest, fsdp)
        if r:
            raise ValueError(f"residual {rest} not divisible by fsdp={fsdp}")
        return cls(data=data, fsdp=fsdp, tensor=tensor, seq=seq)


def make_mesh(
    spec: MeshSpec, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build a Mesh over the given (or all) devices, ICI-ordered when the
    devices expose coords (real TPU), enumeration-ordered otherwise (CPU)."""
    devs = list(devices) if devices is not None else list(jax.devices())
    if len(devs) != spec.num_devices:
        raise ValueError(
            f"mesh spec needs {spec.num_devices} devices, have {len(devs)}"
        )
    devs = _ici_order(devs)
    arr = np.array(devs, dtype=object).reshape(
        spec.data, spec.fsdp, spec.expert, spec.pipe, spec.tensor, spec.seq
    )
    return Mesh(arr, AXES)


def _ici_order(devs: list[jax.Device]) -> list[jax.Device]:
    """Sort devices by physical mesh coordinates when available so adjacent
    mesh positions are ICI neighbors."""

    def key(d):
        c = getattr(d, "coords", None)
        if c is None:
            return (0, d.id)
        return (0, *tuple(c), getattr(d, "core_on_chip", 0))

    try:
        return sorted(devs, key=key)
    except TypeError:  # heterogeneous keys; keep enumeration order
        return devs


def coords_from_annotations(
    annotations: dict[str, str], container: str
) -> list[Coord]:
    """Parse the scheduler's chip-coordinate annotation for a container."""
    from ..utils import consts

    raw = annotations.get(consts.ANNOTATION_CONTAINER_PREFIX + container, "")
    return [parse_coord(p) for p in raw.split(",") if p]


def mesh_from_allocation(
    annotations: dict[str, str],
    container: str,
    spec: MeshSpec,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the job's Mesh from its pod's allocation annotations.

    On real TPU hardware, devices whose ``.coords`` match the allocated chip
    coordinates are selected and laid out in allocation order (the scheduler
    allocated a contiguous sub-box, so allocation order == ICI order).  When
    device coords are unavailable (CPU simulation / tests), the first
    ``spec.num_devices`` devices stand in.
    """
    alloc = coords_from_annotations(annotations, container)
    devs = list(devices) if devices is not None else list(jax.devices())
    by_coord = {}
    for d in devs:
        c = getattr(d, "coords", None)
        if c is not None:
            by_coord[tuple(c)] = d
    chosen: list[jax.Device] = []
    if alloc and by_coord:
        for c in alloc:
            d = by_coord.get(tuple(c))
            if d is None:
                break
            chosen.append(d)
    if len(chosen) != spec.num_devices:
        chosen = devs[: spec.num_devices]
    return make_mesh(spec, chosen)
