"""Virtual time for the digital twin.

A ``VirtualClock`` is a plain callable — drop-in for ``time.monotonic``
everywhere a subsystem accepts a ``clock=`` hook (SloPlane, Autoscaler,
DefragPlanner, Journal.wall_clock).  Time only moves when the scenario
runner advances it, so a day of simulated workload folds into however
many wall-seconds the event loop needs — and two same-seed runs read
IDENTICAL timestamps, which is what makes twin journals byte-identical
across runs.
"""

from __future__ import annotations


class VirtualClock:
    """Monotonic simulated time.  ``clock()`` reads, ``advance``/
    ``advance_to`` move it forward; moving backward is refused (the
    subsystems fed by this clock assume monotonic time, exactly like
    ``time.monotonic``)."""

    __slots__ = ("_now",)

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def __call__(self) -> float:
        return self._now

    def now(self) -> float:
        return self._now

    def advance(self, dt: float) -> float:
        if dt < 0:
            raise ValueError(f"virtual clock cannot move backward ({dt})")
        self._now += dt
        return self._now

    def advance_to(self, t: float) -> float:
        """Jump to absolute sim time ``t`` (no-op if already past it)."""
        if t > self._now:
            self._now = float(t)
        return self._now

    def __repr__(self) -> str:
        return f"VirtualClock(t={self._now:.3f})"
