"""CLI for the digital twin.

    python -m elastic_gpu_scheduler_tpu.twin run --synthetic --duration 1800
    python -m elastic_gpu_scheduler_tpu.twin run --journal /var/log/egs/journal
    python -m elastic_gpu_scheduler_tpu.twin autosearch --journal DIR --rounds 4

``run`` replays a recorded journal (or a synthetic growth scenario)
under virtual time and prints the score report; ``autosearch`` evolves
scoring-policy candidates against the recording and prints the ranked
report.  Neither touches live state.
"""

from __future__ import annotations

import argparse
import json
import sys

from .autosearch import autosearch
from .runner import TwinScenario, run_scenario


def _cmd_run(args: argparse.Namespace) -> int:
    events = None
    if args.journal:
        from ..journal import read_journal

        events = read_journal(args.journal)
        if not events:
            print(f"no journal records under {args.journal}",
                  file=sys.stderr)
            return 1
    scenario = TwinScenario(
        name=args.name,
        mode="recorded" if args.journal else "synthetic",
        seed=args.seed,
        duration_s=args.duration,
        step_s=args.step,
        arrival_scale=args.scale,
        growth=args.growth,
        rater=args.rater,
        defrag_mode=args.defrag,
        out_dir=args.out,
    )
    report = run_scenario(scenario, events=events)
    if args.json:
        json.dump(report, sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        pk = report["packing"]
        slo = report["slo"]
        sc = report["scenario"]
        name = sc.get("name", "twin") if isinstance(sc, dict) else sc
        print(f"twin '{name}' ({report['mode']}, "
              f"seed={report['seed']}): {report['sim_duration_s']:.0f}s "
              f"simulated in {report['wall_s']:.2f}s wall "
              f"({report['speedup_vs_wall']:.0f}x)")
        print(f"  packing: {pk['placed']} placed / {pk['unplaced']} "
              f"unplaced, contiguous={pk['contiguous_frac']:.3f}, "
              f"frag={pk['final_frag_mean']:.3f}, "
              f"free_chip_frac={pk['mean_free_chip_frac']:.3f}")
        print(f"  slo: journeys={report['journeys']}, "
              f"burning={slo['posture'].get('burning')}, "
              f"breaches={slo['breaches']}")
        print(f"  replay: {report['replay']['records']} records, "
              f"{len(report['replay']['violations'])} violations")
        print(f"  journal: {report['journal_dir']}")
    return 2 if report["replay"]["violations"] else 0


def _cmd_autosearch(args: argparse.Namespace) -> int:
    from ..journal import read_journal

    events = read_journal(args.journal)
    if not events:
        print(f"no journal records under {args.journal}", file=sys.stderr)
        return 1
    report = autosearch(
        events,
        seed=args.seed,
        rounds=args.rounds,
        population=args.population,
        tolerance=args.tolerance,
    )
    if args.json:
        json.dump(report, sys.stdout, indent=2, sort_keys=True,
                  default=str)
        print()
    else:
        inc = report["incumbent"]
        print(f"autosearch seed={report['seed']} "
              f"rounds={report['rounds']} "
              f"evaluated={report['evaluated']}")
        print(f"  incumbent {inc['name']}: {inc['stats']}")
        beats = report["beats_incumbent"]
        print(f"  {len(beats)} candidate(s) beat the incumbent on "
              f"rater-neutral metrics:")
        for row in beats:
            print(f"    fitness={row['fitness']} wins={row['wins']}")
            print(f"      {row['source']}")
        print(f"  {report['promotion']}")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m elastic_gpu_scheduler_tpu.twin",
        description="digital-twin fleet simulation and policy autosearch",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    run_p = sub.add_parser("run", help="run a twin scenario")
    run_p.add_argument("--journal", default="",
                       help="recorded journal dir to replay (omit for "
                            "a synthetic scenario)")
    run_p.add_argument("--synthetic", action="store_true",
                       help="force synthetic mode (default when no "
                            "--journal)")
    run_p.add_argument("--name", default="twin")
    run_p.add_argument("--duration", type=float, default=1800.0,
                       help="simulated seconds (default 1800)")
    run_p.add_argument("--step", type=float, default=1.0)
    run_p.add_argument("--seed", type=int, default=20260807)
    run_p.add_argument("--scale", type=float, default=1.0,
                       help="arrival-rate multiplier (what-if load)")
    run_p.add_argument("--growth", type=float, default=1.0,
                       help="arrival growth over the run (2.0 = "
                            "doubles by the end)")
    run_p.add_argument("--rater", default="binpack",
                       help="builtin rater name or a policy score "
                            "expression")
    run_p.add_argument("--defrag", default="auto",
                       choices=("off", "observe", "auto"))
    run_p.add_argument("--out", default=None,
                       help="twin journal output dir (default: tmpdir)")
    run_p.add_argument("--json", action="store_true")
    run_p.set_defaults(fn=_cmd_run)

    as_p = sub.add_parser("autosearch",
                          help="evolve scoring policies on a recording")
    as_p.add_argument("--journal", required=True)
    as_p.add_argument("--rounds", type=int, default=4)
    as_p.add_argument("--population", type=int, default=12)
    as_p.add_argument("--seed", type=int, default=20260807)
    as_p.add_argument("--tolerance", type=float, default=0.02)
    as_p.add_argument("--json", action="store_true")
    as_p.set_defaults(fn=_cmd_autosearch)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
