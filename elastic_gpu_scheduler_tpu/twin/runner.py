"""The twin scenario runner: time-warped fleet simulation.

A ``TwinRunner`` replays a recorded journal workload — or a seeded
synthetic growth curve — through the REAL decision code paths under a
``VirtualClock``:

- placement searches run through ``ChipSet.trade`` with the real raters
  (binpack / spread / policy expressions), exactly the engine the live
  scheduler binds through;
- scaling decisions run through the real ``PolicyEngine.evaluate``
  state machine (hysteresis, cooldowns, SLO-burn veto);
- defrag rounds run through the real ``DefragPlanner.plan`` over shim
  engines (planning on clones, moves applied by the runner);
- SLO burn runs through a fresh ``SloPlane`` fed synthesized journeys
  whose latency population reproduces the fitted quantiles.

Isolation contract: the runner builds FRESH instances of everything —
its own ``Journal``, its own ``SloPlane``, its own ``PolicyEngine`` and
``DefragPlanner``, its own ChipSets.  It never reads or writes the
process-global ``JOURNAL`` / ``SLO`` / ``PROFILER`` singletons, so a
twin run on a live control plane leaves live scheduler state, journal
sequence numbers, and metrics untouched (tests/test_twin.py holds this
as a regression).

The twin journal is a REAL journal: it replays through the existing
``ReplayEngine`` invariant checks (chip conservation, dense seqs,
double-bind/double-free), and its head/tail ``twin`` annotation records
mark the stream as simulated.  Virtual timestamps + a single seeded RNG
make two same-seed runs byte-identical.
"""

from __future__ import annotations

import math
import random
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..core.allocator import ChipSet
from ..core.rater import Binpack, ICILocality, Random as RandomRater, Spread
from ..core.request import TPURequest, TPUUnit
from ..journal import Journal, option_record, read_journal
from ..journal.replay import (
    chipset_from_record,
    option_from_record,
    replay,
    request_from_option,
)
from ..utils import consts
from .clock import VirtualClock
from .model import (
    WorkloadModel,
    fit_workload_model,
    objectives_spec_from_events,
    sample_latency,
    synthesize_model,
)

# last completed run's report — the /debug/twin payload
_LAST_LOCK = threading.Lock()
_LAST_REPORT: Optional[dict] = None

_BUILTIN_RATERS = {
    "binpack": Binpack,
    "spread": Spread,
    "ici-locality": ICILocality,
    "random": RandomRater,
}

# default objectives when a scenario carries none and the recording
# never journaled a load — matches the check-slo fixture shape
_DEFAULT_SLO_SPEC = {
    "window_short_s": 60,
    "window_long_s": 300,
    "burn_threshold": 1.0,
    "min_samples": 5,
    "classes": {
        "default": {"e2e_p95_ms": 2000.0, "availability": 0.99},
    },
}

# synthetic fleet templates: (generation, host dims, hbm GiB/chip) —
# the fleetgen host shape (4 chips per host, 2x2); tools/fleetgen.py's
# ``twin_fleet`` builds richer slice-tiled mixes in this same wire form
_SYNTH_TEMPLATES = (
    ("v5e", (2, 2), 16),
    ("v5e", (2, 2), 16),
    ("v5p", (2, 2), 95),
    ("v6e", (2, 2), 24),
)


def synthesize_fleet(nodes: int = 4, seed: int = 20260807) -> list:
    """Seeded synthetic node specs in journal ``node_add`` wire form:
    ``{"node", "generation", "dims", "wrap", "chips"}`` — the runner
    feeds each through ``chipset_from_record`` so a synthetic fleet is
    built by the exact decoder replay uses for recorded ones."""
    rng = random.Random(seed)
    out = []
    for i in range(nodes):
        gen, dims, hbm = _SYNTH_TEMPLATES[rng.randrange(
            len(_SYNTH_TEMPLATES)
        )]
        coords = []
        for x in range(dims[0]):
            for y in range(dims[1]):
                coords.append([x, y])
        out.append({
            "node": f"twin-{gen}-{i}",
            "generation": gen,
            "dims": list(dims),
            "wrap": [False] * len(dims),
            "chips": [[c, consts.CORE_PER_CHIP, hbm] for c in coords],
        })
    return out


def resolve_twin_rater(spec) -> object:
    """Rater for a twin scenario: an already-built Rater object passes
    through (autosearch candidates); a built-in name resolves to a fresh
    instance; anything else is compiled as a policy EXPRESSION with a
    binpack fallback — the twin never reads the live POLICIES registry,
    so a what-if cannot depend on (or perturb) loaded policy state."""
    if not isinstance(spec, str):
        return spec  # duck-typed Rater
    name = spec.strip()
    if name in _BUILTIN_RATERS:
        return _BUILTIN_RATERS[name]()
    from ..policy.lang import compile_expr
    from ..policy.rater import PolicyRater, SCORE_INPUTS

    program = compile_expr(name, SCORE_INPUTS)
    return PolicyRater(program, fallback=Binpack(), name="twin-expr")


@dataclass
class TwinScenario:
    """One simulation's knobs.  ``mode`` is ``recorded`` (replay a
    journal's bind/forget stream, re-placing with the scenario rater)
    or ``synthetic`` (generate arrivals from the workload model, with
    ``arrival_scale``/``growth`` warping the curve for what-ifs)."""

    name: str = "twin"
    mode: str = "synthetic"  # recorded | synthetic
    seed: int = 20260807
    duration_s: float = 1800.0  # simulated span (≥30 sim-minutes default)
    step_s: float = 1.0
    arrival_scale: float = 1.0  # journey-rate multiplier (what-if load)
    growth: float = 1.0  # rate multiplier reached at duration end (ramp)
    rater: str = "binpack"
    replicas: int = 2  # serving replicas at t=0 (autoscaler's fleet)
    chips_per_replica: int = 4
    slo: Optional[dict] = None  # SloPlane.load_config spec override
    policy: Optional[dict] = None  # ScalingPolicy kwargs override
    autoscaler_interval_s: float = 5.0
    defrag_mode: str = "auto"  # off disables twin defrag rounds
    defrag_threshold: float = 0.5
    defrag_interval_s: float = 30.0
    nodes: int = 4  # synthetic fleet size when ``fleet`` is None
    fleet: Optional[list] = None  # node_add-shaped specs (fleetgen)
    out_dir: Optional[str] = None  # twin journal dir (tempdir when None)

    def to_dict(self) -> dict:
        return {
            "name": self.name, "mode": self.mode, "seed": self.seed,
            "duration_s": self.duration_s, "step_s": self.step_s,
            "arrival_scale": self.arrival_scale, "growth": self.growth,
            "rater": self.rater if isinstance(self.rater, str)
            else getattr(self.rater, "name", "custom"),
            "replicas": self.replicas,
            "chips_per_replica": self.chips_per_replica,
            "slo": self.slo, "policy": self.policy,
            "autoscaler_interval_s": self.autoscaler_interval_s,
            "defrag_mode": self.defrag_mode,
            "defrag_threshold": self.defrag_threshold,
            "defrag_interval_s": self.defrag_interval_s,
            "nodes": self.nodes,
            "out_dir": self.out_dir,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TwinScenario":
        kwargs = {}
        for f_ in ("name", "mode", "rater", "out_dir", "defrag_mode"):
            if d.get(f_) is not None:
                kwargs[f_] = d[f_]
        for f_ in ("seed", "replicas", "chips_per_replica", "nodes"):
            if d.get(f_) is not None:
                kwargs[f_] = int(d[f_])
        for f_ in ("duration_s", "step_s", "arrival_scale", "growth",
                   "autoscaler_interval_s", "defrag_threshold",
                   "defrag_interval_s"):
            if d.get(f_) is not None:
                kwargs[f_] = float(d[f_])
        for f_ in ("slo", "policy"):
            if isinstance(d.get(f_), dict):
                kwargs[f_] = d[f_]
        if isinstance(d.get("fleet"), list):
            kwargs["fleet"] = d["fleet"]
        sc = cls(**kwargs)
        if sc.mode not in ("recorded", "synthetic"):
            raise ValueError(f"twin mode {sc.mode!r} not in "
                             "('recorded', 'synthetic')")
        if sc.duration_s <= 0 or sc.step_s <= 0:
            raise ValueError("twin duration_s/step_s must be positive")
        return sc


# -- defrag shims -------------------------------------------------------------
# DefragPlanner plans over any object exposing the engine surface it
# reads (lock / allocators / pod_maps) plus a clientset with get_pod.
# The runner owns move EXECUTION (the planner's execute path calls the
# live scheduler's migrate_pod; the twin applies moves to its own
# ChipSets and journals the migrate records itself).


class _NodeShim:
    __slots__ = ("lock", "chips", "generation")

    def __init__(self, chips: ChipSet, generation: str):
        self.lock = threading.Lock()
        self.chips = chips
        self.generation = generation


class _SchedShim:
    def __init__(self):
        self.lock = threading.Lock()
        self.allocators: dict[str, _NodeShim] = {}
        self.pod_maps: dict[str, tuple] = {}

    def frag_snapshot(self) -> dict:
        with self.lock:
            allocs = dict(self.allocators)
        out = {}
        for name, na in allocs.items():
            with na.lock:
                idx, largest, _free = na.chips.fragmentation()
            out[name] = (idx, largest)
        return out


class _ClientsetShim:
    """get_pod over the runner's simulated pod table."""

    def __init__(self):
        self.pods: dict[str, object] = {}  # "ns/name" → Pod

    def get_pod(self, namespace: str, name: str):
        return self.pods[f"{namespace}/{name}"]


@dataclass
class _SimPod:
    """One simulated tenant: its placement plus departure time."""

    key: str
    uid: str
    wclass: str
    node: str
    option: object
    chips_equiv: float
    expires_at: Optional[float] = None  # None = recorded forget drives it


class TwinRunner:
    """One scenario, one run.  Single-threaded by design: the event loop
    is the only writer, which is what lets two same-seed runs produce
    byte-identical journals (dict insertion order, seq order and virtual
    timestamps are all deterministic)."""

    def __init__(self, scenario: TwinScenario, events: Optional[list] = None,
                 slo_state: Optional[dict] = None,
                 model: Optional[WorkloadModel] = None,
                 rater=None):
        from ..defrag import DefragPlanner
        from ..fleet.autoscaler import PolicyEngine, ScalingPolicy
        from ..slo import SloPlane

        self.scenario = scenario
        self.events = events or []
        if scenario.mode == "recorded" and not self.events:
            raise ValueError("recorded twin mode needs journal events")
        self.clock = VirtualClock()
        self.rng = random.Random(scenario.seed)
        self.rater = rater if rater is not None else resolve_twin_rater(
            scenario.rater
        )

        # workload model: explicit > fitted-from-recording > synthetic
        if model is not None:
            self.model = model
        elif self.events:
            self.model = fit_workload_model(self.events, slo_state)
        else:
            self.model = synthesize_model(scenario.seed)

        # the twin's OWN journal, stamped with virtual time.  The ALL-CAPS
        # attribute name keeps the journal-discipline lint honest: it
        # recognizes `JOURNAL.record(...)` receivers as the choke point,
        # and the twin's mutations must journal HERE, never globally.
        self.out_dir = scenario.out_dir or tempfile.mkdtemp(prefix="twin-")
        self.JOURNAL = Journal()
        self.JOURNAL.wall_clock = self.clock
        self.JOURNAL.configure(self.out_dir, fsync="off")

        # fresh SLO plane on the virtual clock, sinking into OUR journal
        self.plane = SloPlane(clock=self.clock)
        self.plane.journal = self.JOURNAL
        spec = (scenario.slo or objectives_spec_from_events(self.events)
                or _DEFAULT_SLO_SPEC)
        # a twin run folds ~duration/step journeys per class — keep the
        # recorded min_samples so burn math matches the live plane's
        self.plane.load_config(spec)

        # real scaling state machine
        self.engine = PolicyEngine(ScalingPolicy(**(scenario.policy or {})))
        self.replicas = max(
            self.engine.policy.min_replicas,
            min(scenario.replicas, self.engine.policy.max_replicas),
        )

        # fleet + defrag shims
        self.sched = _SchedShim()
        self.clientset = _ClientsetShim()
        self.planner = DefragPlanner(
            engines=[self.sched],
            clientset=self.clientset,
            mode=scenario.defrag_mode if scenario.defrag_mode == "auto"
            else "observe",
            threshold=scenario.defrag_threshold,
            min_interval_s=scenario.defrag_interval_s,
            clock=self.clock,
        )
        self.defrag_enabled = scenario.defrag_mode != "off"

        # sim state
        self.pods: dict[str, _SimPod] = {}
        self.backlog = 0.0  # queued requests (autoscaler signal source)
        self.binds = self.unplaced = self.forgets = 0
        self.contiguous = 0
        self.scores: list[float] = []
        self.migrations = 0
        self.fleet_decisions: dict[str, int] = {}
        self.journeys = 0
        self.bind_walls: list[float] = []  # wall seconds per placement
        self._arrival_acc: dict[str, float] = {}
        self._pod_serial = 0
        # per-class token accounting for the model-drift gate
        self._served_tokens: dict[str, float] = {}
        self._chip_seconds: dict[str, float] = {}
        self._eff_tput_acc: dict[str, list] = {}  # [eff·dt sum, dt sum]

    # -- fleet construction ---------------------------------------------------

    def _node_specs(self) -> list:
        if self.scenario.mode == "recorded":
            specs: dict[str, dict] = {}
            for rec in self.events:
                if rec.get("type") in ("node_add", "node_resync"):
                    specs[rec["node"]] = {
                        "node": rec["node"],
                        "generation": rec.get("generation") or "v5e",
                        "dims": rec["dims"],
                        "wrap": rec["wrap"],
                        "chips": rec["chips"],
                    }
                elif rec.get("type") == "checkpoint" and not specs:
                    for name, inv in sorted(
                        (rec.get("nodes") or {}).items()
                    ):
                        specs[name] = {"node": name, "generation": "v5e",
                                       **inv}
            if not specs:
                raise ValueError(
                    "recorded twin mode: journal holds no node_add records"
                )
            return [specs[n] for n in sorted(specs)]
        return self.scenario.fleet or synthesize_fleet(
            self.scenario.nodes, self.scenario.seed
        )

    def _boot_fleet(self) -> None:
        for spec in self._node_specs():
            cs = chipset_from_record(spec)
            self.sched.allocators[spec["node"]] = _NodeShim(
                cs, spec["generation"]
            )
            self.JOURNAL.record(
                "node_add", node=spec["node"],
                generation=spec["generation"], dims=list(spec["dims"]),
                wrap=list(spec["wrap"]),
                chips=[list(c) for c in spec["chips"]],
            )

    # -- placement ------------------------------------------------------------

    def _place(self, req: TPURequest, wclass: str,
               prefer_node: Optional[str] = None):
        """(node, Option) via the real placement search: try the
        preferred node first (recorded mode re-places on the recorded
        node, the what-if stance), else rate over every node and take
        the best score — the scheduler's find-best loop in miniature."""
        t0 = time.perf_counter()
        best = None
        names = ([prefer_node] if prefer_node else
                 sorted(self.sched.allocators))
        for name in names:
            shim = self.sched.allocators.get(name)
            if shim is None:
                continue
            opt = shim.chips.trade(req, self.rater)
            if opt is not None and (best is None or opt.score > best[2]):
                best = (name, opt, opt.score)
        self.bind_walls.append(time.perf_counter() - t0)
        if best is None:
            return None
        return best[0], best[1]

    def _bind(self, key: str, uid: str, wclass: str, node: str, opt,
              expires_at: Optional[float], source: str) -> None:
        shim = self.sched.allocators[node]
        shim.chips.transact(opt)
        chips_equiv = sum(
            len(a.coords) if a.whole
            else max(a.core, 0) / consts.CORE_PER_CHIP
            for a in opt.allocs if a.needs_tpu
        )
        self.pods[key] = _SimPod(
            key=key, uid=uid, wclass=wclass, node=node, option=opt,
            chips_equiv=chips_equiv, expires_at=expires_at,
        )
        self.sched.pod_maps[key] = (node, opt)
        self.clientset.pods[key] = self._make_pod(key, uid)
        self.binds += 1
        self.scores.append(opt.score)
        if all(a.contiguous for a in opt.allocs if a.needs_tpu):
            self.contiguous += 1
        self.JOURNAL.record(
            "bind", pod=key, uid=uid, node=node,
            option=option_record(opt), gang=None, source=source,
            wclass=wclass,
        )

    @staticmethod
    def _make_pod(key: str, uid: str):
        from ..k8s.objects import make_pod

        ns, _, name = key.partition("/")
        return make_pod(name, namespace=ns, uid=uid, priority=0)

    def _forget(self, key: str, source: str) -> None:
        pod = self.pods.pop(key, None)
        if pod is None:
            return
        shim = self.sched.allocators.get(pod.node)
        if shim is not None and shim.chips.can_cancel(pod.option):
            shim.chips.cancel(pod.option)
        self.sched.pod_maps.pop(key, None)
        self.clientset.pods.pop(key, None)
        self.forgets += 1
        self.JOURNAL.record("forget", pod=key, uid=pod.uid, node=pod.node,
                            source=source)

    # -- synthetic arrivals ---------------------------------------------------

    def _growth_factor(self, t: float) -> float:
        sc = self.scenario
        frac = min(1.0, t / sc.duration_s) if sc.duration_s > 0 else 1.0
        return sc.arrival_scale * (1.0 + (sc.growth - 1.0) * frac)

    def _spawn_synthetic(self, t: float) -> None:
        for wclass in sorted(self.model.classes):
            cm = self.model.classes[wclass]
            acc = self._arrival_acc.get(wclass, 0.0)
            acc += (cm.arrival_rate_per_s * self._growth_factor(t)
                    * self.scenario.step_s)
            while acc >= 1.0:
                acc -= 1.0
                self._pod_serial += 1
                key = f"twin/{wclass}-{self._pod_serial}"
                uid = f"twin-uid-{self._pod_serial}"
                shape = self._pick_shape(cm)
                if shape[0] == "whole":
                    unit = TPUUnit(core=0, hbm=0, chip_count=shape[1])
                else:
                    unit = TPUUnit(core=shape[1], hbm=0, chip_count=0)
                req = TPURequest(
                    pod_uid=uid, pod_key=key, units=(unit,),
                    container_names=("main",),
                )
                placed = self._place(req, wclass)
                if placed is None:
                    self.unplaced += 1
                    continue
                life = self.rng.expovariate(1.0 / cm.mean_lifetime_s)
                self._bind(key, uid, wclass, placed[0], placed[1],
                           expires_at=t + max(self.scenario.step_s, life),
                           source="twin")
            self._arrival_acc[wclass] = acc

    def _pick_shape(self, cm) -> tuple:
        total = sum(w for _k, _v, w in cm.shapes) or 1.0
        pick = self.rng.random() * total
        for kind, val, w in cm.shapes:
            pick -= w
            if pick <= 0:
                return (kind, val)
        return (cm.shapes[-1][0], cm.shapes[-1][1])

    def _expire_pods(self, t: float) -> None:
        for key in [k for k, p in sorted(self.pods.items())
                    if p.expires_at is not None and p.expires_at <= t]:
            self._forget(key, source="twin")

    # -- recorded replay ------------------------------------------------------

    def _recorded_schedule(self) -> list:
        """(rel_t, rec) for the workload records, clipped to the
        scenario duration.  Relative to the recording's first timestamp
        so virtual time starts at 0 like synthetic runs."""
        rows = []
        t0 = None
        for rec in self.events:
            if rec.get("type") not in ("bind", "forget", "migrate"):
                continue
            if rec.get("type") == "bind" and rec.get("source") == "replay":
                continue  # restart re-assertion, not an arrival
            t = rec.get("t")
            if t is None:
                continue
            if t0 is None:
                t0 = float(t)
            rel = float(t) - t0
            if rel > self.scenario.duration_s:
                break
            rows.append((rel, rec))
        return rows

    def _apply_recorded(self, rec: dict) -> None:
        t = rec["type"]
        if t == "bind":
            key = rec.get("pod") or "?"
            if key in self.pods:
                return
            try:
                recorded = option_from_record(rec["option"])
            except Exception:
                return
            wclass = rec.get("wclass") or self.plane.default_class
            req = request_from_option(recorded, key, rec.get("uid", ""))
            placed = self._place(req, wclass, prefer_node=rec.get("node"))
            if placed is None:
                # the scenario rater cannot place what the recording did
                # on the same node state — count it loudly, then keep
                # the stream consistent with the recorded option
                self.unplaced += 1
                shim = self.sched.allocators.get(rec.get("node"))
                if shim is None or not shim.chips.can_transact(recorded):
                    return
                placed = (rec.get("node"), recorded)
            self._bind(key, rec.get("uid", ""), wclass, placed[0],
                       placed[1], expires_at=None, source="twin")
        elif t == "forget":
            self._forget(rec.get("pod") or "?", source="twin")
        # recorded migrates are skipped: the twin runs its OWN defrag
        # rounds through the real planner, which is the point

    # -- journeys + SLO burn --------------------------------------------------

    def _capacity_tokens_per_s(self, wclass: str, cm) -> tuple:
        """(capacity tokens/s, effective tokens/s/chip) for one class:
        replica chips × measured tokens/s/chip × the measured
        interference factor, on the generation mix actually placed
        (falls back to the fleet's generation mix when the class has no
        placed pods).  The per-chip rate is also the drift reference —
        the sim must SERVE at exactly the modeled per-chip rate, so any
        divergence in the report's ``model_drift`` means the simulation
        arithmetic broke, not that load was high."""
        gens: dict[str, float] = {}
        for p in self.pods.values():
            if p.wclass != wclass:
                continue
            shim = self.sched.allocators.get(p.node)
            gen = shim.generation if shim is not None else "v5e"
            gens[gen] = gens.get(gen, 0.0) + p.chips_equiv
        if not gens:
            for shim in self.sched.allocators.values():
                gens[shim.generation] = gens.get(shim.generation, 0.0) + 1.0
        total_w = sum(gens.values()) or 1.0
        tput = sum(
            w * cm.tokens_per_sec_per_chip.get(
                gen, sum(cm.tokens_per_sec_per_chip.values())
                / max(1, len(cm.tokens_per_sec_per_chip)),
            )
            for gen, w in gens.items()
        ) / total_w
        inter = min(cm.interference.values()) if cm.interference else 1.0
        eff = max(1e-6, tput * max(0.1, inter))
        chips = self.replicas * self.scenario.chips_per_replica
        return max(1e-6, chips * eff), eff

    def _tick_journeys(self, t: float) -> dict:
        """Synthesize this step's journeys per class and fold them into
        the SLO plane; returns the autoscaler signals derived from the
        same demand/capacity balance (so scaling sees the load that is
        burning the budget, like live /v1/stats would)."""
        sc = self.scenario
        demand_req = served_req = 0.0
        rho_worst = 0.0
        for wclass in sorted(self.model.classes):
            cm = self.model.classes[wclass]
            rate = cm.journeys_per_s * self._growth_factor(t)
            tokens_per_req = cm.prompt_tokens_mean + cm.output_tokens_mean
            capacity, eff_tput = self._capacity_tokens_per_s(wclass, cm)
            rho = rate * tokens_per_req / capacity
            rho_worst = max(rho_worst, rho)
            slowdown = max(1.0, rho)
            demand_req += rate
            served_req += min(rate, capacity / tokens_per_req)
            self._served_tokens[wclass] = (
                self._served_tokens.get(wclass, 0.0)
                + min(rate * tokens_per_req, capacity) * sc.step_s
            )
            self._chip_seconds[wclass] = (
                self._chip_seconds.get(wclass, 0.0)
                + self.replicas * sc.chips_per_replica * sc.step_s
                * (min(1.0, rho))
            )
            self._eff_tput_acc[wclass] = (
                self._eff_tput_acc.get(wclass, [0.0, 0.0])
            )
            self._eff_tput_acc[wclass][0] += eff_tput * sc.step_s
            self._eff_tput_acc[wclass][1] += sc.step_s
            n = self._journey_count(rate * sc.step_s)
            for _ in range(n):
                ok = self.rng.random() < cm.ok_rate / max(1.0, rho ** 2)
                kw = {}
                for metric in ("ttft", "tpot", "e2e", "queue", "hop"):
                    q = cm.latency_ms.get(metric)
                    if q:
                        kw[metric + "_ms"] = round(
                            sample_latency(self.rng, q) * slowdown, 3
                        )
                self.plane.record_journey(
                    wclass=wclass, ok=ok,
                    tokens=int(cm.output_tokens_mean), **kw,
                )
                self.journeys += 1
        self.backlog = max(
            0.0, self.backlog + (demand_req - served_req) * sc.step_s
        )
        return {
            "replicas": self.replicas,
            "queued": int(self.backlog),
            "queue_per_replica": round(
                self.backlog / max(1, self.replicas), 3
            ),
            "occupancy": round(min(1.0, rho_worst), 4),
            "page_util": round(min(1.0, rho_worst * 0.9), 4),
            "host_gap_ms": 0.0,
        }

    def _journey_count(self, expected: float) -> int:
        """Deterministic integer draw with the right mean (fractional
        part resolved by the seeded RNG, not by dropping it)."""
        base = int(expected)
        if self.rng.random() < (expected - base):
            base += 1
        return base

    # -- autoscaler + defrag ticks --------------------------------------------

    def _autoscale(self, signals: dict, now: float) -> None:
        slo = self.plane.scaling_input()
        action, reason = self.engine.evaluate(
            signals, self.replicas, now, total_replicas=self.replicas,
            warming_replicas=0, slo=slo,
        )
        self.fleet_decisions[action] = self.fleet_decisions.get(
            action, 0
        ) + 1
        target = self.replicas
        if action == "up":
            target += 1
        elif action == "down":
            target -= 1
        self.JOURNAL.record(
            "fleet", action=action, reason=reason, signals=signals,
            replicas=self.replicas, replicas_total=self.replicas,
            warming=0, slo=slo, policy=self.engine.policy.name,
            executed=action != "hold", target=target,
        )
        self.replicas = target

    def _defrag_round(self) -> None:
        snap = self.sched.frag_snapshot()
        if not any(idx > self.planner.threshold
                   for idx, _ in snap.values()):
            return
        plan = self.planner.plan(self.sched)
        for rnd in plan.rounds:
            for mv in rnd:
                to = self.sched.allocators.get(mv.to_node)
                frm = self.sched.allocators.get(mv.from_node)
                pod = self.pods.get(mv.pod_key)
                if to is None or frm is None or pod is None:
                    continue
                if not to.chips.can_transact(mv.new):
                    continue
                to.chips.transact(mv.new)
                if frm.chips.can_cancel(mv.old):
                    frm.chips.cancel(mv.old)
                pod.node, pod.option = mv.to_node, mv.new
                self.sched.pod_maps[mv.pod_key] = (mv.to_node, mv.new)
                self.migrations += 1
                self.JOURNAL.record(
                    "migrate", pod=mv.pod_key, uid=mv.uid,
                    node=mv.to_node, source_node=mv.from_node,
                    option=option_record(mv.new),
                    option_old=option_record(mv.old),
                    gang=mv.gang or None, source="twin_defrag",
                    wclass=pod.wclass,
                )

    # -- drift ----------------------------------------------------------------

    def _model_drift(self) -> dict:
        """Per-class relative drift between the tokens/s/chip the sim
        actually delivered and the fitted model's — check-twin's ≤20%
        fidelity gate.  A sim that saturates (demand over capacity)
        still serves AT the modeled per-chip rate, so drift here means
        the simulation arithmetic diverged, not that load was high."""
        out = {}
        for wclass in sorted(self.model.classes):
            cm = self.model.classes[wclass]
            chip_s = self._chip_seconds.get(wclass, 0.0)
            if chip_s <= 0:
                continue
            sim_tput = self._served_tokens.get(wclass, 0.0) / chip_s
            acc = self._eff_tput_acc.get(wclass)
            if acc and acc[1] > 0:
                model_tput = acc[0] / acc[1]
            else:
                vals = (list(cm.tokens_per_sec_per_chip.values())
                        or [1.0])
                model_tput = sum(vals) / len(vals)
            out[wclass] = {
                "sim_tokens_per_s_per_chip": round(sim_tput, 3),
                "model_tokens_per_s_per_chip": round(model_tput, 3),
                "drift": round(
                    abs(sim_tput - model_tput) / max(1e-6, model_tput), 4
                ),
            }
        return out

    # -- the run --------------------------------------------------------------

    def run(self) -> dict:
        sc = self.scenario
        wall0 = time.perf_counter()
        self.JOURNAL.record(
            "twin", action="scenario", scenario=sc.name, seed=sc.seed,
            mode=sc.mode, model_source=self.model.source,
            duration_s=sc.duration_s, rater=getattr(
                self.rater, "name", str(sc.rater)
            ),
        )
        self._boot_fleet()
        schedule = (self._recorded_schedule()
                    if sc.mode == "recorded" else [])
        cursor = 0
        next_scale = 0.0
        next_defrag = sc.defrag_interval_s
        steps = int(math.ceil(sc.duration_s / sc.step_s))
        for i in range(steps):
            t = min((i + 1) * sc.step_s, sc.duration_s)
            self.clock.advance_to(t)
            if sc.mode == "recorded":
                while cursor < len(schedule) and schedule[cursor][0] <= t:
                    self._apply_recorded(schedule[cursor][1])
                    cursor += 1
            else:
                self._spawn_synthetic(t)
                self._expire_pods(t)
            signals = self._tick_journeys(t)
            self.plane.evaluate(now=t)
            if t >= next_scale:
                self._autoscale(signals, t)
                next_scale = t + sc.autoscaler_interval_s
            if self.defrag_enabled and t >= next_defrag:
                self._defrag_round()
                next_defrag = t + sc.defrag_interval_s
        self.plane.evaluate(now=self.clock(), force=True)
        report = self._finish(wall0)
        with _LAST_LOCK:
            global _LAST_REPORT
            _LAST_REPORT = report
        return report

    def _finish(self, wall0: float) -> dict:
        sc = self.scenario
        frag = []
        free = total = 0
        for name in sorted(self.sched.allocators):
            cs = self.sched.allocators[name].chips
            frag.append(cs.fragmentation()[0])
            free += cs.free_count()
            total += cs.num_chips
        slo_dbg = self.plane.debug_state()
        posture = self.plane.posture()
        burn: dict[str, dict] = {}
        for cls, objs in sorted((slo_dbg.get("burn") or {}).items()):
            for key, b in sorted((objs or {}).items()):
                burn[f"{cls}:{key}"] = {
                    k: b.get(k)
                    for k in ("burn_short", "burn_long",
                              "total_short", "bad_short")
                }
        scores = {
            "binds": self.binds,
            "placed": self.binds,
            "unplaced": self.unplaced,
            "forgets": self.forgets,
            "migrations": self.migrations,
            "mean_score": round(
                sum(self.scores) / len(self.scores), 3
            ) if self.scores else 0.0,
            "contiguous_frac": round(
                self.contiguous / self.binds, 4
            ) if self.binds else 0.0,
            "final_frag_mean": round(
                sum(frag) / len(frag), 4
            ) if frag else 0.0,
            "mean_free_chip_frac": round(free / total, 4) if total else 0.0,
        }
        self.JOURNAL.record(
            "twin", action="scores", scenario=sc.name, seed=sc.seed,
            mode=sc.mode, scores=scores,
            slo={"breaches": self.plane.breaches,
                 "recoveries": self.plane.recoveries,
                 "burning": posture["burning"]},
        )
        self.JOURNAL.flush()
        self.JOURNAL.close()
        twin_events = read_journal(self.out_dir)
        res = replay(twin_events)  # conservation post-conditions included
        violations = list(res.violations)
        wall = max(1e-9, time.perf_counter() - wall0)
        walls = sorted(self.bind_walls)
        p99 = walls[min(len(walls) - 1,
                        int(0.99 * len(walls)))] if walls else 0.0
        return {
            "scenario": sc.to_dict(),
            "mode": sc.mode,
            "seed": sc.seed,
            "model": self.model.to_dict(),
            "sim_duration_s": sc.duration_s,
            "wall_s": round(wall, 3),
            "speedup_vs_wall": round(sc.duration_s / wall, 1),
            "bind_p99_ms": round(p99 * 1000.0, 3),
            "journeys": self.journeys,
            "replicas_final": self.replicas,
            "fleet_decisions": dict(sorted(self.fleet_decisions.items())),
            "packing": scores,
            "slo": {
                "posture": posture,
                "breaches": self.plane.breaches,
                "recoveries": self.plane.recoveries,
                "burn": burn,
            },
            "model_drift": self._model_drift(),
            "replay": {
                "records": len(twin_events),
                "twin_records": res.twin_records,
                "violations": violations,
            },
            "journal_dir": self.out_dir,
        }


def run_scenario(scenario: TwinScenario, events: Optional[list] = None,
                 slo_state: Optional[dict] = None,
                 model: Optional[WorkloadModel] = None,
                 rater=None) -> dict:
    """Build a runner and run it — the one call sites use (CLI,
    /twin/run, bench, check-twin, autosearch burn scoring)."""
    return TwinRunner(
        scenario, events=events, slo_state=slo_state, model=model,
        rater=rater,
    ).run()


def debug_state() -> dict:
    """The /debug/twin payload: the last completed run's report."""
    with _LAST_LOCK:
        if _LAST_REPORT is None:
            return {"ran": False}
        return {"ran": True, "report": _LAST_REPORT}
