"""Policy autosearch: evolve scoring-policy candidates on the twin.

The policy AST (policy/lang.py — the PR 10 expression language) is the
genome.  Each generation perturbs constants, swaps operators, grafts
input leaves, and recombines subtrees from the fitter half; every
candidate is scored OFFLINE on the recorded workload through the
existing promotion-gate machinery (``replay_gate`` — rater-neutral
packing metrics over a ``what_if`` replay), optionally plus a short
twin run that converts the candidate's packing into a simulated SLO
burn score.

The search NEVER applies anything.  Its output is a ranked report of
gate-PASSED candidates; a human promotes a winner through the existing
policy lifecycle (``POST /policy/load`` → replay gate → canary →
promote), which re-runs the same gate live before any traffic shifts.
Candidates whose gate failed are listed separately for diagnostics and
are never ranked — an autosearch round can therefore never surface a
gate-rejected genome as promotable (tools/check_twin.py holds this).
"""

from __future__ import annotations

import random
from typing import Callable, Optional

from ..core.rater import Binpack
from ..policy.lang import CompileError, compile_expr
from ..policy.promotion import replay_gate
from ..policy.rater import SCORE_INPUTS, PolicyRater

# the incumbent binpack formula in policy-expression form (the same
# weights core/rater.py's Binpack hard-codes) — the seed genome, so the
# search starts AT the incumbent and explores its neighborhood
INCUMBENT_SOURCE = (
    "35*node_used + 30*chip_used + 25*preserve + 10*locality"
)

_BIN_SWAPS = {"+": ("+", "-"), "-": ("-", "+"), "*": ("*",), "/": ("/",)}
_LEAVES = tuple(SCORE_INPUTS)


# -- AST ↔ source -------------------------------------------------------------
# The compiler's ``load`` nodes carry slot INDICES into Program.slots;
# the genome normalizes them to input NAMES so subtrees recombine across
# programs with different slot orders, then renders back to policy
# SOURCE (not Python — Program._py_src is the Python emitter) so every
# candidate round-trips through the real compiler.


def _named_ast(node, slots):
    kind = node[0]
    if kind == "num":
        return node
    if kind == "load":
        return ("load", slots[node[1]])
    if kind in ("neg", "not"):
        return (kind, _named_ast(node[1], slots))
    if kind == "bin":
        return ("bin", node[1], _named_ast(node[2], slots),
                _named_ast(node[3], slots))
    if kind in ("and", "or"):
        return (kind, _named_ast(node[1], slots),
                _named_ast(node[2], slots))
    if kind == "ternary":
        return ("ternary", _named_ast(node[1], slots),
                _named_ast(node[2], slots), _named_ast(node[3], slots))
    if kind == "call":
        return ("call", node[1], [_named_ast(a, slots) for a in node[2]])
    raise ValueError(f"unknown AST node {kind!r}")


def render_source(node) -> str:
    """Named AST → policy-language source (parenthesized everywhere —
    verbose but unambiguous, and the compiler normalizes anyway)."""
    kind = node[0]
    if kind == "num":
        v = node[1]
        return repr(int(v)) if float(v).is_integer() else repr(v)
    if kind == "load":
        return node[1]
    if kind == "neg":
        return f"(-{render_source(node[1])})"
    if kind == "not":
        return f"(!{render_source(node[1])})"
    if kind == "bin":
        return (f"({render_source(node[2])} {node[1]} "
                f"{render_source(node[3])})")
    if kind == "and":
        return f"({render_source(node[1])} && {render_source(node[2])})"
    if kind == "or":
        return f"({render_source(node[1])} || {render_source(node[2])})"
    if kind == "ternary":
        return (f"({render_source(node[1])} ? {render_source(node[2])} "
                f": {render_source(node[3])})")
    if kind == "call":
        args = ", ".join(render_source(a) for a in node[2])
        return f"{node[1]}({args})"
    raise ValueError(f"unknown AST node {kind!r}")


def genome_from_source(source: str):
    """Compile + normalize: source → named AST (raises CompileError on
    an invalid genome, which the search treats as dead)."""
    program = compile_expr(source, SCORE_INPUTS)
    return _named_ast(program.ast, program.slots)


# -- mutation -----------------------------------------------------------------


def _subtrees(node, acc=None):
    """All nodes in pre-order (shared references — read-only walk)."""
    if acc is None:
        acc = []
    acc.append(node)
    kind = node[0]
    if kind in ("neg", "not"):
        _subtrees(node[1], acc)
    elif kind == "bin":
        _subtrees(node[2], acc)
        _subtrees(node[3], acc)
    elif kind in ("and", "or"):
        _subtrees(node[1], acc)
        _subtrees(node[2], acc)
    elif kind == "ternary":
        for c in node[1:]:
            _subtrees(c, acc)
    elif kind == "call":
        for a in node[2]:
            _subtrees(a, acc)
    return acc


def _map_nth(node, n: int, fn, counter=None):
    """Rebuild the tree with pre-order node ``n`` replaced by
    ``fn(node)``.  Counter rides in a one-element list."""
    if counter is None:
        counter = [0]
    idx = counter[0]
    counter[0] += 1
    if idx == n:
        return fn(node)
    kind = node[0]
    if kind in ("num", "load"):
        return node
    if kind in ("neg", "not"):
        return (kind, _map_nth(node[1], n, fn, counter))
    if kind == "bin":
        return ("bin", node[1], _map_nth(node[2], n, fn, counter),
                _map_nth(node[3], n, fn, counter))
    if kind in ("and", "or"):
        return (kind, _map_nth(node[1], n, fn, counter),
                _map_nth(node[2], n, fn, counter))
    if kind == "ternary":
        return ("ternary", _map_nth(node[1], n, fn, counter),
                _map_nth(node[2], n, fn, counter),
                _map_nth(node[3], n, fn, counter))
    if kind == "call":
        return ("call", node[1],
                [_map_nth(a, n, fn, counter) for a in node[2]])
    return node


def mutate(genome, rng: random.Random):
    """One random edit: perturb a constant, swap a +/- operator, swap
    an input leaf, or graft a fresh weighted-input term onto the root."""
    nodes = _subtrees(genome)
    choice = rng.random()
    if choice < 0.45:
        # perturb a constant (the workhorse: reweighting the formula)
        idxs = [i for i, nd in enumerate(nodes) if nd[0] == "num"]
        if idxs:
            n = rng.choice(idxs)
            factor = rng.choice((0.5, 0.8, 1.25, 2.0))
            return _map_nth(
                genome, n,
                lambda nd: ("num", round(nd[1] * factor, 4)),
            )
    if choice < 0.65:
        # swap an additive operator's sign
        idxs = [i for i, nd in enumerate(nodes)
                if nd[0] == "bin" and nd[1] in ("+", "-")]
        if idxs:
            n = rng.choice(idxs)
            return _map_nth(
                genome, n,
                lambda nd: ("bin", "-" if nd[1] == "+" else "+",
                            nd[2], nd[3]),
            )
    if choice < 0.85:
        # re-aim an input leaf at a different score input
        idxs = [i for i, nd in enumerate(nodes) if nd[0] == "load"]
        if idxs:
            n = rng.choice(idxs)
            leaf = rng.choice(_LEAVES)
            return _map_nth(genome, n, lambda _nd: ("load", leaf))
    # graft: root ± weight * fresh_input
    leaf = rng.choice(_LEAVES)
    weight = rng.choice((1.0, 2.0, 5.0, 10.0))
    op = rng.choice(("+", "-"))
    return ("bin", op, genome,
            ("bin", "*", ("num", weight), ("load", leaf)))


def crossover(a, b, rng: random.Random):
    """Swap a random subtree of ``a`` for a random subtree of ``b``."""
    donors = _subtrees(b)
    donor = donors[rng.randrange(len(donors))]
    n = rng.randrange(len(_subtrees(a)))
    return _map_nth(a, n, lambda _nd: donor)


# -- scoring ------------------------------------------------------------------


def _neutral_wins(cand: dict, inc: dict) -> list:
    """Rater-neutral metrics where the candidate is STRICTLY better
    than the incumbent (what_if stat dicts)."""
    wins = []
    if cand["placed"] > inc["placed"]:
        wins.append("placed")
    if cand["contiguous_frac"] > inc["contiguous_frac"]:
        wins.append("contiguous_frac")
    if cand["final_frag_mean"] < inc["final_frag_mean"]:
        wins.append("final_frag_mean")
    if cand["mean_free_chip_frac"] > inc["mean_free_chip_frac"]:
        wins.append("mean_free_chip_frac")
    return wins


def _fitness(gate: dict, burn: Optional[float]) -> float:
    """Scalar rank: packing improvement over the incumbent, minus
    simulated burn when a burn evaluator ran.  Only meaningful among
    gate-PASSED candidates (failed ones never rank)."""
    cand, inc = gate["candidate"], gate["incumbent"]
    score = (
        (cand["mean_free_chip_frac"] - inc["mean_free_chip_frac"]) * 10.0
        + (inc["final_frag_mean"] - cand["final_frag_mean"]) * 10.0
        + (cand["contiguous_frac"] - inc["contiguous_frac"]) * 5.0
        + (cand["placed"] - inc["placed"]) * 0.5
    )
    if burn is not None:
        score -= burn
    return round(score, 6)


def autosearch(
    events: list,
    seed: int = 20260807,
    rounds: int = 4,
    population: int = 12,
    tolerance: float = 0.02,
    burn_eval: Optional[Callable] = None,
    incumbent_source: str = INCUMBENT_SOURCE,
) -> dict:
    """Evolve score-policy candidates against a recorded journal.

    ``burn_eval(rater) -> float`` optionally scores each gate-passed
    candidate's simulated SLO burn (twin run with the candidate as the
    scenario rater); lower is better.  Returns a report dict::

        {"seed", "rounds", "population", "incumbent": {...},
         "candidates": [ranked gate-passed, best first],
         "rejected": [gate-failed, for diagnostics],
         "beats_incumbent": [subset of candidates strictly better on
                             ≥1 rater-neutral metric],
         "promotion": how to promote (never done automatically)}
    """
    rng = random.Random(seed)
    incumbent = Binpack()
    seed_genome = genome_from_source(incumbent_source)

    # incumbent baseline (also sanity-checks the recording is gateable)
    base_gate = replay_gate(events, incumbent, incumbent,
                            tolerance=tolerance)
    inc_stats = base_gate["incumbent"]

    # generation 0: the incumbent genome + seeded weight perturbations
    pool = [seed_genome]
    while len(pool) < population:
        g = seed_genome
        for _ in range(rng.randrange(1, 3)):
            g = mutate(g, rng)
        pool.append(g)

    seen: set = set()
    scored: dict[str, dict] = {}  # source → result row
    for rnd_i in range(rounds):
        for genome in pool:
            src = render_source(genome)
            if src in seen:
                continue
            seen.add(src)
            try:
                program = compile_expr(src, SCORE_INPUTS)
            except CompileError as e:
                scored[src] = {"source": src, "compile_error": str(e),
                               "gate": None, "fitness": None}
                continue
            faults: list = []
            rater = PolicyRater(
                program, fallback=Binpack(),
                name=f"twin-gen{rnd_i}",
                on_fault=lambda *a, **k: faults.append(1),
            )
            gate = replay_gate(events, rater, incumbent,
                               tolerance=tolerance)
            burn = None
            if gate["pass"] and burn_eval is not None:
                try:
                    burn = float(burn_eval(rater))
                except Exception:
                    burn = None
            scored[src] = {
                "source": src,
                "genome": genome,
                "gate": gate,
                "faults": len(faults),
                "burn": burn,
                "fitness": _fitness(gate, burn) if gate["pass"] else None,
                "wins": _neutral_wins(gate["candidate"],
                                      gate["incumbent"])
                if gate["pass"] else [],
            }
        # next generation: mutate + recombine the fitter half
        passed = sorted(
            (r for r in scored.values() if r.get("fitness") is not None),
            key=lambda r: r["fitness"], reverse=True,
        )
        parents = [r["genome"] for r in passed[:max(2, population // 2)]]
        if not parents:
            parents = [seed_genome]
        pool = []
        while len(pool) < population:
            if len(parents) >= 2 and rng.random() < 0.3:
                a, b = rng.sample(range(len(parents)), 2)
                child = crossover(parents[a], parents[b], rng)
            else:
                child = mutate(parents[rng.randrange(len(parents))], rng)
            pool.append(child)

    def _row(r: dict) -> dict:
        gate = r["gate"]
        out = {
            "source": r["source"],
            "fitness": r.get("fitness"),
            "burn": r.get("burn"),
            "faults": r.get("faults", 0),
            "wins": r.get("wins", []),
        }
        if r.get("compile_error"):
            out["compile_error"] = r["compile_error"]
        if gate is not None:
            out["gate"] = {
                "pass": gate["pass"],
                "reasons": gate["reasons"],
                "candidate": {
                    k: gate["candidate"][k]
                    for k in ("placed", "unplaced", "contiguous_frac",
                              "final_frag_mean", "mean_free_chip_frac")
                },
            }
        return out

    ranked = sorted(
        (r for r in scored.values() if r.get("fitness") is not None),
        key=lambda r: r["fitness"], reverse=True,
    )
    rejected = [r for r in scored.values() if r.get("fitness") is None]
    # "beats" = gate-PASSED and strictly better on ≥1 rater-neutral
    # metric.  The identity genome is excluded by its RENDERED source
    # (render_source parenthesizes, so comparing against the raw
    # incumbent_source string would never match).
    identity = render_source(seed_genome)
    beats = [r for r in ranked if r["wins"] and r["source"] != identity]
    return {
        "seed": seed,
        "rounds": rounds,
        "population": population,
        "tolerance": tolerance,
        "evaluated": len(scored),
        "incumbent": {
            "name": incumbent.name,
            "source": incumbent_source,
            "stats": {
                k: inc_stats[k]
                for k in ("placed", "unplaced", "contiguous_frac",
                          "final_frag_mean", "mean_free_chip_frac")
            },
        },
        "candidates": [_row(r) for r in ranked[:16]],
        "rejected": [_row(r) for r in rejected[:16]],
        "beats_incumbent": [_row(r) for r in beats[:8]],
        "promotion": (
            "nothing is applied automatically — promote a winner with "
            "POST /policy/load (verb=score, source=<candidate>) and let "
            "the replay gate + canary lifecycle take it from there"
        ),
    }
