"""Trace-driven digital twin: time-warped fleet simulation.

The twin replays a recorded journal workload — or a workload model
fitted from it, or a fully synthetic one — through the REAL scheduler
building blocks (ChipSet placement search, SLO burn buckets, the
autoscaler PolicyEngine, the defrag planner) under a ``VirtualClock``,
so thirty simulated minutes of fleet behavior folds into about a
wall-second.  Every simulated decision is journaled through the real
``Journal`` wire format and the resulting twin journal replays through
the existing ``journal.replay`` invariant checks, so a twin run is
held to the same conservation standards as a live one.

Entry points:

- ``python -m elastic_gpu_scheduler_tpu.twin run`` — CLI scenario runner
- ``python -m elastic_gpu_scheduler_tpu.twin autosearch`` — policy search
- ``GET /debug/twin`` / ``POST /twin/run`` — server surface
- ``tools/check_twin.py`` (``make check-twin``) — the conformance gate

Isolation: the twin NEVER touches live singletons (global JOURNAL,
SLO, POLICIES, PROFILER).  Every run builds fresh instances and leaves
live scheduler state, journal sequence numbers, and metrics untouched
(tests/test_twin.py holds this).
"""

from __future__ import annotations

from .autosearch import (
    INCUMBENT_SOURCE,
    autosearch,
    crossover,
    genome_from_source,
    mutate,
    render_source,
)
from .clock import VirtualClock
from .model import (
    ClassModel,
    WorkloadModel,
    fit_workload_model,
    objectives_spec_from_events,
    sample_latency,
    synthesize_model,
)
from .runner import (
    TwinRunner,
    TwinScenario,
    debug_state,
    resolve_twin_rater,
    run_scenario,
    synthesize_fleet,
)

__all__ = [
    "INCUMBENT_SOURCE",
    "ClassModel",
    "TwinRunner",
    "TwinScenario",
    "VirtualClock",
    "WorkloadModel",
    "autosearch",
    "crossover",
    "debug_state",
    "fit_workload_model",
    "genome_from_source",
    "mutate",
    "objectives_spec_from_events",
    "render_source",
    "resolve_twin_rater",
    "run_scenario",
    "sample_latency",
    "synthesize_fleet",
    "synthesize_model",
]
