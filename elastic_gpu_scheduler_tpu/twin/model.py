"""Workload models for the digital twin.

Two sources, one shape:

- ``fit_workload_model(events, slo_state=None)`` — fitted from a
  RECORDED journal: per-class arrival rate and pod lifetime from the
  bind/forget stream, chip-shape mix from the recorded options,
  tokens/s/chip by TPU generation + interference slowdowns from the
  profile observatory's journaled EWMA snapshots, and (when the live
  SLO plane's ``debug_state()`` rides along) per-class journey-latency
  quantiles + ok-rate from the recorded journey windows.

- ``synthesize_model(seed)`` — a seeded synthetic model for what-if
  growth scenarios when there is nothing recorded yet.

Latency generation uses inverse-transform sampling through the fitted
(p50, p95, p99) quantiles — a piecewise-linear CDF — so a twin run's
simulated journey population reproduces the recorded latency posture:
if the recorded p95 sat above the objective threshold, the simulated
p95 does too, which is what makes simulated SLO burn agree with the
live-recorded posture.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Optional

DEFAULT_CLASS = "default"
# synthetic-model fallbacks (tokens/s/chip by generation roughly in the
# ratio of the profile observatory's bench fixtures)
_DEFAULT_TPUT = {"v5e": 900.0, "v5p": 1800.0, "v6e": 1400.0}
_DEFAULT_QUANTILES = {
    "ttft": {"p50": 80.0, "p95": 180.0, "p99": 320.0},
    "e2e": {"p50": 400.0, "p95": 900.0, "p99": 1600.0},
    "queue": {"p50": 5.0, "p95": 20.0, "p99": 45.0},
}


@dataclass
class ClassModel:
    """Fitted behavior of one workload class."""

    wclass: str = DEFAULT_CLASS
    arrival_rate_per_s: float = 0.5  # pod/request arrivals
    mean_lifetime_s: float = 120.0  # bind → forget
    # request journeys observed per second (router vantage) — pods are
    # long-lived serving replicas; journeys are the requests they serve
    journeys_per_s: float = 10.0
    # placement-shape mix: ("whole", n_chips, weight) | ("core", units, weight)
    shapes: list = field(default_factory=lambda: [
        ("whole", 2, 0.3), ("core", 100, 0.4), ("core", 50, 0.3),
    ])
    prompt_tokens_mean: float = 512.0
    output_tokens_mean: float = 128.0
    tokens_per_sec_per_chip: dict = field(
        default_factory=lambda: dict(_DEFAULT_TPUT)
    )
    # neighbor class → throughput ratio under co-tenancy (1.0 = no slow-
    # down), the profile observatory's interference_matrix row
    interference: dict = field(default_factory=dict)
    # journey latency quantiles in ms: metric → {p50, p95, p99}
    latency_ms: dict = field(
        default_factory=lambda: {
            m: dict(q) for m, q in _DEFAULT_QUANTILES.items()
        }
    )
    ok_rate: float = 1.0

    def to_dict(self) -> dict:
        return {
            "wclass": self.wclass,
            "arrival_rate_per_s": round(self.arrival_rate_per_s, 6),
            "mean_lifetime_s": round(self.mean_lifetime_s, 3),
            "journeys_per_s": round(self.journeys_per_s, 4),
            "shapes": [list(s) for s in self.shapes],
            "prompt_tokens_mean": round(self.prompt_tokens_mean, 1),
            "output_tokens_mean": round(self.output_tokens_mean, 1),
            "tokens_per_sec_per_chip": {
                g: round(v, 3)
                for g, v in sorted(self.tokens_per_sec_per_chip.items())
            },
            "interference": {
                k: round(v, 4) for k, v in sorted(self.interference.items())
            },
            "latency_ms": {
                m: {q: round(v, 3) for q, v in sorted(qs.items())}
                for m, qs in sorted(self.latency_ms.items())
            },
            "ok_rate": round(self.ok_rate, 4),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ClassModel":
        m = cls(wclass=d.get("wclass", DEFAULT_CLASS))
        for k in ("arrival_rate_per_s", "mean_lifetime_s", "journeys_per_s",
                  "prompt_tokens_mean", "output_tokens_mean", "ok_rate"):
            if d.get(k) is not None:
                setattr(m, k, float(d[k]))
        if d.get("shapes"):
            m.shapes = [
                (str(kind), int(val), float(w)) for kind, val, w in d["shapes"]
            ]
        if d.get("tokens_per_sec_per_chip"):
            m.tokens_per_sec_per_chip = {
                str(g): float(v)
                for g, v in d["tokens_per_sec_per_chip"].items()
            }
        if d.get("interference"):
            m.interference = {
                str(k): float(v) for k, v in d["interference"].items()
            }
        if d.get("latency_ms"):
            m.latency_ms = {
                str(metric): {str(q): float(v) for q, v in qs.items()}
                for metric, qs in d["latency_ms"].items()
            }
        return m


@dataclass
class WorkloadModel:
    """Per-class models + provenance.  ``source`` is ``fitted`` when the
    numbers came from a recording, ``synthetic`` otherwise — twin
    reports carry it so a capacity answer can never silently rest on
    made-up inputs."""

    classes: dict = field(default_factory=dict)  # wclass → ClassModel
    source: str = "synthetic"
    recorded_span_s: float = 0.0
    recorded_binds: int = 0

    def to_dict(self) -> dict:
        return {
            "source": self.source,
            "recorded_span_s": round(self.recorded_span_s, 3),
            "recorded_binds": self.recorded_binds,
            "classes": {
                cls: m.to_dict() for cls, m in sorted(self.classes.items())
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "WorkloadModel":
        return cls(
            classes={
                str(c): ClassModel.from_dict(m)
                for c, m in (d.get("classes") or {}).items()
            },
            source=str(d.get("source", "synthetic")),
            recorded_span_s=float(d.get("recorded_span_s", 0.0)),
            recorded_binds=int(d.get("recorded_binds", 0)),
        )


def sample_latency(rng: random.Random, quantiles: dict) -> float:
    """Inverse-transform sample (ms) through a piecewise-linear CDF
    pinned at the fitted p50/p95/p99 — the cheapest generator whose
    OWN p50/p95/p99 reproduce the fitted ones."""
    p50 = float(quantiles.get("p50", 1.0))
    p95 = max(p50, float(quantiles.get("p95", p50)))
    p99 = max(p95, float(quantiles.get("p99", p95)))
    u = rng.random()
    if u < 0.5:
        lo = p50 * 0.25  # fitted floor: fastest journeys ~ quarter-median
        return lo + (p50 - lo) * (u / 0.5)
    if u < 0.95:
        return p50 + (p95 - p50) * ((u - 0.5) / 0.45)
    if u < 0.99:
        return p95 + (p99 - p95) * ((u - 0.95) / 0.04)
    return p99 * (1.0 + (u - 0.99) * 5.0)  # bounded tail past p99


def objectives_spec_from_events(events: list) -> Optional[dict]:
    """Recover a ``SloPlane.load_config`` spec from the LAST journaled
    ``slo``/``objectives`` record (the plane journals its full config on
    every load), so a recorded scenario replays under exactly the
    objectives the live plane enforced.  None when never journaled."""
    spec = None
    for rec in events:
        if rec.get("type") != "slo" or rec.get("action") != "objectives":
            continue
        classes = {}
        for cls, objs in (rec.get("classes") or {}).items():
            entry = {}
            for key, o in (objs or {}).items():
                if o.get("metric") == "availability":
                    entry[key] = o.get("target")
                else:
                    entry[key] = o.get("threshold_ms")
            if entry:
                classes[cls] = entry
        if classes:
            spec = {
                "classes": classes,
                "window_short_s": rec.get("window_short_s", 60),
                "window_long_s": rec.get("window_long_s", 300),
                "burn_threshold": rec.get("burn_threshold", 1.0),
            }
    return spec


def _option_shape(option: dict):
    """("whole", chips) | ("core", units) for one recorded bind option."""
    whole_chips = 0
    core_units = 0
    for alloc in option.get("allocs") or []:
        try:
            _name, coords, whole, core, _hbm, _contig = alloc
        except (TypeError, ValueError):
            continue
        if whole:
            whole_chips += len(coords)
        elif core:
            core_units += int(core)
    if whole_chips:
        return ("whole", whole_chips)
    if core_units:
        return ("core", core_units)
    return None


def fit_workload_model(events: list,
                       slo_state: Optional[dict] = None) -> WorkloadModel:
    """Fit per-class models from a recorded journal (+ optionally the
    live SLO plane's ``debug_state()`` for journey-latency quantiles).

    Journal inputs: ``bind``/``forget`` arrivals, lifetimes and shape
    mix (keyed by the bind's ``wclass``); the LAST ``profile`` record's
    per-class tokens/s/chip EWMAs and interference matrix.  Raises
    ValueError when the recording holds no binds — a model fitted from
    nothing must fail loudly, not simulate silence."""
    binds_by_class: dict[str, list[float]] = {}
    shapes_by_class: dict[str, dict] = {}
    bind_at: dict[str, tuple[str, float]] = {}  # pod → (wclass, t)
    lifetimes: dict[str, list[float]] = {}
    last_profile: Optional[dict] = None
    t_min = t_max = None
    for rec in events:
        t = rec.get("t")
        rtype = rec.get("type")
        if rtype == "profile":
            last_profile = rec
            continue
        if rtype not in ("bind", "forget") or t is None:
            continue
        t = float(t)
        t_min = t if t_min is None else min(t_min, t)
        t_max = t if t_max is None else max(t_max, t)
        if rtype == "bind":
            if rec.get("source") == "replay":
                continue  # restart re-assertion, not an arrival
            wclass = rec.get("wclass") or DEFAULT_CLASS
            binds_by_class.setdefault(wclass, []).append(t)
            if rec.get("pod"):
                bind_at[rec["pod"]] = (wclass, t)
            shape = _option_shape(rec.get("option") or {})
            if shape is not None:
                counts = shapes_by_class.setdefault(wclass, {})
                counts[shape] = counts.get(shape, 0) + 1
        else:
            entry = bind_at.pop(rec.get("pod"), None)
            if entry is not None:
                wclass, t0 = entry
                lifetimes.setdefault(wclass, []).append(max(0.0, t - t0))
    total_binds = sum(len(v) for v in binds_by_class.values())
    if not total_binds:
        raise ValueError(
            "cannot fit a workload model: the recording holds no bind "
            "records"
        )
    span = max(1e-6, (t_max - t_min)) if t_min is not None else 1e-6

    profiles = (last_profile or {}).get("profiles") or {}
    interference = (last_profile or {}).get("interference") or {}
    windows = (slo_state or {}).get("windows") or {}

    model = WorkloadModel(
        source="fitted", recorded_span_s=span, recorded_binds=total_binds,
    )
    for wclass, arrivals in sorted(binds_by_class.items()):
        m = ClassModel(wclass=wclass)
        m.arrival_rate_per_s = len(arrivals) / span
        lt = lifetimes.get(wclass) or []
        if lt:
            m.mean_lifetime_s = max(1e-3, sum(lt) / len(lt))
        else:
            # nothing forgotten during the recording: pods outlive it
            m.mean_lifetime_s = span
        counts = shapes_by_class.get(wclass) or {}
        n = sum(counts.values())
        if n:
            m.shapes = [
                (kind, val, cnt / n)
                for (kind, val), cnt in sorted(counts.items())
            ]
        prof = profiles.get(wclass) or {}
        tput = prof.get("tput") or prof.get("tokens_per_sec_per_chip")
        if isinstance(tput, dict) and tput:
            m.tokens_per_sec_per_chip = {
                str(g): float(v) for g, v in tput.items() if v
            }
        inter = interference.get(wclass)
        if isinstance(inter, dict):
            m.interference = {
                str(k): float(v) for k, v in inter.items()
            }
        win = windows.get(wclass) or {}
        win_short = float((slo_state or {}).get("window_short_s") or 60.0)
        if win.get("samples"):
            m.journeys_per_s = max(
                1e-3, float(win["samples"]) / max(1.0, win_short)
            )
        for metric in ("ttft", "e2e", "queue", "tpot", "hop"):
            q = win.get(metric + "_ms")
            if isinstance(q, dict) and q.get("p50") is not None:
                m.latency_ms[metric] = {
                    "p50": float(q["p50"]),
                    "p95": float(q.get("p95", q["p50"])),
                    "p99": float(q.get("p99", q.get("p95", q["p50"]))),
                }
        if win.get("ok_frac") is not None:
            m.ok_rate = float(win["ok_frac"])
        model.classes[wclass] = m
    return model


def synthesize_model(seed: int = 20260807,
                     classes=("serve", "batch")) -> WorkloadModel:
    """A seeded synthetic model for growth what-ifs with no recording.
    Everything derives from one RNG so the same seed reproduces the
    same fleet-scale answer bit-for-bit (the fleetgen stance)."""
    rng = random.Random(seed)
    model = WorkloadModel(source="synthetic")
    for i, wclass in enumerate(classes):
        m = ClassModel(wclass=wclass)
        m.arrival_rate_per_s = round(rng.uniform(0.05, 0.15), 3)
        m.mean_lifetime_s = round(rng.uniform(40.0, 80.0), 1)
        m.journeys_per_s = round(rng.uniform(5.0, 20.0), 2)
        whole_w = round(rng.uniform(0.3, 0.7), 2)
        m.shapes = [
            ("whole", rng.choice((1, 2)), whole_w),
            ("core", rng.choice((50, 100)), round(1.0 - whole_w, 2)),
        ]
        m.prompt_tokens_mean = float(rng.choice((256, 512, 1024)))
        m.output_tokens_mean = float(rng.choice((64, 128, 256)))
        m.tokens_per_sec_per_chip = {
            g: round(v * rng.uniform(0.9, 1.1), 1)
            for g, v in _DEFAULT_TPUT.items()
        }
        base = 1.0 + i * 0.5  # later classes arrive slower-served
        m.latency_ms = {
            metric: {q: round(v * base, 1) for q, v in qs.items()}
            for metric, qs in _DEFAULT_QUANTILES.items()
        }
        m.ok_rate = 0.999
        model.classes[wclass] = m
    return model
