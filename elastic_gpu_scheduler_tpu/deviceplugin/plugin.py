"""TPU kubelet device plugin.

The one near-hardware component (SURVEY §7: "the libtpu device plugin
replacing the nvidia/NVML agent").  Serves the kubelet DevicePlugin v1beta1
gRPC API over a unix socket and registers with the kubelet's Registration
service, advertising ``elasticgpu.io/tpu-chip`` in core units (100 devices
per chip — fractional-sharing granularity, matching the scheduler's resource
model, utils/consts.py).

Chip discovery, in order:
1. real TPU device files (/dev/accel*, the PCI TPU driver's nodes);
2. a forced count via ``TPU_CHIP_COUNT`` env / constructor arg (simulation);
Topology coordinates come from the same node labels the scheduler reads
(LABEL_TPU_HOST_TOPOLOGY/OFFSET via env TPU_HOST_TOPOLOGY/TPU_HOST_OFFSET),
falling back to a 1-D mesh.

Allocate maps the kubelet-chosen device IDs back to chip coordinates and
exposes them as ``TPU_VISIBLE_CHIPS`` env plus /dev/accel* device specs — the
on-node half of the coordinate contract whose other half is the scheduler's
``elasticgpu.io/container-<name>`` annotation (reference delegates this to
the sibling Elastic GPU Agent, README.md:30-34; here it's in-repo).

Fractional core-% contract (the qGPU slot, SURVEY §7(d) — "no NVML
analogue; define what tpu-core % means"):

- A container requesting ``tpu-chip: N`` where N is not a multiple of 100
  is a FRACTIONAL tenant: it shares its chip(s) with other fractional
  tenants the scheduler binpacked onto the same chip (core/rater.py).
- The on-node meaning is COOPERATIVE time-slicing, not hardware
  partitioning — TPUs have no MIG/MPS analogue; a TensorCore runs one
  program at a time, so the share is a scheduling weight, not an
  enforced slice.  The plugin exports the contract as env and the
  workload runtime honors it:
    TPU_VISIBLE_CHIPS    the chip coordinates this container may use
    TPU_CHIP_CORE_UNITS  total core units allocated (100 = one chip)
    TPU_CHIP_SHARES      exact per-chip breakdown ("coord=units,...") —
                         the kubelet may split an allocation unevenly
                         across chips
    TPU_CORE_PERCENT     the MINIMUM per-chip share in percent (the
                         conservative figure a process-wide limit must
                         respect)
    XLA_PYTHON_CLIENT_MEM_FRACTION
                         min-share/100, set for fractional tenants only:
                         the XLA fraction applies process-wide across
                         all visible chips, so only the smallest chip
                         share is safe against that chip's neighbors
                         (whole-chip tenants keep full preallocation)
- SLO stance: fractional tenants get throughput proportional to their
  share only under cooperative neighbors; latency SLOs require whole
  chips (core: a multiple of 100), which the scheduler places with
  exclusive chip ownership (core/allocator.py owned-chips rule).

Kubelet-restart lifecycle (the real device-plugin contract): kubelet
forgets every plugin on restart and recreates kubelet.sock.
``start_kubelet_watch`` polls the socket inode; on change it re-serves
the plugin socket if the restart removed it, then re-registers — so the
DaemonSet pod survives kubelet restarts without a restart of its own.

gRPC note: messages are protoc-generated (deviceplugin_pb2.py); service
stubs are hand-wired with grpc generic handlers since grpcio-tools is not in
this environment.
"""

from __future__ import annotations

import glob
import logging
import os
import threading
import time
from concurrent import futures
from typing import Optional

import grpc

from . import deviceplugin_pb2 as pb
from ..core.topology import Topology, parse_coord, parse_topology
from ..profile import PROFILER
from ..tracing import TRACEPARENT_HEADER, TRACER
from ..utils import consts

log = logging.getLogger("tpu-device-plugin")


def _grpc_traceparent(context) -> str:
    """W3C trace context from gRPC invocation metadata (the DevicePlugin
    API carries no pod identity, so the ``traceparent`` metadata key —
    populated by a tracing-aware caller from the pod's
    ``elasticgpu.io/traceparent`` annotation — is how an Allocate joins
    the pod's scheduling trace).  Best-effort: kubelet sends none."""
    try:
        for k, v in context.invocation_metadata() or ():
            if k.lower() == TRACEPARENT_HEADER:
                return v
    except Exception:
        pass
    return ""

API_VERSION = "v1beta1"
KUBELET_SOCKET = "/var/lib/kubelet/device-plugins/kubelet.sock"
PLUGIN_SOCKET_NAME = "elasticgpu-tpu.sock"
HEALTHY = "Healthy"

_SVC = "v1beta1.DevicePlugin"
_REG_SVC = "v1beta1.Registration"


def discover_chips(
    chip_count: int = 0,
    host_topology: str = "",
    host_offset: str = "",
) -> list[tuple[str, str]]:
    """Returns [(coord_str, device_path)]."""
    paths = sorted(glob.glob("/dev/accel*"))
    if chip_count <= 0:
        chip_count = (
            len(paths)
            if paths
            else int(os.environ.get("TPU_CHIP_COUNT", "0") or 0)
        )
    if chip_count <= 0:
        return []
    host_topology = host_topology or os.environ.get("TPU_HOST_TOPOLOGY", "")
    host_offset = host_offset or os.environ.get("TPU_HOST_OFFSET", "")
    if host_topology:
        dims = parse_topology(host_topology)
        topo = Topology(dims)
        offset = (
            parse_coord(host_offset) if host_offset else (0,) * len(dims)
        )
        coords = [
            ".".join(str(o + v) for o, v in zip(offset, local))
            for local in topo.coords()
        ][:chip_count]
    else:
        coords = [str(i) for i in range(chip_count)]
    out = []
    for i, c in enumerate(coords):
        path = paths[i] if i < len(paths) else f"/dev/accel{i}"
        out.append((c, path))
    return out


class TPUDevicePlugin:
    """DevicePlugin service implementation."""

    def __init__(
        self,
        chips: Optional[list[tuple[str, str]]] = None,
        core_units_per_chip: int = consts.CORE_PER_CHIP,
        resource_name: str = consts.RESOURCE_TPU_CORE,
    ):
        self.chips = chips if chips is not None else discover_chips()
        self.core_units = core_units_per_chip
        self.resource_name = resource_name
        self._stop = threading.Event()
        self._server: Optional[grpc.Server] = None
        self._health: dict[str, bool] = {c: True for c, _ in self.chips}
        self._health_event = threading.Event()  # set → re-announce now
        # device files present at startup: the probe set for check_devices
        self._probe_paths = {
            c: path for c, path in self.chips if os.path.exists(path)
        }

    # -- device model --------------------------------------------------------

    def device_list(self) -> list[pb.Device]:
        """One device per core unit: ID "<coord>/<unit>" (100 per chip)."""
        devs = []
        for coord, _path in self.chips:
            health = HEALTHY if self._health.get(coord, True) else "Unhealthy"
            for u in range(self.core_units):
                devs.append(pb.Device(ID=f"{coord}/{u}", health=health))
        return devs

    def set_health(self, coord: str, healthy: bool) -> None:
        """Failure detection hook: mark a chip (un)healthy and re-announce —
        kubelet then shrinks/restores the node's allocatable, and the
        scheduler's capacity refresh (core/node.refresh_from_node) follows.
        Signals only on an actual transition (an unconditional signal would
        turn the ListAndWatch heartbeat into a busy loop)."""
        if self._health.get(coord, True) != healthy:
            self._health[coord] = healthy
            self._health_event.set()

    def check_devices(self) -> None:
        """Re-probe the device files that existed at startup; a vanished one
        marks its chip Unhealthy, reappearance restores it.  Simulated chips
        (no device file at startup) are never probed."""
        for coord, path in self._probe_paths.items():
            self.set_health(coord, os.path.exists(path))

    @staticmethod
    def chip_of_device(device_id: str) -> str:
        return device_id.split("/", 1)[0]

    # -- rpc implementations -------------------------------------------------

    def GetDevicePluginOptions(self, request, context):
        return pb.DevicePluginOptions(
            pre_start_required=False, get_preferred_allocation_available=True
        )

    def ListAndWatch(self, request, context):
        yield pb.ListAndWatchResponse(devices=self.device_list())
        # re-announce on health changes immediately, else slow heartbeat
        while not self._stop.is_set():
            self._health_event.wait(timeout=10.0)
            if self._stop.is_set():
                break
            self._health_event.clear()
            self.check_devices()
            yield pb.ListAndWatchResponse(devices=self.device_list())

    def GetPreferredAllocation(self, request, context):
        """Prefer filling already-chosen chips (must-include first), then the
        fewest additional chips — core-unit binpacking within the node, so
        fractional tenants consolidate and whole chips stay free."""
        resp = pb.PreferredAllocationResponse()
        for creq in request.container_requests:
            need = creq.allocation_size
            chosen = list(creq.must_include_device_i_ds)[:need]
            remaining = [
                d for d in creq.available_device_i_ds if d not in set(chosen)
            ]
            by_chip: dict[str, list[str]] = {}
            for d in remaining:
                by_chip.setdefault(self.chip_of_device(d), []).append(d)
            # chips already partially chosen first, then fewest-available
            chosen_chips = {self.chip_of_device(d) for d in chosen}
            order = sorted(
                by_chip.items(),
                key=lambda kv: (kv[0] not in chosen_chips, len(kv[1]), kv[0]),
            )
            for _chip, devs in order:
                for d in sorted(devs):
                    if len(chosen) >= need:
                        break
                    chosen.append(d)
                if len(chosen) >= need:
                    break
            resp.container_responses.append(
                pb.ContainerPreferredAllocationResponse(device_i_ds=chosen)
            )
        return resp

    def Allocate(self, request, context):
        with TRACER.span(
            "deviceplugin.allocate",
            parent=_grpc_traceparent(context) or None,
            containers=len(request.container_requests),
        ) as sp:
            return self._allocate(request, sp)

    def _profile_chips(self, by_chip: dict[str, int], tenant: str) -> None:
        """Emit per-chip occupancy samples into the profile observatory
        (the node-path half of the behavioral telemetry: which chips
        carry how many core units, keyed by the tenant when the caller's
        trace context identifies one).  One ring append per chip; no-op
        unless profiling is enabled."""
        if not PROFILER.enabled:
            return
        node = os.environ.get("NODE_NAME", "") or "local"
        for coord, units in by_chip.items():
            PROFILER.record_chip(
                node, coord, units, self.core_units, tenant=tenant
            )

    def _allocate(self, request, sp):
        by_path = dict(self.chips)
        resp = pb.AllocateResponse()
        all_chips: list[str] = []
        total_units = 0
        for creq in request.container_requests:
            chip_coords = sorted(
                {self.chip_of_device(d) for d in creq.devices_i_ds}
            )
            cresp = pb.ContainerAllocateResponse()
            cresp.envs["TPU_VISIBLE_CHIPS"] = ",".join(chip_coords)
            units = len(creq.devices_i_ds)
            cresp.envs["TPU_CHIP_CORE_UNITS"] = str(
                units
            )  # fractional share size in core units
            # the fractional contract (module docstring): per-chip share
            # in percent, plus a JAX allocator cap for fractional tenants
            # per-chip shares from the ACTUAL device distribution — the
            # kubelet treats core-unit device ids as fungible, so an
            # allocation can split unevenly across chips (40 on A + 10 on
            # B); a cross-chip average would overstate the smaller share
            # and oversubscribe HBM against that chip's neighbors
            by_chip: dict[str, int] = {}
            for d in creq.devices_i_ds:
                c = self.chip_of_device(d)
                by_chip[c] = by_chip.get(c, 0) + 1
            cresp.envs["TPU_CHIP_SHARES"] = ",".join(
                f"{c}={u}" for c, u in sorted(by_chip.items())
            )
            # behavioral telemetry: per-chip occupancy samples keyed by
            # the caller's trace id (the pod's scheduling trace, when
            # the traceparent metadata carried one)
            self._profile_chips(
                by_chip, sp.trace_id if sp is not None else ""
            )
            min_units = min(by_chip.values()) if by_chip else 0
            # the conservative contract: the MINIMUM per-chip share (the
            # XLA mem fraction is process-wide across visible chips, so
            # only the smallest share is safe against neighbors)
            pct = round(100 * min_units / self.core_units)
            cresp.envs["TPU_CORE_PERCENT"] = str(pct)
            if by_chip and min_units < self.core_units:
                cresp.envs["XLA_PYTHON_CLIENT_MEM_FRACTION"] = (
                    f"{min_units / self.core_units:.2f}"
                )
            for coord in chip_coords:
                path = by_path.get(coord)
                if path:
                    cresp.devices.append(
                        pb.DeviceSpec(
                            container_path=path, host_path=path, permissions="rw"
                        )
                    )
            resp.container_responses.append(cresp)
            all_chips.extend(chip_coords)
            total_units += units
        sp.set_attr("chips", sorted(set(all_chips)))
        sp.set_attr("core_units", total_units)
        return resp

    def PreStartContainer(self, request, context):
        return pb.PreStartContainerResponse()

    # -- server wiring -------------------------------------------------------

    def _generic_handler(self):
        rpcs = {
            "GetDevicePluginOptions": grpc.unary_unary_rpc_method_handler(
                self.GetDevicePluginOptions,
                request_deserializer=pb.Empty.FromString,
                response_serializer=pb.DevicePluginOptions.SerializeToString,
            ),
            "ListAndWatch": grpc.unary_stream_rpc_method_handler(
                self.ListAndWatch,
                request_deserializer=pb.Empty.FromString,
                response_serializer=pb.ListAndWatchResponse.SerializeToString,
            ),
            "GetPreferredAllocation": grpc.unary_unary_rpc_method_handler(
                self.GetPreferredAllocation,
                request_deserializer=pb.PreferredAllocationRequest.FromString,
                response_serializer=(
                    pb.PreferredAllocationResponse.SerializeToString
                ),
            ),
            "Allocate": grpc.unary_unary_rpc_method_handler(
                self.Allocate,
                request_deserializer=pb.AllocateRequest.FromString,
                response_serializer=pb.AllocateResponse.SerializeToString,
            ),
            "PreStartContainer": grpc.unary_unary_rpc_method_handler(
                self.PreStartContainer,
                request_deserializer=pb.PreStartContainerRequest.FromString,
                response_serializer=pb.PreStartContainerResponse.SerializeToString,
            ),
        }
        return grpc.method_handlers_generic_handler(_SVC, rpcs)

    def serve(self, socket_path: str) -> grpc.Server:
        if os.path.exists(socket_path):
            os.unlink(socket_path)
        server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        server.add_generic_rpc_handlers((self._generic_handler(),))
        server.add_insecure_port(f"unix://{socket_path}")
        server.start()
        self._server = server
        log.info(
            "device plugin serving %d chips (%d devices) on %s",
            len(self.chips),
            len(self.chips) * self.core_units,
            socket_path,
        )
        return server

    def stop(self):
        self._stop.set()
        self._health_event.set()  # wake ListAndWatch immediately
        if self._server is not None:
            self._server.stop(grace=1)

    @staticmethod
    def _sock_ino(path: str):
        """Socket identity: (inode, ctime_ns) — a recreated socket can
        reuse the inode on tmpfs, but not the creation stamp."""
        try:
            st = os.stat(path)
            return (st.st_ino, st.st_ctime_ns)
        except OSError:
            return None

    def start_kubelet_watch(
        self,
        plugin_dir: str,
        endpoint: str = PLUGIN_SOCKET_NAME,
        interval: float = 1.0,
    ) -> threading.Thread:
        """The kubelet-restart contract: a restarted kubelet forgets every
        registered plugin and recreates kubelet.sock (new inode).  Poll
        the inode; on change, re-serve our socket if the restart removed
        it, then re-register (with bounded retry — the kubelet may not be
        accepting yet).  Returns the watcher thread (daemon)."""
        ksock = os.path.join(plugin_dir, "kubelet.sock")
        own = os.path.join(plugin_dir, endpoint)

        def loop():
            last = self._sock_ino(ksock)
            while not self._stop.wait(interval):
                try:
                    last = tick(last)
                except Exception:
                    # the watcher must survive anything (a dying watcher
                    # disables restart recovery until a pod restart);
                    # re-evaluate from scratch next poll
                    log.exception("kubelet watch iteration failed")
                    last = None

        def tick(last):
                cur = self._sock_ino(ksock)
                if cur is None:
                    return None  # kubelet down; any reappearance is new
                if cur == last:
                    return last
                log.info(
                    "kubelet.sock inode changed (kubelet restart); "
                    "re-registering %s", self.resource_name,
                )
                if not os.path.exists(own):
                    # some kubelet versions clean the plugin dir on
                    # restart: bring our socket back before registering
                    if self._server is not None:
                        self._server.stop(grace=0.5)
                    self.serve(own)
                registered = False
                for attempt in range(5):
                    try:
                        self.register(
                            kubelet_socket=ksock, endpoint=endpoint
                        )
                        registered = True
                        break
                    except Exception as e:
                        log.warning(
                            "re-register attempt %d failed: %s",
                            attempt + 1, e,
                        )
                        if self._stop.wait(0.5 * (attempt + 1)):
                            return
                if not registered:
                    # forget the inode so the next poll retries — giving
                    # up here would leave the node advertising zero
                    # chips until ANOTHER kubelet restart
                    return None
                return cur

        t = threading.Thread(target=loop, name="kubelet-watch", daemon=True)
        t.start()
        return t

    def register(
        self,
        kubelet_socket: str = KUBELET_SOCKET,
        endpoint: str = PLUGIN_SOCKET_NAME,
    ) -> None:
        """Register with the kubelet's Registration service."""
        with grpc.insecure_channel(f"unix://{kubelet_socket}") as ch:
            register = ch.unary_unary(
                f"/{_REG_SVC}/Register",
                request_serializer=pb.RegisterRequest.SerializeToString,
                response_deserializer=pb.Empty.FromString,
            )
            register(
                pb.RegisterRequest(
                    version=API_VERSION,
                    endpoint=endpoint,
                    resource_name=self.resource_name,
                    # kubelet stores the options from THIS message and only
                    # calls GetPreferredAllocation when advertised here
                    options=self.GetDevicePluginOptions(pb.Empty(), None),
                ),
                timeout=10,
            )
        log.info("registered %s with kubelet", self.resource_name)


def main(argv=None) -> int:  # pragma: no cover - thin wrapper
    import argparse

    p = argparse.ArgumentParser("tpu-device-plugin")
    p.add_argument("--plugin-dir", default="/var/lib/kubelet/device-plugins")
    p.add_argument("--chip-count", type=int, default=0)
    p.add_argument("--no-register", action="store_true")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    chips = discover_chips(chip_count=args.chip_count)
    plugin = TPUDevicePlugin(chips=chips)
    sock = os.path.join(args.plugin_dir, PLUGIN_SOCKET_NAME)
    plugin.serve(sock)
    if not args.no_register:
        plugin.register(
            kubelet_socket=os.path.join(args.plugin_dir, "kubelet.sock")
        )
        plugin.start_kubelet_watch(args.plugin_dir)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        plugin.stop()
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
