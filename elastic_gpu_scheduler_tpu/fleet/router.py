"""Front-door router: spread /v1/* streams across serving replicas with
prefix-cache-aware affinity.

One serving engine per pod caps the fleet at one pod's throughput; the
router is the fan-out point.  Routing policy, in precedence order:

1. **Prefix affinity.**  The incoming prompt's rolling BLAKE2b digest
   chain (utils/prefixdigest — the SAME chain the engine's prefix cache
   keys pages by) is matched longest-first against the FLEET-WIDE
   prefix-cache index (:class:`PrefixIndex`): one digest may be held by
   several replicas, and the route goes to the routable holder with the
   longest match (locality score, load as tiebreak), so the engine's
   ``_match_prefix`` turns the route into real skipped prefill work.
   The index is a bounded LRU — cold digests age out at roughly the
   rate replica caches recycle pages — and entries pointing at replicas
   LEAVING rotation (removed, scaled down, breaker-down) are pruned
   eagerly, so a stale digest can never steer a prompt at a dead
   backend ahead of the health fallback.
2. **Page adoption.**  Holders exist but none is routable (draining /
   warming / prefill-role) — or load-margin shedding is enabled and the
   holder is overloaded: the request routes to the best candidate WITH
   an ``X-KV-Source`` header naming the holder, and the backend pulls
   the prefix's KV pages over the wire (utils/kvwire) before admission
   — the fleet moves the KV, not the request.  A cold scale-up starts
   winning repeated-prefix traffic immediately instead of re-prefilling.
3. **Prefill/decode split.**  A long prompt with no index hit routes
   through a ``prefill``-role replica first (``/v1/prefill`` batches
   the chunked prefill and caches the pages), then the completion runs
   on a decode replica that adopts the pages — decode slots never stall
   behind a long admission.  Replicas advertise their role in
   ``/v1/stats``; prefill-role replicas get ZERO completion traffic.
4. **Least loaded.**  No index hit: the candidate with the smallest
   (queued + router in-flight, active slot fraction) from the health
   loop's last ``/v1/stats`` poll plus the router's own in-flight
   counter (fresher than any poll).
5. **Failover.**  Connect failure or a 5xx status line from the chosen
   replica (detected BEFORE any byte is forwarded to the client) falls
   through to the next candidate; each failure feeds that replica's
   circuit breaker.

Replica health: a background loop polls ``/healthz`` + ``/v1/stats``.
States: ``up`` (routable), ``warming`` (healthz 503 with
``{"warming": true}`` — the replica is pre-lowering its compile
lattice at boot and must receive ZERO traffic until the cache is warm;
distinct from draining because it is capacity ARRIVING, which the
autoscaler reads as a scale-up already in flight), ``draining``
(healthz 503 / relay down — finishes in-flight streams, gets no new
sessions), ``down`` (breaker open or consecutive probe failures).  Replicas marked ``relay=True``
serve through the TPU probe relay: when ``utils.tpuprobe``'s
RelayMonitor last saw the relay down they are marked draining
IMMEDIATELY, without burning a per-replica HTTP timeout first — the
relay has one health signal and the router must reuse it, not
rediscover it as a timeout storm (BENCH_r02's down relay).

SSE pass-through: after the backend's status line is parsed, the relay
loop is a raw byte pump (recv → send per burst), so the engine's
burst-coalesced SSE chunks reach the client with their framing — and
their syscall economy — intact.  The router hop opens a ``fleet.route``
span whose context is forwarded as the backend ``traceparent`` header:
client → router → replica → engine step forms ONE W3C trace chain.
"""

from __future__ import annotations

import http.client
import json
import logging
import socket
import threading
import time
from collections import OrderedDict
from http.server import ThreadingHTTPServer
from typing import Optional

from ..faultinject import FAULTS
from ..metrics import (
    FLEET_REPLICAS,
    FLEET_ROUTE_OVERHEAD,
    FLEET_ROUTED,
    REGISTRY,
)
from ..slo import SLO
from ..tracing import TRACEPARENT_HEADER, TRACER
from ..utils import prefixdigest
from ..utils.backoff import Backoff
from ..utils.kvwire import KV_SOURCE_HEADER
from ..utils.tpuprobe import RELAY_MONITOR

log = logging.getLogger("tpu-scheduler")

REPLICA_STATES = ("up", "warming", "draining", "down")


def _post_json(
    addr: tuple[str, int], path: str, payload: bytes,
    timeout: float = 30.0,
) -> tuple[int, bytes]:
    """Small blocking replica POST (prefill split, migration command).
    http.client rather than a raw socket: these answers may be chunked,
    and hand-rolled chunk parsing is exactly the wire logic the stdlib
    already gets right.  Protocol errors surface as ConnectionError so
    callers keep one except-clause for 'the replica broke'."""
    conn = http.client.HTTPConnection(addr[0], addr[1], timeout=timeout)
    try:
        conn.request(
            "POST", path, payload, {"Content-Type": "application/json"}
        )
        resp = conn.getresponse()
        return resp.status, resp.read()
    except http.client.HTTPException as e:
        raise ConnectionError(f"malformed replica response: {e}") from None
    finally:
        conn.close()


def _scan_journey(journey: dict, data: bytes, now: float) -> None:
    """SLO journey telemetry from relayed bytes (only when the SLO plane
    is on — the pump stays a pure byte pump otherwise).  Counts SSE
    ``data:`` events for TTFT/TPOT, and picks the backend's queue-wait
    out of the one ``: slo {...}`` comment the stream path emits.  Cost
    per burst: one-two ``bytes.count`` scans."""
    if not data:
        return
    n = data.count(b"data:")
    if n:
        if journey.get("t_first") is None:
            journey["t_first"] = now
        journey["events"] = journey.get("events", 0) + n
        journey["done_events"] = (
            journey.get("done_events", 0) + data.count(b"data: [DONE]")
        )
        journey["t_last"] = now
    elif journey.get("t_first") is None:
        # non-SSE body bytes: first body byte IS the client-perceived
        # first response byte (a blocking completion's headers+body
        # arrive after generation)
        journey["t_first"] = now
    if "queue_ms" not in journey and b": slo " in data:
        i = data.find(b": slo ")
        end = data.find(b"\n", i)
        line = data[i + 6:end if end != -1 else len(data)]
        try:
            meta = json.loads(line)
            if isinstance(meta, dict) and "queue_ms" in meta:
                journey["queue_ms"] = float(meta["queue_ms"])
        except (ValueError, TypeError):
            pass  # a torn comment split across bursts: drop, not crash


class _RelayAborted(Exception):
    """The response relay broke AFTER bytes reached the client (client
    disconnect, or a backend drop mid-stream).  NOT failover-eligible —
    retrying would duplicate a partially-delivered generation — and a
    client hangup must never feed the replica's circuit breaker."""

    def __init__(self, reason: str, client_side: bool):
        super().__init__(reason)
        self.client_side = client_side


class Replica:
    """One serving backend.  Mutable health/load state is written by the
    health loop and the relay path; reads are GIL-atomic attribute loads
    (same stance as the engine's ``cancelled`` flag)."""

    def __init__(
        self, name: str, host: str, port: int, relay: bool = False
    ):
        self.name = name
        self.host = host
        self.port = int(port)
        # True = this replica serves through the TPU probe relay; its
        # health follows the RelayMonitor's signal without an HTTP probe
        self.relay = relay
        self.state = "up"  # optimistic: first health pass corrects it
        self.state_reason = "new"
        # router-imposed drain (scale-down victim, migration/resize
        # bracket): while True the health loop must NOT promote the
        # replica back to 'up' on a healthy probe — the backend engine
        # is healthy by design during a router-level drain
        self.pinned_draining = False
        # guards the (state, pinned_draining) pair: drain()/undrain()
        # and the health loop's promotion race on different threads, and
        # LOAD-check-STORE on two attributes is not atomic
        self._state_lock = threading.Lock()
        self.consecutive_failures = 0
        self.breaker_open_until = 0.0  # monotonic; 0 = closed
        # breaker cooldown policy (utils/backoff): each re-open after a
        # failed half-open probe grows the cooldown exponentially, and
        # EVERY cooldown is jittered — a fleet-wide flap that opened
        # every breaker in one instant must not close them all in one
        # instant either (the synchronized half-open probe storm)
        self._breaker_backoff = Backoff(base_s=0.0, max_s=120.0, jitter=0.5)
        # requests this router is relaying right now.  '+= 1' on an
        # attribute is LOAD/ADD/STORE — not atomic across handler
        # threads, and a lost decrement would block scale-down forever
        # (it waits for inflight == 0) — so mutations go through
        # inflight_enter/exit under a lock
        self.inflight = 0
        self._inflight_lock = threading.Lock()
        self.stats: dict = {}  # last /v1/stats payload
        self.stats_at = 0.0
        self.routed = 0  # total requests sent here

    @property
    def addr(self) -> tuple[str, int]:
        return (self.host, self.port)

    @property
    def role(self) -> str:
        """Disaggregated-serving role advertised on /v1/stats: 'prefill'
        replicas never receive completion traffic (they serve
        /v1/prefill + /v1/kv/export only); 'decode'/'both' do."""
        return str(self.stats.get("role") or "both")

    def exportable(self, now: float) -> bool:
        """Can this replica still serve /v1/kv/export pulls?  Draining
        is fine (the engine is healthy, it just takes no new sessions);
        down/breaker-open means nobody should connect at all."""
        return (
            self.state in ("up", "draining")
            and now >= self.breaker_open_until
        )

    def inflight_enter(self) -> None:
        with self._inflight_lock:
            self.inflight += 1

    def inflight_exit(self) -> None:
        with self._inflight_lock:
            self.inflight -= 1

    def load_key(self) -> tuple:
        """Least-loaded ordering: queued work first (the thing a new
        request actually waits behind), then slot occupancy, then name
        for determinism."""
        queued = int(self.stats.get("queued", 0)) + self.inflight
        slots = int(self.stats.get("active_slots", 0))
        max_batch = max(1, int(self.stats.get("max_batch", 1)))
        return (queued, slots / max_batch, self.name)

    def routable(self, now: float) -> bool:
        return self.state == "up" and now >= self.breaker_open_until

    def note_failure(self, threshold: int, cooldown_s: float) -> None:
        self.consecutive_failures += 1
        if self.consecutive_failures >= threshold:
            # jittered, escalating cooldown: base follows the configured
            # cooldown, repeat opens back off exponentially (capped)
            bo = self._breaker_backoff
            bo.base_s = max(0.01, float(cooldown_s))
            self.breaker_open_until = time.monotonic() + bo.next_delay()
            self.state = "down"
            self.state_reason = (
                f"circuit breaker open ({self.consecutive_failures} "
                "consecutive failures)"
            )

    def note_success(self) -> None:
        self.consecutive_failures = 0
        self.breaker_open_until = 0.0
        self._breaker_backoff.reset()

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "addr": f"{self.host}:{self.port}",
            "state": self.state,
            "reason": self.state_reason,
            "relay": self.relay,
            "role": self.role,
            "inflight": self.inflight,
            "routed": self.routed,
            "consecutive_failures": self.consecutive_failures,
            "breaker_open": time.monotonic() < self.breaker_open_until,
            "queued": self.stats.get("queued"),
            "active_slots": self.stats.get("active_slots"),
            "max_batch": self.stats.get("max_batch"),
            "kv": self.stats.get("kv"),
        }


class ReplicaSet:
    """The router's replica registry + health loop.

    ``relay_monitor`` is injectable for tests; it defaults to the
    process-global RELAY_MONITOR the scheduler CLI starts.  The health
    loop is the ONLY writer of ``state`` for live replicas (the relay
    path may open a breaker, which the next health pass reconciles)."""

    def __init__(
        self,
        interval_s: float = 2.0,
        probe_timeout_s: float = 1.0,
        breaker_threshold: int = 3,
        breaker_cooldown_s: float = 5.0,
        relay_monitor=None,
    ):
        self.interval_s = max(0.05, float(interval_s))
        self.probe_timeout_s = probe_timeout_s
        self.breaker_threshold = max(1, breaker_threshold)
        self.breaker_cooldown_s = breaker_cooldown_s
        self.relay_monitor = (
            relay_monitor if relay_monitor is not None else RELAY_MONITOR
        )
        self._lock = threading.Lock()  # guards the dict, not replica fields
        self._replicas: dict[str, Replica] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # leaving-rotation listeners (name, reason): fired when a replica
        # is removed, pinned-draining (scale-down / migration victim) or
        # observed transitioning to 'down' — the router prunes its
        # prefix-index entries here so a stale digest can never route a
        # prompt at a dead backend ahead of the health fallback
        self.on_leave: list = []
        self._last_states: dict[str, str] = {}

    # -- membership ----------------------------------------------------------

    def add(self, replica: Replica) -> Replica:
        with self._lock:
            self._replicas[replica.name] = replica
        return replica

    def remove(self, name: str) -> Optional[Replica]:
        with self._lock:
            r = self._replicas.pop(name, None)
            self._last_states.pop(name, None)
        if r is not None:
            self._fire_leave(name, "removed")
        return r

    def _fire_leave(self, name: str, reason: str) -> None:
        for cb in list(self.on_leave):
            try:
                cb(name, reason)
            except Exception:
                log.exception("replica-leave listener failed for %s", name)

    def get(self, name: str) -> Optional[Replica]:
        with self._lock:
            return self._replicas.get(name)

    def all(self) -> list[Replica]:
        with self._lock:
            return sorted(self._replicas.values(), key=lambda r: r.name)

    def routable(self) -> list[Replica]:
        now = time.monotonic()
        return [r for r in self.all() if r.routable(now)]

    def drain(self, name: str, reason: str = "requested") -> bool:
        """Mark a replica draining (no new sessions; in-flight streams
        finish) — the scale-down path's first step.  PINNED: the health
        loop will not promote it back to 'up' on a healthy probe (the
        backend IS healthy during a router-level drain); ``undrain``
        releases it."""
        r = self.get(name)
        if r is None:
            return False
        with r._state_lock:
            r.state = "draining"
            r.state_reason = reason
            r.pinned_draining = True
        # a pinned drain IS leaving rotation (scale-down victim, move in
        # progress): affinity must stop steering repeated prefixes here
        self._fire_leave(name, f"draining: {reason}")
        return True

    def undrain(self, name: str, reason: str = "restored") -> bool:
        """Release a router-imposed drain (scale-down refused, move
        complete): the replica is routable again and the health loop
        resumes normal state management."""
        r = self.get(name)
        if r is None:
            return False
        with r._state_lock:
            r.pinned_draining = False
            if r.state == "draining":
                r.state = "up"
                r.state_reason = reason
        return True

    # -- health --------------------------------------------------------------

    def _http_get(self, replica: Replica, path: str) -> tuple[int, bytes]:
        """Tiny one-shot GET (no http.client: its default parsing is
        fine, but a 3-line raw exchange keeps the probe dependency-free
        and its timeout semantics obvious)."""
        if FAULTS.enabled:
            FAULTS.maybe_fire("router.probe")
        with socket.create_connection(
            replica.addr, timeout=self.probe_timeout_s
        ) as s:
            s.settimeout(self.probe_timeout_s)
            s.sendall(
                f"GET {path} HTTP/1.1\r\nHost: {replica.host}\r\n"
                "Connection: close\r\n\r\n".encode()
            )
            buf = b""
            while True:
                b = s.recv(65536)
                if not b:
                    break
                buf += b
        head, _, body = buf.partition(b"\r\n\r\n")
        try:
            status = int(head.split(b" ", 2)[1])
        except (IndexError, ValueError):
            raise ConnectionError("malformed status line")
        return status, body

    def refresh_one(self, r: Replica) -> None:
        """One health pass for one replica.  Relay-backed replicas are
        resolved from the RelayMonitor's last probe FIRST: a down relay
        means every replica behind it is draining NOW — reusing the
        monitor's state instead of discovering the outage one HTTP
        timeout at a time (the timeout-storm failure mode)."""
        if r.pinned_draining:
            # router-imposed drain (scale-down / move in progress): the
            # backend probing healthy is expected and must NOT flip the
            # replica routable mid-drain
            r.state = "draining"
            return
        if r.relay and self.relay_monitor.up is False:
            r.state = "draining"
            r.state_reason = (
                f"TPU relay down (RelayMonitor: {self.relay_monitor.detail})"
            )
            return
        try:
            status, body = self._http_get(r, "/healthz")
        except (OSError, ConnectionError) as e:
            r.note_failure(self.breaker_threshold, self.breaker_cooldown_s)
            if r.consecutive_failures < self.breaker_threshold:
                # transient: stay in the current state one more round
                r.state_reason = f"healthz failed: {e}"
            return
        if status == 503:
            # a 503 is NOT one state: a replica mid-warm-up (compile
            # lattice pre-lowering, compilecache/) answers 503
            # {"warming": true} and is about to become capacity — the
            # autoscaler must not scale again for it, and the router
            # must not route into its compile storm.  Anything else is
            # the classic drain.  Body parse failure = drain (the
            # conservative historical reading).
            try:
                payload = json.loads(body)
            except ValueError:
                payload = {}
            if isinstance(payload, dict) and payload.get("warming"):
                r.state = "warming"
                wu = payload.get("warmup") or {}
                r.state_reason = (
                    "warming: lattice "
                    f"{wu.get('built', 0)}/{wu.get('lattice_size', 0)} "
                    "pre-lowered"
                )
                r.note_success()
                # stats stay advisory but useful mid-warm-up (warm-up
                # progress, page/queue config for the debug surfaces)
                self._poll_stats(r)
                return
            r.state = "draining"
            r.state_reason = "healthz 503 (replica draining)"
            r.note_success()
            return
        if status != 200:
            r.note_failure(self.breaker_threshold, self.breaker_cooldown_s)
            r.state_reason = f"healthz {status}"
            return
        r.note_success()
        with r._state_lock:
            # re-check UNDER the state lock: a drain() that landed while
            # the probe was in flight must not be overwritten by this
            # healthy result (check-then-set on two attributes races
            # without the lock)
            if r.pinned_draining:
                r.state = "draining"
                return
            r.state = "up"
            r.state_reason = "healthy"
        self._poll_stats(r)

    def _poll_stats(self, r: Replica) -> None:
        try:
            sstat, body = self._http_get(r, "/v1/stats")
            if sstat == 200:
                r.stats = json.loads(body)
                r.stats_at = time.monotonic()
        except (OSError, ConnectionError, ValueError):
            pass  # load data is advisory; health already answered

    def refresh(self) -> None:
        for r in self.all():
            self.refresh_one(r)
        counts = {s: 0 for s in REPLICA_STATES}
        for r in self.all():
            counts[r.state] = counts.get(r.state, 0) + 1
            # down-transition detection AFTER the pass: catches both the
            # health loop's own verdicts and breaker opens fed by the
            # relay path between passes
            prev = self._last_states.get(r.name)
            if r.state == "down" and prev != "down":
                self._fire_leave(r.name, r.state_reason or "down")
            self._last_states[r.name] = r.state
        for s, n in counts.items():
            FLEET_REPLICAS.set(s, value=float(n))

    def start(self) -> "ReplicaSet":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.refresh()
                except Exception:
                    log.exception("fleet health pass failed")
                if self._stop.wait(self.interval_s):
                    return

        self._thread = threading.Thread(
            target=loop, name="fleet-health", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)


class PrefixIndex:
    """Fleet-wide prefix-cache index: digest-chain link → the replicas
    believed to hold that prefix's KV pages (a prefix can live on
    SEVERAL replicas once pages ship — adoption, prefill export,
    migration — and the router should know every copy).  Bounded LRU on
    digests; a holder whose pages were LRU-evicted replica-side just
    costs one empty export (adoption falls back to re-prefill), so no
    per-holder freshness is tracked.
    ``drop_replica`` prunes every entry naming a replica that
    left rotation — the satellite bugfix: without it a stale digest
    keeps steering repeated prompts at a dead backend until the LRU
    happens to age it out."""

    def __init__(self, cap: int = 65536):
        self._map: "OrderedDict[bytes, set[str]]" = OrderedDict()
        self._cap = max(1024, int(cap))
        self._lock = threading.Lock()

    def record(self, digests: list[bytes], name: str) -> None:
        if not digests:
            return
        with self._lock:
            for d in digests:
                ent = self._map.get(d)
                if ent is None:
                    ent = self._map[d] = set()
                ent.add(name)
                self._map.move_to_end(d)
            while len(self._map) > self._cap:
                self._map.popitem(last=False)

    def lookup(self, digests: list[bytes]) -> dict[str, int]:
        """replica name → matched page count (each replica's LONGEST
        known link of this chain).  Touches the longest hit digest."""
        out: dict[str, int] = {}
        with self._lock:
            touched = False
            for k in range(len(digests) - 1, -1, -1):
                ent = self._map.get(digests[k])
                if not ent:
                    continue
                if not touched:
                    self._map.move_to_end(digests[k])
                    touched = True
                for name in ent:
                    if name not in out:
                        out[name] = k + 1
        return out

    def drop_replica(self, name: str) -> int:
        """Prune every entry naming ``name``; returns digests touched."""
        with self._lock:
            dead = []
            n = 0
            for d, ent in self._map.items():
                if name in ent:
                    ent.discard(name)
                    n += 1
                    if not ent:
                        dead.append(d)
            for d in dead:
                del self._map[d]
        return n

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)


class FleetRouter:
    """The /v1/* front door over a ReplicaSet (see the module docstring
    for policy).  ``page_size`` must match the replicas' engine page
    size for affinity hits to be REAL cache hits; the health loop adopts
    the first replica's advertised value when they disagree.

    Disaggregated-serving knobs: ``adopt`` (pull pages to the chosen
    replica when the prefix's holders aren't routable; default on),
    ``adopt_load_margin`` (> 0 enables load-based shedding: route AWAY
    from an overloaded holder and adopt instead when its queue exceeds
    the best alternative's by this many requests; 0 = affinity always
    wins, the historic behavior), ``disagg_min_pages`` (a no-hit prompt
    with at least this many full pages routes through a prefill-role
    replica first when one is up; 0 disables the split)."""

    def __init__(
        self,
        replicas: ReplicaSet,
        host: str = "0.0.0.0",
        port: int = 8100,
        page_size: int = 16,
        prefix_cap: int = 65536,
        max_affinity_pages: int = 64,
        backend_timeout_s: float = 300.0,
        adopt: bool = True,
        adopt_min_pages: int = 1,
        adopt_load_margin: float = 0.0,
        disagg_min_pages: int = 4,
    ):
        self.replicas = replicas
        self.host = host
        self.port = port
        self.page_size = max(1, int(page_size))
        self.max_affinity_pages = max(1, int(max_affinity_pages))
        self.backend_timeout_s = backend_timeout_s
        self.adopt = bool(adopt)
        self.adopt_min_pages = max(1, int(adopt_min_pages))
        self.adopt_load_margin = float(adopt_load_margin)
        self.disagg_min_pages = max(0, int(disagg_min_pages))
        # optional callable → dict serving the COMBINED fleet payload
        # (router + autoscaler + resize) at this port's /debug/fleet —
        # the CLI wires FleetState.debug_state here so both servers
        # answer with the same shape; unset (library use) falls back to
        # the router-only view
        self.state_provider = None
        # SLO plane (slo/): the router is the one vantage that sees
        # client-perceived latency, so it records one journey per routed
        # completion when objectives are loaded (SLO.enabled); the
        # assembler (slo/assembly.py, wired by the CLI) serves
        # /debug/trace/<id> cross-process on this port
        self.slo = SLO
        self.assembler = None
        # the fleet-wide prefix-cache index; entries naming a replica
        # that leaves rotation are pruned via the leave listener
        self.prefix_index = PrefixIndex(prefix_cap)
        replicas.on_leave.append(self._on_replica_leave)
        self._page_size_resolved = False  # one-shot adoption latch
        self.affinity_hits = 0
        self.affinity_requests = 0
        self.matched_pages = 0
        self.adoptions = 0  # routes shipped with an X-KV-Source header
        self.disagg_prefills = 0  # long prompts split through prefill
        self.migrations = 0  # migrate_session calls that handed off
        self.pruned_digests = 0  # index entries dropped by leave events
        self.requests = 0
        # per-request router overhead samples (seconds) — the
        # FLEET_ROUTE_OVERHEAD histogram's raw tail for tools that need
        # an exact p99 (bench fleet section, check-fleet); bounded like
        # the engine's gap buffer
        self.overhead_samples: list[float] = []
        self._overhead_cap = 8192
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- routing -------------------------------------------------------------

    def _adopt_page_size(self) -> None:
        """Reconcile the affinity page size with what replicas actually
        advertise on /v1/stats: a mismatched configuration would keep
        'hitting' digests that no engine's cache keys by, silently
        degrading affinity to sticky-random routing.  First advertised
        value wins; adoption clears the map (its digests were chained at
        the wrong page boundaries).  One-shot: after any replica has
        answered, the latch keeps this off the per-request path."""
        if self._page_size_resolved:
            return
        for r in self.replicas.all():
            ps = r.stats.get("page_size")
            if not ps:
                continue
            ps = int(ps)
            if ps != self.page_size:
                log.warning(
                    "fleet router adopting replica-advertised page_size "
                    "%d (configured %d); prefix index reset",
                    ps, self.page_size,
                )
                self.prefix_index = PrefixIndex(self.prefix_index._cap)
                self.page_size = ps
            self._page_size_resolved = True
            return

    def _on_replica_leave(self, name: str, reason: str) -> None:
        n = self.prefix_index.drop_replica(name)
        if n:
            self.pruned_digests += n
            log.info(
                "fleet router pruned %d prefix-index digests for "
                "replica %s leaving rotation (%s)", n, name, reason,
            )

    def _digests(self, body: dict) -> list[bytes]:
        prompt = body.get("prompt")
        if not isinstance(prompt, list) or not all(
            isinstance(t, int) and not isinstance(t, bool)
            # int32 range: the chain hashes native int32 bytes; an
            # out-of-range id would raise OverflowError from the hasher
            # and kill the handler thread — the BACKEND owns rejecting
            # it with a proper 400, the router just declines to hash
            and -(2 ** 31) <= t < 2 ** 31
            for t in prompt
        ):
            return []
        self._adopt_page_size()
        adapter = str(body.get("adapter", ""))
        # adapter NAME seeds the router's chain (the engine seeds by bank
        # index, which the router never sees; equality semantics — same
        # adapter ⇔ same seed — are what affinity needs)
        seed = (
            prefixdigest.prefix_seed(0)
            if not adapter
            else b"adapter:" + adapter.encode()
        )
        return prefixdigest.page_digests(
            prompt, self.page_size, max_pages=self.max_affinity_pages,
            seed=seed,
        )

    def _affinity_record(self, digests: list[bytes], name: str) -> None:
        self.prefix_index.record(digests, name)

    def _completion_candidates(self) -> list[Replica]:
        """Routable replicas that take completion traffic — the
        prefill/decode split keeps prefill-role replicas out."""
        now = time.monotonic()
        return [
            r for r in self.replicas.all()
            if r.routable(now) and r.role != "prefill"
        ]

    def select(
        self, body: dict
    ) -> tuple[Optional[Replica], str, list[bytes], Optional[Replica]]:
        """(replica, kind, digests, donor): the routing decision, before
        any network IO.  kind ∈ affinity | adopt | least_loaded |
        no_replica; ``donor`` (adopt only) is the replica the target
        should pull the prefix's KV pages from (X-KV-Source)."""
        candidates = self._completion_candidates()
        digests = self._digests(body)
        if digests:
            self.affinity_requests += 1
        if not candidates:
            return None, "no_replica", digests, None
        matches = self.prefix_index.lookup(digests) if digests else {}
        least = min(candidates, key=lambda r: r.load_key())
        if matches:
            by_name = {r.name: r for r in self.replicas.all()}
            cand_names = {r.name for r in candidates}
            routable_holders = sorted(
                ((pages, by_name[n]) for n, pages in matches.items()
                 if n in cand_names),
                key=lambda t: (-t[0], t[1].load_key()),
            )
            if routable_holders:
                pages, best = routable_holders[0]
                if (
                    self.adopt
                    and self.adopt_load_margin > 0
                    and best is not least
                    and pages >= self.adopt_min_pages
                    and best.load_key()[0] - least.load_key()[0]
                    >= self.adopt_load_margin
                ):
                    # the holder is the hot spot: move the KV, not the
                    # request — the least-loaded candidate pulls the
                    # pages and takes the session (load-margin shedding)
                    self.matched_pages += pages
                    return least, "adopt", digests, best
                self.affinity_hits += 1
                self.matched_pages += pages
                return best, "affinity", digests, None
            # holders exist but none takes completions (draining /
            # warming / prefill-role / just removed): adopt the prefix
            # onto the best candidate from any holder still able to
            # serve exports
            now = time.monotonic()
            donors = sorted(
                ((pages, by_name[n]) for n, pages in matches.items()
                 if n in by_name and by_name[n].exportable(now)),
                key=lambda t: -t[0],
            )
            if (
                self.adopt and donors
                and donors[0][0] >= self.adopt_min_pages
            ):
                pages, donor = donors[0]
                self.matched_pages += pages
                return least, "adopt", digests, donor
        return least, "least_loaded", digests, None

    def failover_order(self, first: Replica) -> list[Replica]:
        rest = sorted(
            (
                r for r in self._completion_candidates()
                if r.name != first.name
            ),
            key=lambda r: r.load_key(),
        )
        return [first] + rest

    # -- disaggregated serving orchestration ---------------------------------

    def _prefill_split(self, body: dict, digests: list[bytes]) -> Optional[Replica]:
        """Route a long no-hit prompt through a prefill-role replica:
        POST /v1/prefill there (chunked prefill caches the pages), then
        return it as the donor the decode replica adopts from.  Returns
        None when the split doesn't apply or the prefill failed (the
        request then just prefills on the decode replica — correctness
        never depends on the split)."""
        if self.disagg_min_pages <= 0 or len(digests) < self.disagg_min_pages:
            return None
        now = time.monotonic()
        prefills = [
            r for r in self.replicas.all()
            if r.routable(now) and r.role == "prefill"
        ]
        if not prefills:
            return None
        target = min(prefills, key=lambda r: r.load_key())
        payload = json.dumps({
            "prompt": body.get("prompt"),
            "adapter": str(body.get("adapter", "")),
        }).encode()
        target.inflight_enter()
        try:
            status, _body = _post_json(
                target.addr, "/v1/prefill", payload,
                timeout=self.backend_timeout_s,
            )
        except (OSError, ConnectionError) as e:
            log.warning("disagg prefill on %s failed: %s", target.name, e)
            target.note_failure(
                self.replicas.breaker_threshold,
                self.replicas.breaker_cooldown_s,
            )
            return None
        finally:
            target.inflight_exit()
        if status != 200:
            return None
        target.note_success()
        target.routed += 1
        self.disagg_prefills += 1
        # the prefill replica now holds the pages: index them so later
        # repeats of the prefix adopt from it directly
        self._affinity_record(digests, target.name)
        return target

    def migrate_session(
        self, src: str, dst: str, slot: Optional[int] = None,
        timeout: float = 30.0,
    ) -> dict:
        """Command a live session handoff: POST /v1/migrate/out on
        ``src`` naming ``dst`` as the destination (both replica names).
        Returns the backend's verdict plus ok=False shapes for
        transport errors — the autoscaler's rebalance path consumes
        this, journaling each call as a ``kv_migrate`` record."""
        s, d = self.replicas.get(src), self.replicas.get(dst)
        if s is None or d is None:
            return {"ok": False, "error": "unknown replica"}
        body = {"dest": f"{d.host}:{d.port}"}
        if slot is not None:
            body["slot"] = int(slot)
        try:
            status, payload = _post_json(
                s.addr, "/v1/migrate/out", json.dumps(body).encode(),
                timeout=timeout,
            )
        except (OSError, ConnectionError) as e:
            return {"ok": False, "error": str(e)}
        try:
            res = json.loads(payload)
        except ValueError:
            res = {}
        res.setdefault("ok", status == 200)
        res["status"] = status
        if res.get("ok"):
            # the session's KV lives on dst now; index updates ride the
            # next routed request for that prefix
            self.migrations += 1
        return res

    # -- relay ---------------------------------------------------------------

    def _forward(
        self,
        replica: Replica,
        method: str,
        path: str,
        body: bytes,
        traceparent: str,
        client_sock: socket.socket,
        extra_headers: Optional[dict] = None,
        journey: Optional[dict] = None,
    ) -> tuple[int, float]:
        """Send the request to ``replica`` and pump the response back to
        the client verbatim.  Returns (backend status, router overhead
        seconds — connect + request forward; the wait for the backend's
        first byte is GENERATION time for non-streamed completions and
        deliberately excluded).  Raises before any client byte is
        written if the backend is unreachable or answers 5xx, so the
        caller can fail over cleanly."""
        t0 = time.perf_counter()
        if FAULTS.enabled:
            # router→replica socket: 'partition'/'error' here exercises
            # the before-first-client-byte failover path deterministically
            FAULTS.maybe_fire("router.connect")
        bs = socket.create_connection(replica.addr, timeout=5.0)
        try:
            bs.settimeout(self.backend_timeout_s)
            headers = (
                f"{method} {path} HTTP/1.1\r\n"
                f"Host: {replica.host}\r\n"
                f"Content-Length: {len(body)}\r\n"
                "Content-Type: application/json\r\n"
                "Connection: close\r\n"
            )
            if traceparent:
                headers += f"{TRACEPARENT_HEADER}: {traceparent}\r\n"
            for k, v in (extra_headers or {}).items():
                headers += f"{k}: {v}\r\n"
            bs.sendall(headers.encode("latin1") + b"\r\n" + body)
            overhead = time.perf_counter() - t0
            # read until the backend's header block is complete: the
            # status decides failover vs relay, and nothing is forwarded
            # to the client until that decision is made
            buf = b""
            while b"\r\n\r\n" not in buf:
                b = bs.recv(65536)
                if not b:
                    raise ConnectionError("backend closed before headers")
                buf += b
            try:
                status = int(buf.split(b" ", 2)[1])
            except (IndexError, ValueError):
                raise ConnectionError("malformed backend status line")
            if status >= 500:
                raise ConnectionError(f"backend answered {status}")
            if journey is not None:
                # backend queue wait rides a response header on blocking
                # completions (streams carry it as an SSE comment the
                # scan below picks up)
                head, _, body_start = buf.partition(b"\r\n\r\n")
                for hline in head.split(b"\r\n")[1:]:
                    k, _, v = hline.partition(b":")
                    if k.strip().lower() == b"x-tpu-queue-wait-ms":
                        try:
                            journey["queue_ms"] = float(v.strip())
                        except ValueError:
                            pass
                        break
                _scan_journey(
                    journey, body_start, time.perf_counter()
                )
            # byte pump: each backend burst (the engine coalesces SSE
            # events into one chunk per burst) is one send to the client
            # — framing and syscall economy pass through unchanged.
            # From the first client write on, failures are _RelayAborted
            # (see class docstring), never failover
            try:
                client_sock.sendall(buf)
            except OSError as e:
                raise _RelayAborted(f"client write failed: {e}", True)
            while True:
                try:
                    b = bs.recv(65536)
                except OSError as e:
                    raise _RelayAborted(
                        f"backend dropped mid-stream: {e}", False
                    )
                if not b:
                    break
                if journey is not None:
                    _scan_journey(journey, b, time.perf_counter())
                try:
                    client_sock.sendall(b)
                except OSError as e:
                    raise _RelayAborted(f"client write failed: {e}", True)
            return status, overhead
        finally:
            try:
                bs.close()
            except OSError:
                pass

    def handle_completion(
        self,
        method: str,
        path: str,
        raw: bytes,
        traceparent: str,
        client_sock: socket.socket,
    ) -> Optional[tuple[int, bytes]]:
        """Route one /v1/* request.  Returns (status, json body) when
        the router must answer itself (no replica / bad body); None when
        the response was already relayed to the client."""
        self.requests += 1
        try:
            body = json.loads(raw or b"{}")
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
        except ValueError as e:
            return 400, json.dumps({"error": f"router: {e}"}).encode()
        # SLO request journey: the router is the one vantage that sees
        # client-perceived latency.  One dict per request when the plane
        # is on; the relay's scan fills TTFT/token timing into it, and
        # _record_journey folds it into the per-class windows.
        slo_on = self.slo.enabled and path == "/v1/completions"
        journey: Optional[dict] = (
            {"t0": time.perf_counter()} if slo_on else None
        )
        jevents: list = []
        with TRACER.span(
            "fleet.route", parent=traceparent or None, path=path,
            stream=bool(body.get("stream")),
        ) as sp:
            replica, kind, digests, donor = self.select(body)
            if replica is None:
                FLEET_ROUTED.inc("no_replica")
                sp.set_attr("kind", "no_replica")
                if journey is not None:
                    self._record_journey(
                        body, sp, journey, jevents, ok=False,
                        kind="no_replica", replica="", status=503,
                    )
                return 503, json.dumps(
                    {"error": "no serving replica available"}
                ).encode()
            if (
                kind == "least_loaded"
                and path == "/v1/completions"
                and donor is None
            ):
                # prefill/decode split: a long no-hit prompt prefills on
                # a prefill-role replica; the decode target then adopts
                # the pages instead of stalling its slots on the prompt
                donor = self._prefill_split(body, digests)
                if donor is not None:
                    kind = "disagg"
                    jevents.append({
                        "event": "prefill_split", "replica": donor.name,
                    })
            # the router hop joins the W3C chain: the backend request
            # carries THIS span's context, so the replica's serve.request
            # span becomes its child
            backend_tp = sp.traceparent() if sp else traceparent
            attempt_kind = kind
            last_err: Optional[str] = None
            extra = None
            if donor is not None:
                # adoption: the target pulls the prefix's pages from the
                # donor before admission (utils/kvwire; best-effort on
                # the backend — a failed pull just re-prefills)
                extra = {KV_SOURCE_HEADER: f"{donor.host}:{donor.port}"}
                self.adoptions += 1
                sp.set_attr("kv_source", donor.name)
                if kind == "adopt":
                    jevents.append({
                        "event": "adopt", "donor": donor.name,
                    })
            for target in self.failover_order(replica):
                target.inflight_enter()
                try:
                    status, overhead = self._forward(
                        target, method, path, raw, backend_tp,
                        client_sock, extra_headers=extra,
                        journey=journey,
                    )
                except _RelayAborted as e:
                    # bytes already reached the client: no failover (a
                    # retry would duplicate a partial generation), and a
                    # client hangup never feeds the replica's breaker
                    if not e.client_side:
                        target.note_failure(
                            self.replicas.breaker_threshold,
                            self.replicas.breaker_cooldown_s,
                        )
                    FLEET_ROUTED.inc("aborted")
                    sp.set_attr("kind", "aborted")
                    sp.set_attr("replica", target.name)
                    sp.end(status="error")
                    if journey is not None:
                        jevents.append({
                            "event": "aborted",
                            "client_side": e.client_side,
                        })
                        self._record_journey(
                            body, sp, journey, jevents, ok=False,
                            kind="aborted", replica=target.name,
                            status=499,
                        )
                    return None
                except (OSError, ConnectionError) as e:
                    last_err = str(e)
                    target.note_failure(
                        self.replicas.breaker_threshold,
                        self.replicas.breaker_cooldown_s,
                    )
                    attempt_kind = "failover"
                    jevents.append({
                        "event": "failover", "replica": target.name,
                        "error": str(e)[:120],
                    })
                    if target.state == "down":
                        jevents.append({
                            "event": "breaker_open",
                            "replica": target.name,
                        })
                    continue
                finally:
                    target.inflight_exit()
                target.note_success()
                target.routed += 1
                self._affinity_record(digests, target.name)
                FLEET_ROUTED.inc(attempt_kind)
                FLEET_ROUTE_OVERHEAD.observe(value=overhead)
                self.overhead_samples.append(overhead)
                if len(self.overhead_samples) > self._overhead_cap:
                    del self.overhead_samples[: self._overhead_cap // 2]
                sp.set_attr("replica", target.name)
                sp.set_attr("kind", attempt_kind)
                sp.set_attr("overhead_ms", round(overhead * 1e3, 3))
                sp.set_attr("status", status)
                if journey is not None:
                    journey["hop_ms"] = overhead * 1000
                    self._record_journey(
                        body, sp, journey, jevents, ok=status < 400,
                        kind=attempt_kind, replica=target.name,
                        status=status,
                    )
                return None
            # distinct from no_replica (nothing routable → 503): here
            # replicas LOOKED routable but every connect/forward failed
            FLEET_ROUTED.inc("exhausted")
            sp.set_attr("kind", "exhausted")
            if journey is not None:
                self._record_journey(
                    body, sp, journey, jevents, ok=False,
                    kind="exhausted", replica="", status=502,
                )
            return 502, json.dumps(
                {"error": f"every replica failed (last: {last_err})"}
            ).encode()

    def _record_journey(
        self, body: dict, sp, journey: dict, jevents: list,
        ok: bool, kind: str, replica: str, status: int,
    ) -> None:
        """Fold one relayed request into the SLO plane's journey ring
        (hot-path cost: arithmetic + one list append)."""
        now = time.perf_counter()
        t0 = journey["t0"]
        t_first = journey.get("t_first")
        t_last = journey.get("t_last")
        tokens = max(
            0, journey.get("events", 0) - journey.get("done_events", 0)
        )
        tpot_ms = None
        if tokens > 1 and t_first is not None and t_last is not None \
                and t_last > t_first:
            tpot_ms = round((t_last - t_first) * 1000 / (tokens - 1), 3)
        self.slo.record_journey(
            wclass=str(
                body.get("workload_class") or self.slo.default_class
            ),
            tenant=str(body.get("tenant", "")),
            ok=ok,
            ttft_ms=(
                round((t_first - t0) * 1000, 3)
                if t_first is not None else None
            ),
            tpot_ms=tpot_ms,
            e2e_ms=round((now - t0) * 1000, 3),
            queue_ms=journey.get("queue_ms"),
            hop_ms=(
                round(journey["hop_ms"], 3)
                if journey.get("hop_ms") is not None else None
            ),
            tokens=tokens,
            trace_id=sp.trace_id if sp else "",
            replica=replica,
            kind=kind,
            events=jevents + [{"status": status}],
            vantage="router",
        )

    # -- introspection -------------------------------------------------------

    def debug_state(self) -> dict:
        return {
            "replicas": [r.to_dict() for r in self.replicas.all()],
            "requests": self.requests,
            "affinity": {
                "requests": self.affinity_requests,
                "hits": self.affinity_hits,
                "hit_pct": round(
                    100.0 * self.affinity_hits
                    / max(1, self.affinity_requests), 2,
                ),
                "matched_pages": self.matched_pages,
                "map_entries": len(self.prefix_index),
                "page_size": self.page_size,
            },
            "disagg": {
                "adoptions": self.adoptions,
                "disagg_prefills": self.disagg_prefills,
                "migrations": self.migrations,
                "pruned_digests": self.pruned_digests,
                "adopt": self.adopt,
                "adopt_load_margin": self.adopt_load_margin,
                "disagg_min_pages": self.disagg_min_pages,
            },
        }

    def aggregate_stats(self) -> dict:
        """Fleet-wide /v1/stats: per-replica payloads plus sums a client
        can capacity-plan on."""
        reps = self.replicas.all()
        agg = {
            "queued": sum(int(r.stats.get("queued", 0)) for r in reps),
            "active_slots": sum(
                int(r.stats.get("active_slots", 0)) for r in reps
            ),
            "max_batch": sum(int(r.stats.get("max_batch", 0)) for r in reps),
            "replicas_up": sum(1 for r in reps if r.state == "up"),
            "replicas": {r.name: r.stats for r in reps},
        }
        return agg

    # -- HTTP lifecycle ------------------------------------------------------

    def _make_handler(router):
        import socketserver

        class Handler(socketserver.StreamRequestHandler):
            disable_nagle_algorithm = True
            rbufsize = 1 << 16

            def handle(self):
                try:
                    self._one_request()
                except (ConnectionError, BrokenPipeError, TimeoutError):
                    pass

            def _respond(self, code: int, payload: bytes,
                         ctype: str = "application/json") -> None:
                reasons = {200: "OK", 400: "Bad Request", 404: "Not Found",
                           502: "Bad Gateway", 503: "Service Unavailable"}
                head = (
                    f"HTTP/1.1 {code} {reasons.get(code, 'OK')}\r\n"
                    f"Content-Type: {ctype}\r\n"
                    f"Content-Length: {len(payload)}\r\n"
                    "Connection: close\r\n\r\n"
                ).encode("latin1")
                self.wfile.write(head + payload)
                self.wfile.flush()

            def _one_request(self) -> None:
                line = self.rfile.readline(8192)
                if not line:
                    return
                try:
                    method, target, _version = (
                        line.decode("latin1").split()
                    )
                except ValueError:
                    return
                clen = 0
                traceparent = ""
                while True:
                    h = self.rfile.readline(8192)
                    if h in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = h.partition(b":")
                    k = k.strip().lower()
                    if k == b"content-length":
                        try:
                            clen = int(v.strip())
                        except ValueError:
                            return
                    elif k == b"traceparent":
                        traceparent = v.strip().decode("latin1")
                raw = self.rfile.read(clen) if clen > 0 else b""
                path = target.partition("?")[0]
                if method == "GET":
                    if path == "/healthz":
                        up = len(router.replicas.routable())
                        code = 200 if up else 503
                        return self._respond(code, json.dumps(
                            {"ok": up > 0, "replicas_up": up}
                        ).encode())
                    if path == "/v1/stats":
                        return self._respond(
                            200, json.dumps(router.aggregate_stats()).encode()
                        )
                    if path in ("/debug/fleet", "/fleet"):
                        provider = router.state_provider
                        payload = (
                            provider() if provider is not None
                            else router.debug_state()
                        )
                        return self._respond(
                            200, json.dumps(payload, indent=1).encode(),
                        )
                    if path == "/debug/slo":
                        return self._respond(
                            200,
                            json.dumps(
                                router.slo.debug_state(), indent=1
                            ).encode(),
                        )
                    if path.startswith("/debug/trace/"):
                        # cross-process assembly when the CLI wired an
                        # assembler; local-ring fallback otherwise
                        tid = path[len("/debug/trace/"):]
                        if router.assembler is not None:
                            payload = router.assembler.assemble(tid)
                        else:
                            from ..slo.assembly import (
                                local_trace_payload,
                            )

                            payload = local_trace_payload(tid)
                        return self._respond(
                            200, json.dumps(payload, indent=1).encode(),
                        )
                    if path == "/metrics":
                        return self._respond(
                            200, REGISTRY.expose().encode(), "text/plain"
                        )
                    return self._respond(
                        404, json.dumps({"error": f"no route {path}"}).encode()
                    )
                if method == "POST" and path.startswith("/v1/"):
                    # flush our buffered writer before the relay writes to
                    # the raw socket (it is empty here, but the invariant
                    # must hold if a header is ever written first)
                    self.wfile.flush()
                    answered = router.handle_completion(
                        method, path, raw, traceparent, self.connection
                    )
                    if answered is not None:
                        code, payload = answered
                        self._respond(code, payload)
                    return
                return self._respond(
                    404, json.dumps({"error": f"no route {path}"}).encode()
                )

        return Handler

    def start(self) -> int:
        self.replicas.start()
        self._httpd = ThreadingHTTPServer(
            (self.host, self.port), self._make_handler()
        )
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fleet-router",
            daemon=True,
        )
        self._thread.start()
        log.info("fleet router serving on %s:%d", self.host, self.port)
        return self.port

    def stop(self) -> None:
        self.replicas.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
