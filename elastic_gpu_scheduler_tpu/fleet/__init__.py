"""Elastic serving fleet: front-door router, autoscaler, live gang resize.

The serving plane (serve.py / server/inference.py) is one engine per
pod; this package turns N of those pods into ONE elastic service —
ROADMAP item 3, built on three earlier subsystems:

- **Router** (:mod:`fleet.router`): an HTTP front door that spreads
  ``/v1/*`` streams across replicas with prefix-cache-aware affinity —
  the rolling BLAKE2b digest chain PR 4 gave the engine's prefix cache
  (shared definition: utils/prefixdigest) routes a session to the
  replica already holding its longest prefix; least-loaded fallback
  from ``/v1/stats`` signals, health-checked replica set with a
  draining state, per-replica circuit breakers, relay-aware health
  (utils/tpuprobe), SSE byte-pump pass-through that preserves the
  engine's burst coalescing, and a ``fleet.route`` span joining the
  W3C traceparent chain.

- **Autoscaler** (:mod:`fleet.autoscaler`): folds per-replica engine
  signals (queue depth, slot occupancy, KV-page footprint, host gap)
  plus the profile observatory's per-class throughput into scale
  decisions with hysteresis, cooldowns and min/max bounds; executes
  them as admissions/releases through the scheduler's HTTP verbs
  (placement prefers the TPU generation with the highest measured
  throughput-per-chip for the fleet's class), and journals EVERY
  evaluation as a ``fleet`` record so ``score_policy`` can replay a
  candidate policy against recorded traffic before promotion.

- **Resize** (:mod:`fleet.resize`): grow/shrink a running SPMD serving
  gang without a cold restart — journaled all-or-nothing membership
  transactions bracketed by the defrag drain/elastic-resume hooks
  (≤1 lost in-flight chunk per paused member), with a ``resize``
  journal record whose replay invariant checks chip conservation and
  exact membership.

CLI: ``--fleet=off|router|auto`` on the scheduler entry point (cli.py);
CI gate: ``make check-fleet``; runbook: OPERATIONS.md "Elastic serving
fleet".
"""

from typing import Optional

from .autoscaler import (  # noqa: F401
    Autoscaler,
    PolicyEngine,
    ScalingPolicy,
    SchedulerGangExecutor,
    fold_signals,
    generation_preference,
    score_policy,
)
from .resize import GangResizer, member_chips  # noqa: F401
from .router import FleetRouter, Replica, ReplicaSet  # noqa: F401


class FleetState:
    """The pieces one ``--fleet`` deployment wires together, as a single
    stoppable handle with one combined ``/debug/fleet`` payload (served
    by both the scheduler server and the router's own port)."""

    def __init__(
        self,
        router: Optional[FleetRouter] = None,
        autoscaler: Optional[Autoscaler] = None,
        resizer: Optional[GangResizer] = None,
        assembler=None,  # slo.assembly.TraceAssembler (SLO plane)
    ):
        self.router = router
        self.autoscaler = autoscaler
        self.resizer = resizer
        self.assembler = assembler

    def debug_state(self) -> dict:
        out: dict = {}
        if self.router is not None:
            out["router"] = self.router.debug_state()
        if self.autoscaler is not None:
            out["autoscaler"] = self.autoscaler.debug_state()
        if self.resizer is not None:
            out["resize"] = self.resizer.debug_state()
        if self.assembler is not None:
            out["trace_assembly"] = self.assembler.debug_state()
        return out

    def stop(self) -> None:
        if self.autoscaler is not None:
            self.autoscaler.stop()
        if self.router is not None:
            self.router.stop()
        if self.assembler is not None:
            self.assembler.stop()
